#!/usr/bin/env python
"""Benchmark: ResNet-50 ImageNet-shape training throughput (images/sec/chip).

Mirrors the reference's headline workload (BASELINE.md: ChainerMN ResNet-50
ImageNet; the 15-min/1024-GPU run sustained ~125 images/sec/GPU on P100).
Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
vs_baseline is images/sec/chip divided by the reference's 125 img/s/GPU.

Resilience: TPU backend init can fail transiently (round 1 died with
``UNAVAILABLE: TPU backend setup/compile error`` before any framework code
ran), and JAX caches a failed backend for the life of the process — so the
retry MUST be a fresh process. This script therefore runs as a parent that
spawns itself with ``--child`` and retries with backoff on initialization
errors. On final failure it still prints one parseable JSON line carrying the
error class instead of a bare stack trace.

Runs on whatever accelerator jax sees (the driver provides the real TPU);
synthetic data — this measures the training step, not input pipelines.
"""

import json
import os
import subprocess
import sys
import time

BASELINE_IMG_PER_SEC_PER_CHIP = 125.0  # BASELINE.md derived P100 number

# bf16 peak FLOP/s per *jax device* by device_kind substring. v2/v3 expose one
# core per device (peak is per-core); v4+ expose one chip (megacore).
_CHIP_PEAK_FLOPS = [
    ("v6", 918e12),  # Trillium
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 61.5e12),
    ("v2", 22.5e12),
]

# Error signatures that mean "backend never came up" (retryable) rather than
# "the benchmark itself is broken" (not retryable). NOTE: HBM OOM
# (RESOURCE_EXHAUSTED) is deliberately NOT here — that is handled by the
# batch-halving loop, not by retrying the same batch in a fresh process.
_RETRYABLE = (
    "UNAVAILABLE",
    "Unable to initialize backend",
    "DEADLINE_EXCEEDED",
    "failed to connect",
    "Connection reset",
    "Socket closed",
)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _chip_peak(device_kind: str):
    dk = device_kind.lower()
    for key, peak in _CHIP_PEAK_FLOPS:
        if key in dk:
            return peak
    return None


def child_main() -> None:
    import jax

    # Testing hook (the driver never sets this): force a platform. The
    # config update is required — this container's sitecustomize
    # force-registers the axon TPU platform and overrides JAX_PLATFORMS.
    plat = os.environ.get("CHAINERMN_TPU_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)

    import jax.numpy as jnp
    import optax

    import chainermn_tpu
    from chainermn_tpu.models import ResNet50
    from chainermn_tpu.training import jit_train_step

    devs = jax.devices()
    log(f"devices: {devs} (kind={devs[0].device_kind!r})")
    n_chips = len(devs)

    comm = chainermn_tpu.create_communicator("tpu", allreduce_grad_dtype="bfloat16")
    model = ResNet50(num_classes=1000)

    batch = int(os.environ.get("CHAINERMN_TPU_BENCH_BATCH", "0")) or 128 * n_chips
    while batch >= 8:
        try:
            rng = jax.random.PRNGKey(0)
            images = jax.random.normal(rng, (batch, 224, 224, 3), jnp.bfloat16)
            labels = jnp.zeros((batch,), jnp.int32)
            t0 = time.time()
            variables = model.init(rng, images[:2], train=True)
            variables = comm.bcast_data(variables)
            opt = chainermn_tpu.create_multi_node_optimizer(
                optax.sgd(0.1, momentum=0.9), comm
            )
            opt_state = jax.device_put(opt.init(variables["params"]), comm.named_sharding())
            log(f"init done in {time.time() - t0:.1f}s; batch={batch}")

            # One AOT compile serves both execution and the MFU estimate
            # (a separate lower().compile() would not share the jit cache and
            # would double the multi-minute ResNet compile).
            jitted = jit_train_step(model, opt, comm)
            t0 = time.time()
            step = jitted.lower(variables, opt_state, images, labels).compile()
            log(f"compile: {time.time() - t0:.1f}s")
            # per-DEVICE per-step FLOPs from the compiled (post-SPMD-
            # partitioning) module — already each chip's share, so the MFU
            # math below must NOT divide by n_chips again.
            step_flops = None
            try:
                ca = step.cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if ca else {}
                step_flops = float(ca.get("flops", 0.0)) or None
            except Exception as e:
                log(f"cost_analysis unavailable: {e}")
            t0 = time.time()
            variables, opt_state, loss = jax.block_until_ready(
                step(variables, opt_state, images, labels)
            )
            log(f"first step: {time.time() - t0:.1f}s; loss={float(loss):.3f}")
            for _ in range(2):  # warmup
                variables, opt_state, loss = jax.block_until_ready(
                    step(variables, opt_state, images, labels)
                )
            cs = {"total_bytes": 0}
            # per-step comm traffic read straight from the compiled HLO
            # (stderr only; opt-in via env)
            if os.environ.get("CHAINERMN_TPU_BENCH_COMMSTATS"):
                try:
                    from chainermn_tpu.extensions import parse_hlo_collectives

                    cs = parse_hlo_collectives(step.as_text())
                    detail = ", ".join(
                        f"{k} x{v['count']} ({v['bytes'] / 1e6:.1f}MB)"
                        for k, v in cs.items() if isinstance(v, dict)
                    )
                    log("collectives/step: " + (detail or "none"))
                except Exception as e:
                    log(f"collective_stats unavailable: {e}")
            n_steps = 10
            t0 = time.time()
            for _ in range(n_steps):
                variables, opt_state, loss = step(variables, opt_state, images, labels)
            jax.block_until_ready(loss)
            dt = time.time() - t0
            imgs_per_sec = batch * n_steps / dt
            if cs.get("total_bytes"):
                log(f"collective traffic: {cs['total_bytes'] / 1e6:.1f} MB/step "
                    f"-> {cs['total_bytes'] * n_steps / dt / 1e9:.2f} GB/s "
                    "effective")
            per_chip = imgs_per_sec / n_chips
            log(f"{n_steps} steps in {dt:.2f}s -> {imgs_per_sec:.1f} img/s total")
            record = {
                "metric": "resnet50_imagenet_train_throughput",
                "value": round(per_chip, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(per_chip / BASELINE_IMG_PER_SEC_PER_CHIP, 3),
            }
            step_time = dt / n_steps
            record["step_time_ms"] = round(step_time * 1e3, 2)
            record["batch_per_chip"] = batch // n_chips
            record["device_kind"] = devs[0].device_kind
            if step_flops:
                achieved = step_flops / step_time  # flops are per-device already
                record["achieved_tflops_per_chip"] = round(achieved / 1e12, 2)
                peak = _chip_peak(devs[0].device_kind)
                if peak:
                    record["mfu"] = round(achieved / peak, 4)
                    log(f"MFU: {achieved / peak:.1%} of {peak / 1e12:.0f} TFLOP/s peak")
            print(json.dumps(record))
            return
        except Exception as e:  # OOM or shape limits: halve and retry
            full_msg = f"{type(e).__name__}: {e}"
            if any(s in full_msg for s in _RETRYABLE):
                raise  # backend-level failure: let the parent retry a fresh process
            log(f"batch {batch} failed: {full_msg[:300]}")
            batch //= 2
    raise SystemExit("benchmark could not run at any batch size")


def parent_main() -> None:
    attempts = int(os.environ.get("CHAINERMN_TPU_BENCH_ATTEMPTS", "5"))
    delay = float(os.environ.get("CHAINERMN_TPU_BENCH_RETRY_DELAY", "10"))
    # Backend init can HANG (tunnel down) rather than fail fast; a hung child
    # would otherwise make the whole bench silently exceed the driver's
    # budget with no JSON emitted. Timeout covers init + compile + 13 steps.
    attempt_timeout = float(os.environ.get("CHAINERMN_TPU_BENCH_TIMEOUT", "900"))
    last_tail = ""
    for i in range(1, attempts + 1):
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child"],
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                timeout=attempt_timeout,
            )
        except subprocess.TimeoutExpired as te:
            log(f"bench attempt {i}/{attempts} timed out after {attempt_timeout:.0f}s")
            stderr_txt, stdout_txt = te.stderr, te.stdout
            if isinstance(stderr_txt, bytes):
                stderr_txt = stderr_txt.decode(errors="replace")
            if isinstance(stdout_txt, bytes):
                stdout_txt = stdout_txt.decode(errors="replace")
            if stderr_txt:
                sys.stderr.write(stderr_txt)
            # A child can emit its result and then hang in runtime teardown —
            # a measurement in hand beats re-running the whole benchmark.
            for line in reversed((stdout_txt or "").strip().splitlines()):
                try:
                    if json.loads(line).get("metric"):
                        log("child hung after completing; using its result")
                        print(line)
                        return
                except (json.JSONDecodeError, AttributeError):
                    continue
            last_tail = f"TimeoutExpired after {attempt_timeout:.0f}s (backend hang?)"
            if i < attempts:
                time.sleep(delay)
                delay = min(delay * 2, 120.0)
            continue
        if proc.stderr:  # forward child diagnostics
            sys.stderr.write(proc.stderr)
            sys.stderr.flush()
        out = (proc.stdout or "").strip()
        if proc.returncode == 0 and out:
            # forward the child's final JSON line untouched
            print(out.splitlines()[-1])
            return
        last_tail = ((proc.stderr or "") + "\n" + out)[-3000:].strip()
        retryable = proc.returncode != 0 and (
            any(s in last_tail for s in _RETRYABLE) or not last_tail
        )
        log(f"bench attempt {i}/{attempts} failed (rc={proc.returncode}); "
            f"{'retrying in %.0fs' % delay if retryable and i < attempts else 'giving up'}")
        if not retryable:
            break
        if i < attempts:
            time.sleep(delay)
            delay = min(delay * 2, 120.0)
    # Final failure: one parseable JSON record, not a stack trace.
    err_class = next(
        (s for s in _RETRYABLE + ("TimeoutExpired",) if s in last_tail), "unknown"
    )
    print(json.dumps({
        "metric": "resnet50_imagenet_train_throughput",
        "value": None,
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "error": err_class,
        "detail": last_tail[-500:],
        "attempts": attempts,
    }))
    raise SystemExit(1)


def main() -> None:
    if "--child" in sys.argv:
        # child stdout carries ONLY the JSON record; everything else is stderr
        child_main()
    else:
        parent_main()


if __name__ == "__main__":
    main()
