#!/usr/bin/env python
"""Benchmark: ResNet-50 ImageNet-shape training throughput (images/sec/chip).

Mirrors the reference's headline workload (BASELINE.md: ChainerMN ResNet-50
ImageNet; the 15-min/1024-GPU run sustained ~125 images/sec/GPU on P100).
Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
vs_baseline is images/sec/chip divided by the reference's 125 img/s/GPU.

Runs on whatever accelerator jax sees (the driver provides the real TPU);
synthetic data — this measures the training step, not input pipelines.
"""

import json
import os
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    import jax
    import jax.numpy as jnp
    import optax

    import chainermn_tpu
    from chainermn_tpu.models import ResNet50
    from chainermn_tpu.training import jit_train_step

    devs = jax.devices()
    log(f"devices: {devs}")
    n_chips = len(devs)

    comm = chainermn_tpu.create_communicator("tpu", allreduce_grad_dtype="bfloat16")
    model = ResNet50(num_classes=1000)

    batch = 128 * n_chips
    while batch >= 8:
        try:
            rng = jax.random.PRNGKey(0)
            images = jax.random.normal(rng, (batch, 224, 224, 3), jnp.bfloat16)
            labels = jnp.zeros((batch,), jnp.int32)
            t0 = time.time()
            variables = model.init(rng, images[:2], train=True)
            variables = comm.bcast_data(variables)
            opt = chainermn_tpu.create_multi_node_optimizer(
                optax.sgd(0.1, momentum=0.9), comm
            )
            opt_state = jax.device_put(opt.init(variables["params"]), comm.named_sharding())
            log(f"init done in {time.time() - t0:.1f}s; batch={batch}")

            step = jit_train_step(model, opt, comm)
            t0 = time.time()
            variables, opt_state, loss = jax.block_until_ready(
                step(variables, opt_state, images, labels)
            )
            log(f"compile+first step: {time.time() - t0:.1f}s; loss={float(loss):.3f}")
            for _ in range(2):  # warmup
                variables, opt_state, loss = jax.block_until_ready(
                    step(variables, opt_state, images, labels)
                )
            cs = {"total_bytes": 0}
            # per-step comm traffic from the compiled HLO (stderr only);
            # costs one extra XLA compile, so opt-in via env
            if os.environ.get("CHAINERMN_TPU_BENCH_COMMSTATS"):
                try:
                    from chainermn_tpu.extensions import collective_stats

                    cs = collective_stats(step, variables, opt_state, images, labels)
                    detail = ", ".join(
                        f"{k} x{v['count']} ({v['bytes'] / 1e6:.1f}MB)"
                        for k, v in cs.items() if isinstance(v, dict)
                    )
                    log("collectives/step: " + (detail or "none"))
                except Exception as e:
                    log(f"collective_stats unavailable: {e}")
            n_steps = 10
            t0 = time.time()
            for _ in range(n_steps):
                variables, opt_state, loss = step(variables, opt_state, images, labels)
            jax.block_until_ready(loss)
            dt = time.time() - t0
            imgs_per_sec = batch * n_steps / dt
            if cs.get("total_bytes"):
                log(f"collective traffic: {cs['total_bytes'] / 1e6:.1f} MB/step "
                    f"-> {cs['total_bytes'] * n_steps / dt / 1e9:.2f} GB/s "
                    "effective")
            per_chip = imgs_per_sec / n_chips
            log(f"{n_steps} steps in {dt:.2f}s -> {imgs_per_sec:.1f} img/s total")
            print(json.dumps({
                "metric": "resnet50_imagenet_train_throughput",
                "value": round(per_chip, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(per_chip / 125.0, 3),
            }))
            return
        except Exception as e:  # OOM or shape limits: halve and retry
            log(f"batch {batch} failed: {type(e).__name__}: {str(e)[:200]}")
            batch //= 2
    raise SystemExit("benchmark could not run at any batch size")


if __name__ == "__main__":
    main()
