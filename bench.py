#!/usr/bin/env python
"""Benchmark: ResNet-50 ImageNet-shape training throughput (images/sec/chip)
plus the communicator-strategy x wire-dtype x double-buffering sweep.

Mirrors the reference's headline workload (BASELINE.md: ChainerMN ResNet-50
ImageNet; the 15-min/1024-GPU run sustained ~125 images/sec/GPU on P100).
Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...,
"sweep": [...], "allreduce_gbps": N} where vs_baseline is images/sec/chip
divided by the reference's 125 img/s/GPU, and "sweep" carries one record per
{tpu-f32, tpu-bf16, flat, hierarchical, two_dimensional} x {double buffering
on/off} configuration with its step time and HLO-derived per-step collective
traffic (SURVEY.md S6/S7 hard-part 4: does double buffering still win when
XLA already overlaps?).

NOTE on single-chip runs: with one device the mesh collectives are identity
and per-step collective bytes are ~0 — the sweep then measures strategy
*overhead* (it should be ~zero) and the record says "n_chips": 1 so the
numbers aren't over-read. On a real multi-chip slice the same harness
produces true allreduce bandwidth.

Resilience: TPU backend init can fail transiently (round 1 died with
``UNAVAILABLE: TPU backend setup/compile error`` before any framework code
ran), and JAX caches a failed backend for the life of the process — so the
retry MUST be a fresh process. This script therefore runs as a parent that
spawns itself with ``--child`` and retries with backoff on initialization
errors. On final failure it still prints one parseable JSON line carrying the
error class instead of a bare stack trace.

Runs on whatever accelerator jax sees (the driver provides the real TPU);
synthetic data — this measures the training step, not input pipelines.
"""

import json
import os
import signal
import subprocess
import sys
import time

BASELINE_IMG_PER_SEC_PER_CHIP = 125.0  # BASELINE.md derived P100 number

# bf16 peak FLOP/s per *jax device* by device_kind substring. v2/v3 expose one
# core per device (peak is per-core); v4+ expose one chip (megacore).
_CHIP_PEAK_FLOPS = [
    ("v6", 918e12),  # Trillium
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 61.5e12),
    ("v2", 22.5e12),
]

# Error signatures that mean "backend never came up" (retryable) rather than
# "the benchmark itself is broken" (not retryable). NOTE: HBM OOM
# (RESOURCE_EXHAUSTED) is deliberately NOT here — that is handled by the
# batch-halving loop, not by retrying the same batch in a fresh process.
_RETRYABLE = (
    "UNAVAILABLE",
    "Unable to initialize backend",
    "DEADLINE_EXCEEDED",
    "failed to connect",
    "Connection reset",
    "Socket closed",
)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# Scratch file where the child persists every record the moment it exists.
# Survives abandoned pipes, SIGKILLed children, and the driver's process-tree
# kill: whatever measurement was ever completed can be salvaged by the parent
# (or by a later attempt) instead of being re-earned or lost.
def _scratch_path() -> str:
    # Default is scoped by pid — the parent exports its choice to children so
    # one run shares a file, but concurrent runs (the CI smoke test runs
    # beside a real-chip bench) never cross-contaminate or unlink each
    # other's salvage.
    return os.environ.get(
        "CHAINERMN_TPU_BENCH_SCRATCH",
        f"/tmp/chainermn_tpu_bench_scratch_{os.getpid()}.jsonl",
    )


def _scratch_write(record: dict) -> None:
    try:
        with open(_scratch_path(), "a") as f:
            f.write(json.dumps(record) + "\n")
    except OSError as e:
        log(f"scratch write failed: {e}")


def _scratch_salvage() -> dict | None:
    """Last parseable *measurement* record from the scratch file, if any."""
    try:
        with open(_scratch_path()) as f:
            lines = f.read().strip().splitlines()
    except OSError:
        return None
    for line in reversed(lines):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and rec.get("metric") and rec.get("value"):
            return rec
    return None


def _chip_peak(device_kind: str):
    dk = device_kind.lower()
    for key, peak in _CHIP_PEAK_FLOPS:
        if key in dk:
            return peak
    return None


def _write_warm_marker(stem, rung, explicit_batch, n_chips, tiny, platform,
                       compile_s, t0) -> None:
    """Drop the headline_<stem>_<key>.ok marker _budget_plan keys warm
    detection on — only for a REAL (TPU, non-tiny) run whose executable
    demonstrably reached the persistent cache: a fresh ``-cache`` entry
    appeared since ``t0`` (cold compile persisted) or the compile was a
    warm hit (<10s: deserialization is local and fast; a cold compile
    through the remote tunnel is minutes). A >=10s compile with no new
    entry means serialization was skipped (enable_compilation_cache
    tolerates that) and the next run is still cold — writing the marker
    would recreate the round-4 double-TERM. The key matches what
    _budget_plan computes on the parent side: the raw env value for an
    explicitly-set batch, the per-chip rung otherwise (the parent cannot
    know n_chips, so its default key is the per-chip 256)."""
    if tiny or platform != "tpu":
        return
    try:
        cache_dir = os.environ.get(
            "CHAINERMN_TPU_BENCH_CACHE", "/tmp/chainermn_tpu_jax_cache")
        if not cache_dir or not os.path.isdir(cache_dir):
            return
        persisted = any(
            e.name.endswith("-cache") and e.stat().st_mtime >= t0 - 5
            for e in os.scandir(cache_dir))
        if not (persisted or compile_s < 10):
            return
        key = explicit_batch if explicit_batch else rung // max(n_chips, 1)
        with open(os.path.join(
                cache_dir, f"headline_{stem}_{key}.ok"), "w") as mf:
            mf.write(f"{compile_s:.1f}\n")
    except OSError:
        pass


def _measure(model, comm, batch, *, double_buffering, n_steps, warmup=3,
             commstats=True, image_size=224):
    """Compile + time one configuration; returns a result dict.

    Shared by the headline measurement and the sweep so every number comes
    from the same code path."""
    import jax
    import jax.numpy as jnp
    import optax

    import chainermn_tpu
    from chainermn_tpu.training import jit_train_step

    from chainermn_tpu.monitor import instrument

    rng = jax.random.PRNGKey(0)
    images = jax.random.normal(
        rng, (batch, image_size, image_size, 3), jnp.bfloat16
    )
    labels = jnp.zeros((batch,), jnp.int32)
    t_init = time.time()
    variables = comm.bcast_data(model.init(rng, images[:2], train=True))
    log(f"model.init done in {time.time() - t_init:.1f}s (batch={batch})")
    opt = chainermn_tpu.create_multi_node_optimizer(
        optax.sgd(0.1, momentum=0.9), comm, double_buffering=double_buffering
    )
    opt_state = jax.device_put(opt.init(variables["params"]), comm.named_sharding())
    jitted = jit_train_step(model, opt, comm)
    # One AOT compile serves execution, the MFU estimate, and commstats (a
    # separate lower().compile() would not share the jit cache and would
    # double the multi-minute ResNet compile).
    t0 = time.time()
    step = jitted.lower(variables, opt_state, images, labels).compile()
    compile_s = time.time() - t0
    # (The cold/warm cache marker for _budget_plan is written by
    # child_main after a successful rung — it, not this shared helper,
    # knows whether the run is tiny, on TPU, and env-keyed or
    # ladder-keyed.)
    step_flops = None
    try:
        ca = step.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        # per-DEVICE per-step FLOPs from the compiled (post-SPMD-partitioning)
        # module — already each chip's share; don't divide by n_chips again.
        step_flops = float(ca.get("flops", 0.0)) or None
    except Exception as e:
        log(f"cost_analysis unavailable: {e}")
    cs = {"total_bytes": 0}
    if commstats:
        try:
            from chainermn_tpu.extensions import parse_hlo_collectives

            cs = parse_hlo_collectives(step.as_text())
        except Exception as e:
            log(f"collective_stats unavailable: {e}")
    # The AOT-compiled executable bypasses jit_train_step's own monitored
    # wrapper, so instrument it here: the measured loop feeds the step
    # counter/histogram every record embeds as its "monitor" block. (Wrapper
    # cost is host-side dict/deque ops — noise against a real step.)
    step = instrument(step, "bench_train_step")
    # Timing closes with a device->host FETCH of the loss, not
    # block_until_ready: through the axon tunnel block_until_ready can
    # return on the relay's ack before the device finishes (observed: 50
    # ResNet-50 steps "completing" in 87ms = 925 TFLOP/s on a 197-peak
    # chip), while a value fetch cannot resolve early. The fetch adds one
    # RTT, amortized over n_steps.
    for _ in range(warmup):
        variables, opt_state, loss = step(variables, opt_state, images, labels)
        float(loss)
    t0 = time.time()
    for _ in range(n_steps):
        variables, opt_state, loss = step(variables, opt_state, images, labels)
    loss_val = float(loss)
    dt = time.time() - t0
    step_time = dt / n_steps
    return {
        "loss": loss_val,
        "compile_s": round(compile_s, 1),
        "step_time_ms": round(step_time * 1e3, 2),
        "img_per_sec": batch * n_steps / dt,
        "step_flops_per_device": step_flops,
        "collective_bytes_per_step": int(cs.get("total_bytes", 0)),
        # effective collective bandwidth: HLO bytes/step over measured step
        # time (0 on a single chip — collectives are identity there)
        "allreduce_gbps": round(
            cs.get("total_bytes", 0) / step_time / 1e9, 3
        ),
    }


# The sweep grid: reference strategy names x double buffering. tpu-bf16 is
# the flagship (reference pure_nccl + fp16 allreduce analog).
_SWEEP_GRID = [
    ("tpu_f32", "tpu", {}),
    ("tpu_bf16", "tpu", {"allreduce_grad_dtype": "bfloat16"}),
    ("flat", "flat", {}),
    ("hierarchical", "hierarchical", {}),
    ("two_dimensional", "two_dimensional", {}),
]


def enable_compilation_cache(jax_mod) -> None:
    """Persistent compilation cache, shared by every battery script: a cold
    conv7 ResNet-50 compile through the axon tunnel can eat most of an
    attempt budget; with the cache, every later process (retry attempts,
    sweep cells, onchip_* scripts, the driver's round-end run) reuses the
    serialized executable and spends its budget measuring instead of
    compiling. Only compiles >10s persist; errors are non-fatal (an axon
    backend that can't serialize just skips it). Opt out with
    CHAINERMN_TPU_BENCH_CACHE=''."""
    cache_dir = os.environ.get(
        "CHAINERMN_TPU_BENCH_CACHE", "/tmp/chainermn_tpu_jax_cache"
    )
    if not cache_dir:
        return
    try:
        jax_mod.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception as e:  # config names can shift across jax versions
        log(f"compilation cache unavailable: {e}")
        return
    try:
        jax_mod.config.update("jax_persistent_cache_min_compile_time_secs",
                              10.0)
    except Exception as e:
        log(f"cache min-compile-time threshold not set: {e}")


def _devices_or_fail_fast(jax_mod, *, mode: str = "train",
                          metric: str = "resnet50_imagenet_train_throughput",
                          unit: str = "images/sec/chip"):
    """Backend init with a watchdog: TPU backend bring-up has HUNG (not
    failed) in 3 of the last 5 rounds — ``jax.devices()`` through a
    wedged tunnel blocks forever, so without a timeout the whole attempt
    (and then the parent's retry ladder) burns on a backend that will
    never come up. Probe ``jax.devices()`` on a daemon thread bounded by
    ``CHAINERMN_TPU_BENCH_INIT_TIMEOUT`` (default 180 s — healthy init is
    seconds). On timeout, fail FAST to the committed-evidence path: emit
    one parseable record with ``backend_init_timeout`` set (plus the
    newest persisted TPU measurement that ``_failure_record`` embeds —
    the round still carries real evidence) and ``os._exit`` — the probe
    thread is wedged inside a C call, so a normal interpreter teardown
    could hang exactly like the init did. A backend that raised (rather
    than hung) re-raises unchanged: those errors stay retryable."""
    timeout = float(os.environ.get("CHAINERMN_TPU_BENCH_INIT_TIMEOUT",
                                   "180"))
    box: dict = {}

    def probe():
        try:
            box["devs"] = jax_mod.devices()
        except BaseException as exc:  # noqa: BLE001 — relayed below
            box["err"] = exc

    import threading

    t = threading.Thread(target=probe, daemon=True,
                         name="backend-init-probe")
    t.start()
    t.join(timeout)
    if "devs" in box:
        return box["devs"]
    if "err" in box:
        raise box["err"]
    log(f"backend init watchdog: jax.devices() still hung after "
        f"{timeout:.0f}s; failing fast with the committed evidence")
    rec = _failure_record(
        "backend_init_timeout",
        f"jax.devices() did not return within {timeout:.0f}s "
        "(tunnel wedged?)", 0)
    rec.update({"metric": metric, "unit": unit, "mode": mode,
                "backend_init_timeout": True})
    print(json.dumps(rec), flush=True)
    _scratch_write(rec)
    os._exit(1)


def child_main() -> None:
    # Python's default SIGTERM disposition is immediate kernel termination —
    # no stack unwind, no PJRT client teardown, so the parent's TERM-first
    # escalation would release nothing. Raise SystemExit instead so the
    # interpreter unwinds and the device grant is returned. (Best effort: if
    # the main thread is blocked inside a C extension call — e.g. a remote
    # compile — the handler only runs when that call returns.)
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))

    import jax

    # Testing hook (the driver never sets this): force a platform. The
    # config update is required — this container's sitecustomize
    # force-registers the axon TPU platform and overrides JAX_PLATFORMS.
    plat = os.environ.get("CHAINERMN_TPU_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)

    # Persistent compilation cache: a cold conv7 ResNet-50 compile through
    # the axon tunnel can eat most of an attempt budget; with the cache,
    # every later bench process (retry attempts, sweep cells at the same
    # batch, and the driver's own round-end run) reuses the serialized
    # executable and spends its budget measuring instead of compiling.
    # Write errors are non-fatal by default (jax_raise_persistent_cache_
    # errors=False), so an axon backend that can't serialize just skips it.
    enable_compilation_cache(jax)

    import chainermn_tpu
    from chainermn_tpu.models import ResNet50

    devs = _devices_or_fail_fast(jax)
    log(f"devices: {devs} (kind={devs[0].device_kind!r})")
    n_chips = len(devs)

    stem = os.environ.get("CHAINERMN_TPU_BENCH_STEM", "conv7")
    # Smoke-test hook (CI only; the driver never sets it): a tiny model +
    # small images exercise the whole harness — retry parent, sweep,
    # commstats — in seconds on CPU.
    tiny = bool(os.environ.get("CHAINERMN_TPU_BENCH_TINY"))
    image_size = 32 if tiny else 224
    if tiny:
        from chainermn_tpu.models import ResNet

        model = ResNet(stage_sizes=[1, 1], width=8, num_classes=10, stem=stem)
    else:
        model = ResNet50(num_classes=1000, stem=stem)
    n_steps = int(os.environ.get("CHAINERMN_TPU_BENCH_STEPS", "50"))
    sweep_steps = int(os.environ.get("CHAINERMN_TPU_BENCH_SWEEP_STEPS", "20"))
    comm = chainermn_tpu.create_communicator("tpu", allreduce_grad_dtype="bfloat16")

    deadline = time.time() + float(
        os.environ.get("CHAINERMN_TPU_BENCH_CHILD_BUDGET", "1200")
    )
    # 256/chip, not 128: the AOT roofline (PERF.md round 4) shows this
    # workload is HBM-bound and arithmetic intensity — batch — is the MFU
    # lever (ceiling 27% at 128, 31% at 256, 35% at 512). The halving loop
    # below degrades gracefully on OOM — EXCEPT when the batch was set
    # explicitly (CHAINERMN_TPU_BENCH_BATCH): a sweep cell labeled
    # batch=512 must fail on OOM rather than silently measure 256 under
    # the wrong label (the next cell measures 256 on purpose).
    explicit_batch = int(os.environ.get("CHAINERMN_TPU_BENCH_BATCH", "0"))

    def _headline_record(h, b):
        per_chip = h["img_per_sec"] / n_chips
        rec = {
            "metric": "resnet50_imagenet_train_throughput",
            "value": round(per_chip, 2),
            "unit": "images/sec/chip",
            "vs_baseline": round(per_chip / BASELINE_IMG_PER_SEC_PER_CHIP, 3),
            "step_time_ms": h["step_time_ms"],
            "batch_per_chip": b // n_chips,
            "n_chips": n_chips,
            "stem": stem,
            "device_kind": devs[0].device_kind,
            "collective_bytes_per_step": h["collective_bytes_per_step"],
            "allreduce_gbps": h["allreduce_gbps"],
        }
        if tiny:
            rec["tiny"] = True  # CI smoke run, not a real measurement
        # acceptance: every mode's record carries the registry snapshot
        # (step counters, step-time percentiles, device-memory gauges)
        try:
            from chainermn_tpu.monitor import snapshot as monitor_snapshot

            rec["monitor"] = monitor_snapshot()
        except Exception as e:
            log(f"monitor snapshot unavailable: {e}")
        if h["step_flops_per_device"]:
            achieved = h["step_flops_per_device"] / (h["step_time_ms"] / 1e3)
            rec["achieved_tflops_per_chip"] = round(achieved / 1e12, 2)
            peak = _chip_peak(devs[0].device_kind)
            if peak:
                rec["mfu"] = round(achieved / peak, 4)
                log(f"MFU: {achieved / peak:.1%} of "
                    f"{peak / 1e12:.0f} TFLOP/s peak")
        return rec

    # Batch LADDER, small to large. The AOT roofline says batch is the MFU
    # lever (27% ceiling at 128, 31% at 256, 35% at 512) — but chip windows
    # are scarce and a cold batch-256 compile through the tunnel has
    # exceeded an 11-minute attempt budget where batch-128 compiled in 27s
    # (r2 vs r5 evidence). So: land a guaranteed-fast record first, then
    # climb; every completed rung is printed + persisted to scratch BEFORE
    # the next compile starts, so a window that closes mid-climb keeps the
    # best rung so far instead of nothing. With a warm compilation cache
    # the lower rungs cost seconds. An explicit batch (sweep cells) is a
    # single rung and must fail rather than substitute a different batch.
    if explicit_batch:
        ladder = [explicit_batch]
    elif tiny:
        ladder = [256 * n_chips]
    else:
        ladder = [128 * n_chips, 256 * n_chips, 512 * n_chips]

    headline, batch, record = None, None, None
    prev_wall = prev_compile = None
    # Pessimistic cost of a COLD rung: its compile cannot be preempted (the
    # remote-compile C call defers SIGTERM, and a follow-up SIGKILL orphans
    # the single-tenant lease — PERF.md hazard #2), so never START one that
    # might not fit. A warm previous rung (compile hit the persistent
    # cache) predicts warm neighbors: the same earlier process that cached
    # this rung's graph ran the same ladder.
    climb_floor = float(os.environ.get("CHAINERMN_TPU_BENCH_CLIMB_FLOOR",
                                       "1500"))
    ladder = list(ladder)
    while ladder:
        rung = ladder.pop(0)
        if headline is not None:
            remaining = deadline - time.time()
            warm = prev_compile is not None and prev_compile < 60
            need = max(3 * prev_wall, 120.0) if warm else climb_floor
            if remaining < need:
                log(f"ladder: skipping batch {rung} ({remaining:.0f}s left "
                    f"< {need:.0f}s needed; prev rung {prev_wall:.0f}s, "
                    f"compile {'warm' if warm else 'cold'})")
                break
        rung_start = time.time()
        try:
            h = _measure(
                model, comm, rung, double_buffering=False, n_steps=n_steps,
                image_size=image_size,
            )
            prev_wall = time.time() - rung_start
            prev_compile = h["compile_s"]
            log(f"headline rung: batch={rung} "
                f"step={h['step_time_ms']}ms "
                f"{h['img_per_sec']:.0f} img/s "
                f"(compile {h['compile_s']}s, total {prev_wall:.0f}s)")
            _write_warm_marker(
                stem, rung, explicit_batch, n_chips, tiny,
                devs[0].platform, h["compile_s"], rung_start)
        except Exception as e:  # OOM / shape limits on this rung
            full_msg = f"{type(e).__name__}: {e}"
            if any(s in full_msg for s in _RETRYABLE):
                raise  # backend-level failure: let the parent retry fresh
            log(f"batch {rung} failed: {full_msg[:300]}")
            if explicit_batch:
                raise SystemExit(
                    f"explicit batch {explicit_batch} failed; not "
                    "substituting another (the measurement label must "
                    "match the measured batch)")
            if headline is None:
                # no record yet: the smallest planned rung doesn't fit —
                # descend by halving (replaces the climb; a bigger rung
                # cannot fit where a smaller one OOM'd)
                if rung >= 16:
                    ladder = [rung // 2]
                continue
            break  # OOM above a working rung: larger rungs won't fit either
        if headline is None or h["img_per_sec"] > headline["img_per_sec"]:
            headline, batch = h, rung
        # A measurement in hand must survive a later rung's compile or a
        # sweep overrun: emit the best record NOW (the parent salvages the
        # last parseable line on child timeout) and persist it to the
        # scratch file — stdout pipes die with the process tree; the file
        # does not.
        record = _headline_record(headline, batch)
        print(json.dumps(record), flush=True)
        _scratch_write(record)
    if headline is None:
        raise SystemExit("benchmark could not run at any batch size")
    per_chip = headline["img_per_sec"] / n_chips

    # ---- strategy x double-buffering sweep (BASELINE.md metric 2) -------- #
    sweep = []
    if os.environ.get("CHAINERMN_TPU_BENCH_SWEEP", "1") != "0":
        for name, strategy, kwargs in _SWEEP_GRID:
            for db in (False, True):
                label = f"{name}{'+db' if db else ''}"
                if name == "tpu_bf16" and not db:
                    # exactly the headline configuration — reuse its numbers
                    # instead of burning a second multi-minute compile
                    sweep.append({
                        "config": label,
                        "step_time_ms": headline["step_time_ms"],
                        "img_per_sec_per_chip": round(per_chip, 1),
                        "collective_bytes_per_step":
                            headline["collective_bytes_per_step"],
                        "allreduce_gbps": headline["allreduce_gbps"],
                        "from_headline": True,
                    })
                    continue
                if time.time() > deadline:
                    sweep.append({"config": label, "skipped": "time budget"})
                    continue
                try:
                    c = chainermn_tpu.create_communicator(strategy, **kwargs)
                    r = _measure(model, c, batch, double_buffering=db,
                                 n_steps=sweep_steps, image_size=image_size)
                    sweep.append({
                        "config": label,
                        "step_time_ms": r["step_time_ms"],
                        "img_per_sec_per_chip": round(
                            r["img_per_sec"] / n_chips, 1
                        ),
                        "collective_bytes_per_step":
                            r["collective_bytes_per_step"],
                        "allreduce_gbps": r["allreduce_gbps"],
                    })
                    log(f"sweep {label}: {r['step_time_ms']}ms/step, "
                        f"{r['collective_bytes_per_step'] / 1e6:.1f} MB/step, "
                        f"{r['allreduce_gbps']} GB/s")
                except Exception as e:
                    sweep.append({
                        "config": label,
                        "error": f"{type(e).__name__}: {e}"[:200],
                    })
                    log(f"sweep {label} failed: {type(e).__name__}: {e}")
        record["sweep"] = sweep
        db_pairs = {
            s["config"]: s["step_time_ms"] for s in sweep
            if "step_time_ms" in s
        }
        base, db = db_pairs.get("tpu_bf16"), db_pairs.get("tpu_bf16+db")
        if base and db:
            # the SURVEY S7 hard-part-4 answer, as data
            record["double_buffering_speedup"] = round(base / db, 4)

    print(json.dumps(record))
    _scratch_write(record)


def serving_main() -> None:
    """``bench.py --mode serving``: continuous-batching decode benchmark
    over :mod:`chainermn_tpu.serving` — the serving-side counterpart of the
    ResNet training headline. Prints ONE JSON line:
    ``{"metric": "serving_decode_throughput", "value": tokens/sec, ...,
    "ttft_p50_ms", "ttft_p99_ms", "tpot_p50_ms", "slot_occupancy", ...}``.

    Workload: a burst of ragged random prompts (the arrival pattern that
    exercises admission + slot reuse) through a fixed slot pool; one
    warmup request compiles the two engine programs, then the measured
    run counts only steady-state work. The zero-recompile invariant is
    carried in the record (``"recompiles"``) so a regression shows up in
    the perf artifact, not just in tests. Runs on whatever accelerator
    jax sees — on the CPU mesh it establishes the harness baseline
    (records say so via ``device_kind``), on a real chip the serving perf
    number. No retry parent: decode workloads don't hit the multi-minute
    remote-compile hazard the training bench's ladder machinery exists
    for; a failure prints a parseable error record instead.
    """
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    import numpy as np

    import jax

    plat = os.environ.get("CHAINERMN_TPU_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    enable_compilation_cache(jax)

    import jax.numpy as jnp

    from chainermn_tpu.models import TransformerLM, generate
    from chainermn_tpu.serving import FCFSScheduler, ServingEngine

    e = os.environ.get
    n_slots = int(e("CHAINERMN_TPU_SERVE_SLOTS", "8"))
    n_requests = int(e("CHAINERMN_TPU_SERVE_REQUESTS", "32"))
    prefill_len = int(e("CHAINERMN_TPU_SERVE_PREFILL_LEN", "32"))
    max_new = int(e("CHAINERMN_TPU_SERVE_MAX_NEW", "32"))
    vocab = int(e("CHAINERMN_TPU_SERVE_VOCAB", "256"))
    d_model = int(e("CHAINERMN_TPU_SERVE_DMODEL", "128"))
    n_layers = int(e("CHAINERMN_TPU_SERVE_LAYERS", "4"))
    n_heads = int(e("CHAINERMN_TPU_SERVE_HEADS", "8"))
    skip_sections = {s for s in e(
        "CHAINERMN_TPU_SERVE_SKIP_SECTIONS", "").split(",") if s}
    # the kernel + speculative sections reuse the paged
    # section's workload/engine parameters
    if "paged_serving" in skip_sections:
        skip_sections |= {"paged_kernel_serving",
                          "speculative_serving"}

    devs = _devices_or_fail_fast(jax, mode="serving",
                                 metric="serving_decode_throughput",
                                 unit="tokens/sec")
    log(f"serving bench: devices={len(devs)} kind={devs[0].device_kind!r} "
        f"slots={n_slots} requests={n_requests}")
    try:
        model = TransformerLM(
            vocab_size=vocab, d_model=d_model, n_heads=n_heads,
            n_layers=n_layers, max_len=prefill_len + max_new,
        )
        rng = np.random.RandomState(0)
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, prefill_len), jnp.int32))
        engine = ServingEngine(model, params, n_slots=n_slots,
                               prefill_len=prefill_len)

        # warmup: compile prefill + decode once, off the measured clock
        warm = FCFSScheduler(engine)
        warm.submit(rng.randint(1, vocab, 4).astype(np.int32), 2)
        warm.run_until_idle()

        sched = FCFSScheduler(engine)  # fresh metrics for the measured run
        t0 = time.time()
        for _ in range(n_requests):
            prompt = rng.randint(1, vocab,
                                 rng.randint(1, prefill_len + 1))
            sched.submit(prompt.astype(np.int32),
                         int(rng.randint(1, max_new + 1)))
        sched.run_until_idle()
        wall = time.time() - t0
        m = sched.metrics.report()
        record = {
            "metric": "serving_decode_throughput",
            "value": m["tokens_per_sec"],
            "unit": "tokens/sec",
            "mode": "serving",
            "n_chips": len(devs),
            "device_kind": devs[0].device_kind,
            "n_slots": n_slots,
            "n_requests": n_requests,
            "prefill_len": prefill_len,
            "max_new": max_new,
            "model": {"vocab": vocab, "d_model": d_model,
                      "n_layers": n_layers, "n_heads": n_heads},
            "tokens_generated": m["tokens_generated"],
            "wall_s": round(wall, 3),
            "ttft_p50_ms": round(m["ttft_p50_s"] * 1e3, 3),
            "ttft_p99_ms": round(m["ttft_p99_s"] * 1e3, 3),
            "ttft_mean_ms": round(m["ttft_mean_s"] * 1e3, 3),
            "tpot_p50_ms": round(m["tpot_p50_s"] * 1e3, 3),
            "tpot_p99_ms": round(m["tpot_p99_s"] * 1e3, 3),
            "slot_occupancy": m["slot_occupancy_mean"],
            "slot_occupancy_p99": m["slot_occupancy_p99"],
            "queue_depth_mean": m["queue_depth_mean"],
            "queue_depth_p99": m["queue_depth_p99"],
            "recompiles": engine.compile_counts(),
        }

        # ---- continuous telemetry: collector ON vs OFF, warm engine --- #
        # ISSUE 15 acceptance: the background collector + detector graph
        # must cost <2% of serving throughput. The SAME job list runs
        # twice through fresh schedulers on the already-warm engine — OFF
        # first, then ON with a Collector sampling every registry
        # instrument at ts_cadence plus the standard per-instance sensor
        # set and a HealthMonitor — and the record carries the overhead
        # fraction, ON-vs-OFF token parity, the zero-recompile invariant,
        # and the health verdict the run ended on.
        from chainermn_tpu.monitor.health import (
            HealthMonitor,
            standard_replica_sensors,
        )
        from chainermn_tpu.monitor.timeseries import Collector

        ts_cadence = float(e("CHAINERMN_TPU_SERVE_TS_CADENCE", "0.05"))
        ts_jobs = [
            (rng.randint(1, vocab,
                         rng.randint(1, prefill_len + 1)).astype(np.int32),
             int(rng.randint(1, max_new + 1)))
            for _ in range(n_requests)
        ]
        ts_counts = engine.compile_counts_detailed()

        def run_ts_workload(ts_on):
            s = FCFSScheduler(engine)
            col = mon = None
            if ts_on:
                col = Collector(cadence_s=ts_cadence)
                sigs, dets = standard_replica_sensors(
                    s.metrics.instance, stall_timeout_s=60.0, tag="bench")
                for sg in sigs:
                    col.add_signal(sg)
                for dt in dets:
                    col.add_detector(dt)
                mon = HealthMonitor(store=col.store)
                mon.watch(s.metrics.instance, detectors=dets)
                col.attach_health(mon)
                s.metrics.attach_health(
                    lambda m=mon, k=s.metrics.instance: m.score_json(k))
                col.start()
            t0 = time.time()
            reqs = [s.submit(p, n) for p, n in ts_jobs]
            s.run_until_idle()
            wall = time.time() - t0
            if col is not None:
                col.stop()
            return s, reqs, wall, col, mon

        s_off, reqs_off, wall_ts_off, _, _ = run_ts_workload(False)
        s_ts, reqs_ts, wall_ts_on, ts_col, ts_mon = run_ts_workload(True)
        ts_parity = all(
            bool(np.array_equal(a.output, b.output))
            for a, b in zip(reqs_ts, reqs_off))
        assert engine.compile_counts_detailed() == ts_counts, "recompiled!"
        m_ts = s_ts.metrics.report()
        record["telemetry_serving"] = {
            "cadence_s": ts_cadence,
            "wall_s_on": round(wall_ts_on, 3),
            "wall_s_off": round(wall_ts_off, 3),
            "overhead_frac": round(
                wall_ts_on / max(wall_ts_off, 1e-9) - 1.0, 4),
            "tokens_per_sec_on": s_ts.metrics.report()["tokens_per_sec"],
            "tokens_per_sec_off": s_off.metrics.report()["tokens_per_sec"],
            "parity_on_vs_off": ts_parity,
            "recompiles_after_warmup": 0,
            "n_series": len(ts_col.store.names()),
            "ticks": ts_col.ticks,
            "health": m_ts.get("health"),
            "worst_state": ts_mon.report()["worst"],
        }
        ts_rec = record["telemetry_serving"]
        log(f"telemetry serving: overhead={ts_rec['overhead_frac']} "
            f"({ts_rec['ticks']} ticks over {ts_rec['n_series']} series), "
            f"health={ts_rec['worst_state']}, parity={ts_parity}")

        if "prefix_serving" in skip_sections:
            log("prefix_serving: skipped via CHAINERMN_TPU_SERVE_SKIP_SECTIONS")
        else:
            # ---- prefix-heavy workload: shared system prompt, mixed tails - #
            # The admission fast path's acceptance numbers (ISSUE 5): the SAME
            # workload runs twice through bucketed batched-prefill engines —
            # prefix cache ON vs OFF — so the TTFT delta isolates KV reuse.
            # Every request shares a system-prompt prefix; tails are ragged.
            buckets = tuple(
                int(x) for x in e(
                    "CHAINERMN_TPU_SERVE_BUCKETS",
                    f"{max(1, prefill_len // 4)},{prefill_len}").split(","))
            batch_k = int(e("CHAINERMN_TPU_SERVE_PREFILL_BATCH", "4"))
            shared_len = min(int(e("CHAINERMN_TPU_SERVE_SHARED_PREFIX",
                                   str(3 * prefill_len // 4))), prefill_len - 1)
            block = int(e("CHAINERMN_TPU_SERVE_PREFIX_BLOCK",
                          str(max(1, prefill_len // 8))))
            n_blocks = int(e("CHAINERMN_TPU_SERVE_PREFIX_BLOCKS", "64"))
            min_insert = int(e("CHAINERMN_TPU_SERVE_MIN_INSERT", "2"))
            shared = rng.randint(1, vocab, shared_len).astype(np.int32)
            tail_max = prefill_len - shared_len
            jobs = [
                (np.concatenate([shared, rng.randint(
                    1, vocab, 1 + i % tail_max).astype(np.int32)]),
                 int(rng.randint(1, max_new + 1)))
                for i in range(n_requests)
            ]

            def run_prefix_workload(prefix_on):
                eng = ServingEngine(
                    model, params, n_slots=n_slots, prefill_buckets=buckets,
                    prefill_batch=batch_k,
                    prefix_cache_blocks=n_blocks if prefix_on else 0,
                    prefix_block_size=block,
                    prefix_min_insert_blocks=min_insert)
                eng.warmup()                      # every program, off the clock
                counts = eng.compile_counts_detailed()
                seeder = FCFSScheduler(eng)       # seed the trie off the clock
                seeder.submit(
                    np.concatenate([shared, np.array([1], np.int32)]), 1)
                seeder.run_until_idle()
                s = FCFSScheduler(eng)
                t0 = time.time()
                reqs = [s.submit(p, n) for p, n in jobs]
                s.run_until_idle()
                wall = time.time() - t0
                assert eng.compile_counts_detailed() == counts, "recompiled!"
                return eng, s.metrics.report(), reqs, wall

            eng_on, m_on, reqs_on, wall_on = run_prefix_workload(True)
            eng_off, m_off, _, wall_off = run_prefix_workload(False)
            # token-for-token parity vs solo generate() (greedy), through
            # prefix fetch + batched suffix prefill
            parity = True
            for i in (0, 1):
                prompt, n = jobs[i]
                ref = np.asarray(generate(model, params,
                                          jnp.asarray(prompt)[None], n)[0])
                parity = parity and bool(np.array_equal(reqs_on[i].output, ref))
            pstats = eng_on.prefix_stats()
            record["prefix_serving"] = {
                "buckets": list(buckets),
                "prefill_batch": batch_k,
                "shared_prefix": shared_len,
                "prefix_blocks": n_blocks,
                "block_size": block,
                # per-ADMISSION hit rate (fraction of admitted requests whose
                # prompt was partly served from cache); the trie's own stats
                # (below) count every match probe incl. re-scanned candidates
                "hit_rate": m_on.get("prefix_hit_rate", 0.0),
                "trie": pstats,
                "evictions": pstats["evictions"],
                "cached_prefix_frac_mean": m_on.get("cached_prefix_frac_mean",
                                                    0.0),
                "prefill_batch_occupancy":
                    m_on.get("prefill_batch_size_mean", 0.0),
                "ttft_p50_ms": round(m_on["ttft_p50_s"] * 1e3, 3),
                "ttft_p99_ms": round(m_on["ttft_p99_s"] * 1e3, 3),
                "ttft_p50_ms_off": round(m_off["ttft_p50_s"] * 1e3, 3),
                "ttft_p99_ms_off": round(m_off["ttft_p99_s"] * 1e3, 3),
                "ttft_p50_speedup": round(
                    m_off["ttft_p50_s"] / max(m_on["ttft_p50_s"], 1e-9), 3),
                "tokens_per_sec": m_on["tokens_per_sec"],
                "tokens_per_sec_off": m_off["tokens_per_sec"],
                "wall_s": round(wall_on, 3),
                "wall_s_off": round(wall_off, 3),
                "recompiles_after_warmup":
                    sum(eng_on.recompiles.values())
                    + sum(eng_off.recompiles.values()),
                "parity_vs_solo_generate": parity,
                "compile_counts": eng_on.compile_counts_detailed(),
            }
            log(f"prefix serving: "
                f"hit_rate={record['prefix_serving']['hit_rate']} "
                f"ttft_p50 {record['prefix_serving']['ttft_p50_ms']}ms (on) vs "
                f"{record['prefix_serving']['ttft_p50_ms_off']}ms (off), "
                f"parity={parity}")

        if "paged_serving" in skip_sections:
            log("paged_serving: skipped via CHAINERMN_TPU_SERVE_SKIP_SECTIONS")
        else:
            # ---- paged KV decode: ON vs OFF at the SAME device KV budget - #
            # The PR-7 acceptance: a dense engine reserves cache_len rows per
            # slot regardless of what requests actually use, so concurrency =
            # n_slots. The paged engine spends the SAME row budget as a block
            # pool and admits by blocks actually needed — short requests pack
            # 4x+ more concurrent decodes into identical memory (worst-case
            # block-budget admission, so zero preemptions in the clean run).
            pg_prefill = int(e("CHAINERMN_TPU_SERVE_PAGED_PREFILL", "16"))
            pg_cache = int(e("CHAINERMN_TPU_SERVE_PAGED_CACHE", "64"))
            pg_bs = int(e("CHAINERMN_TPU_SERVE_KV_BLOCK", "8"))
            pg_batch = int(e("CHAINERMN_TPU_SERVE_PAGED_BATCH", "4"))
            pg_max_new = int(e("CHAINERMN_TPU_SERVE_PAGED_MAX_NEW", "6"))
            pg_quant = e("CHAINERMN_TPU_SERVE_KV_QUANT", "none")
            dense_slots = int(e("CHAINERMN_TPU_SERVE_DENSE_SLOTS", "2"))
            paged_slots = int(e("CHAINERMN_TPU_SERVE_PAGED_SLOTS", "12"))
            budget_rows = dense_slots * pg_cache       # dense-resident KV rows
            pg_blocks = budget_rows // pg_bs + 1       # same rows (+ scratch)
            pg_jobs = [
                (rng.randint(1, vocab,
                             2 + i % (pg_prefill // 2 - 1)).astype(np.int32),
                 pg_max_new)
                for i in range(int(e("CHAINERMN_TPU_SERVE_PAGED_REQUESTS",
                                     "16")))
            ]

            def run_paged_workload(paged_on):
                kw = (dict(paged=True, kv_blocks=pg_blocks, kv_block_size=pg_bs,
                           kv_quant=pg_quant, n_slots=paged_slots)
                      if paged_on else dict(n_slots=dense_slots))
                eng = ServingEngine(model, params, prefill_buckets=(pg_prefill,),
                                    prefill_batch=pg_batch, cache_len=pg_cache,
                                    **kw)
                eng.warmup()
                counts = eng.compile_counts_detailed()
                s = FCFSScheduler(eng)
                t0 = time.time()
                reqs = [s.submit(p, n) for p, n in pg_jobs]
                s.run_until_idle()
                wall = time.time() - t0
                assert eng.compile_counts_detailed() == counts, "recompiled!"
                return eng, s.metrics.report(), reqs, wall

            eng_pg, m_pg, reqs_pg, wall_pg = run_paged_workload(True)
            eng_dn, m_dn, reqs_dn, wall_dn = run_paged_workload(False)
            pg_parity = True
            for i in (0, 1):
                prompt, n = pg_jobs[i]
                ref = np.asarray(generate(model, params,
                                          jnp.asarray(prompt)[None], n)[0])
                pg_parity = (pg_parity
                             and bool(np.array_equal(reqs_pg[i].output, ref))
                             and bool(np.array_equal(reqs_dn[i].output, ref)))
            record["paged_serving"] = {
                "kv_blocks": pg_blocks,
                "kv_block_size": pg_bs,
                "kv_quant": pg_quant,
                "kv_budget_rows": budget_rows,
                "dense_slots": dense_slots,
                "paged_slots": paged_slots,
                "max_concurrent_paged": eng_pg.peak_active,
                "max_concurrent_dense": eng_dn.peak_active,
                "concurrency_gain": round(
                    eng_pg.peak_active / max(eng_dn.peak_active, 1), 3),
                "tokens_per_sec": m_pg["tokens_per_sec"],
                "tokens_per_sec_dense": m_dn["tokens_per_sec"],
                "wall_s": round(wall_pg, 3),
                "wall_s_dense": round(wall_dn, 3),
                "preemptions": m_pg.get("kv_preemptions", 0),
                "kv_blocks_per_request_mean":
                    m_pg.get("kv_blocks_per_request_mean", 0.0),
                "kv_stats": eng_pg.kv_stats(),
                "parity_vs_solo_generate": pg_parity,
                "recompiles_after_warmup":
                    sum(eng_pg.recompiles.values())
                    + sum(eng_dn.recompiles.values()),
            }
            p = record["paged_serving"]
            log(f"paged serving: {p['max_concurrent_paged']} vs "
                f"{p['max_concurrent_dense']} concurrent "
                f"({p['concurrency_gain']}x) at {budget_rows} KV rows, "
                f"preemptions={p['preemptions']}, parity={pg_parity}")

        if "paged_kernel_serving" in skip_sections:
            log("paged_kernel_serving: skipped via "
                "CHAINERMN_TPU_SERVE_SKIP_SECTIONS")
        else:
            # ---- fused paged-decode kernel: ON vs OFF ---------------------- #
            # ISSUE 14: two paged engines differing ONLY in paged_kernel= run
            # the identical workload. Off TPU the kernel executes in Pallas
            # interpret mode, so the tokens/s pair is parity/recompile
            # EVIDENCE there, not a performance claim — the speedup number is
            # only meaningful on real hardware (the smoke test gates on
            # device_kind the same way). The bytes-read model rides along:
            # it is the analytical XLA-dense-view vs streamed-blocks cost,
            # computed from the workload's final lengths, chip-free.
            from chainermn_tpu.parallel.paged_kernel import (
                bytes_read_model,
                kernel_supported,
            )

            def run_kernel_workload():
                eng = ServingEngine(model, params, prefill_buckets=(pg_prefill,),
                                    prefill_batch=pg_batch, cache_len=pg_cache,
                                    paged=True, kv_blocks=pg_blocks,
                                    kv_block_size=pg_bs, kv_quant=pg_quant,
                                    n_slots=paged_slots, paged_kernel=True)
                eng.warmup()
                counts = eng.compile_counts_detailed()
                s = FCFSScheduler(eng)
                t0 = time.time()
                reqs = [s.submit(p_, n_) for p_, n_ in pg_jobs]
                s.run_until_idle()
                wall = time.time() - t0
                assert eng.compile_counts_detailed() == counts, "recompiled!"
                return eng, s.metrics.report(), reqs, wall

            eng_kn, m_kn, reqs_kn, wall_kn = run_kernel_workload()
            # the OFF side IS the paged section's engine — identical config
            # down to paged_kernel=False, same jobs — so its run is reused
            # rather than rebuilt (the tier-1 bench smoke rides this)
            eng_kf, m_kf, reqs_kf, wall_kf = eng_pg, m_pg, reqs_pg, wall_pg
            kn_parity = all(
                bool(np.array_equal(a.output, b.output))
                for a, b in zip(reqs_kn, reqs_kf))
            for i in (0, 1):
                prompt, n = pg_jobs[i]
                ref = np.asarray(generate(model, params,
                                          jnp.asarray(prompt)[None], n)[0])
                kn_parity = (kn_parity
                             and bool(np.array_equal(reqs_kn[i].output, ref)))
            final_lengths = [len(p_) + n_ for p_, n_ in pg_jobs]
            supported, why = kernel_supported()
            record["paged_kernel_serving"] = {
                "kernel_used": bool(eng_kn.paged_kernel),
                "kernel_supported": supported,
                "fallback_reason": why,
                "interpret_mode": jax.default_backend() != "tpu",
                "device_kind": jax.devices()[0].device_kind,
                "kv_quant": pg_quant,
                "kv_block_size": pg_bs,
                "tokens_per_sec": m_kn["tokens_per_sec"],
                "tokens_per_sec_off": m_kf["tokens_per_sec"],
                "wall_s": round(wall_kn, 3),
                "wall_s_off": round(wall_kf, 3),
                "parity_vs_xla_and_solo": kn_parity,
                "recompiles_after_warmup":
                    sum(eng_kn.recompiles.values())
                    + sum(eng_kf.recompiles.values()),
                "bytes_read_model": bytes_read_model(
                    final_lengths, block_size=pg_bs,
                    max_blocks=-(-pg_cache // pg_bs),
                    n_heads=model.n_heads,
                    head_dim=model.d_model // model.n_heads,
                    n_layers=model.n_layers, kv_quant=pg_quant),
            }
            kn = record["paged_kernel_serving"]
            log(f"paged kernel: used={kn['kernel_used']} "
                f"(interpret={kn['interpret_mode']}), parity={kn_parity}, "
                f"read_amp={kn['bytes_read_model']['read_amplification']}x "
                f"modelled")

        if "speculative_serving" in skip_sections:
            log("speculative_serving: skipped via "
                "CHAINERMN_TPU_SERVE_SKIP_SECTIONS")
        else:
            # ---- speculative decode: prompt-lookup drafting ON vs OFF ----- #
            # ISSUE 12: a shared-system-prompt workload with LONG greedy
            # generations (the regime speculation targets) through two paged
            # engines differing ONLY in ``speculative=``; the n-gram drafter
            # costs no second model, so the tokens/s ratio isolates
            # multi-token commit per dispatch. Outputs are asserted
            # token-identical ON vs OFF. A randomly-initialized transformer's
            # greedy trajectory is aperiodic noise (nothing for prompt-lookup
            # to mine — accept rate ~0, a pure slowdown), so this section
            # measures the CONTROLLED-accept-rate regime instead: the random
            # params are surgically rewritten into a "copy-cycle" model —
            # every block's output projections zeroed (residual blocks become
            # identity, attention still computed at full cost), one-hot
            # embeddings, and an lm_head permutation so greedy decode walks a
            # period-``sp_period`` token cycle with huge argmax margins. The
            # accept rate this induces travels in the record; the speedup
            # number is the dispatch-amortization mechanism, not a claim
            # about random-weight trajectories.
            from chainermn_tpu.serving import SpeculativeConfig
            sp_k = int(e("CHAINERMN_TPU_SERVE_SPEC_K", "6"))
            sp_max_new = int(e("CHAINERMN_TPU_SERVE_SPEC_MAX_NEW", "64"))
            sp_requests = int(e("CHAINERMN_TPU_SERVE_SPEC_REQUESTS", "8"))
            sp_slots = int(e("CHAINERMN_TPU_SERVE_SPEC_SLOTS", "4"))
            sp_period = int(e("CHAINERMN_TPU_SERVE_SPEC_PERIOD", "4"))
            # a deliberately tiny model: the section measures dispatch
            # amortization, which is LARGEST when per-step compute is small,
            # and two engines (ON + OFF) get compiled from it
            sp_d = int(e("CHAINERMN_TPU_SERVE_SPEC_DMODEL", "32"))
            sp_layers = int(e("CHAINERMN_TPU_SERVE_SPEC_LAYERS", "1"))
            sp_heads = int(e("CHAINERMN_TPU_SERVE_SPEC_HEADS", "2"))
            sp_vocab = min(vocab, sp_d)          # one-hot rows need d >= vocab
            sp_model = TransformerLM(
                vocab_size=sp_vocab, d_model=sp_d, n_heads=sp_heads,
                n_layers=sp_layers, max_len=prefill_len + sp_max_new)
            sp_params = jax.device_get(sp_model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, prefill_len), jnp.int32)))
            sp_p = sp_params["params"]
            sp_p["embed"]["embedding"] = (
                4.0 * np.eye(sp_vocab, sp_d)).astype(np.float32)
            sp_p["pos_embed"]["embedding"] = np.zeros_like(
                sp_p["pos_embed"]["embedding"])
            for li in range(sp_layers):
                blk = sp_p[f"block_{li}"]
                for nm in ("proj", "Dense_1"):
                    blk[nm]["kernel"] = np.zeros_like(blk[nm]["kernel"])
                    blk[nm]["bias"] = np.zeros_like(blk[nm]["bias"])
            sp_head = np.zeros_like(sp_p["lm_head"]["kernel"])
            for t in range(sp_vocab):     # successor permutation, short cycles
                sp_head[t, (t // sp_period) * sp_period
                        + ((t % sp_period) + 1) % sp_period] = 1.0
            sp_p["lm_head"]["kernel"] = sp_head
            sp_p["lm_head"]["bias"] = np.zeros_like(sp_p["lm_head"]["bias"])
            sp_shared = rng.randint(1, sp_vocab, shared_len).astype(np.int32)
            sp_cache = prefill_len + sp_max_new
            sp_blocks = sp_slots * (sp_cache // pg_bs + 2) + 1
            sp_jobs = [
                (np.concatenate([sp_shared, rng.randint(
                    1, sp_vocab, 1 + i % max(1, tail_max)).astype(np.int32)]),
                 sp_max_new)
                for i in range(sp_requests)
            ]

            def run_spec_workload(spec_on):
                eng = ServingEngine(
                    sp_model, sp_params, n_slots=sp_slots,
                    prefill_buckets=(prefill_len,), prefill_batch=pg_batch,
                    cache_len=sp_cache, paged=True, kv_blocks=sp_blocks,
                    kv_block_size=pg_bs,
                    speculative=(SpeculativeConfig(k=sp_k) if spec_on
                                 else None))
                eng.warmup()
                counts = eng.compile_counts_detailed()
                s = FCFSScheduler(eng)
                t0 = time.time()
                reqs = [s.submit(p, n) for p, n in sp_jobs]
                s.run_until_idle()
                wall = time.time() - t0
                assert eng.compile_counts_detailed() == counts, "recompiled!"
                return eng, s.metrics.report(), reqs, wall

            eng_sp, m_sp, reqs_sp, wall_sp = run_spec_workload(True)
            eng_ns, m_ns, reqs_ns, wall_ns = run_spec_workload(False)
            sp_parity = all(
                bool(np.array_equal(a.output, b.output))
                for a, b in zip(reqs_sp, reqs_ns))
            sp_stats = eng_sp.spec_stats()
            record["speculative_serving"] = {
                "drafter": "ngram",
                "spec_k": sp_k,
                "n_requests": sp_requests,
                "max_new": sp_max_new,
                "shared_prefix": shared_len,
                "cycle_period": sp_period,
                "model": {"vocab": sp_vocab, "d_model": sp_d,
                          "n_layers": sp_layers, "n_heads": sp_heads,
                          "family": "copy-cycle"},
                "accept_rate": sp_stats["accept_rate"],
                "spec_tokens_proposed": sp_stats["spec_tokens_proposed"],
                "spec_tokens_accepted": sp_stats["spec_tokens_accepted"],
                "tokens_per_sec": m_sp["tokens_per_sec"],
                "tokens_per_sec_off": m_ns["tokens_per_sec"],
                "decode_speedup": round(
                    m_sp["tokens_per_sec"]
                    / max(m_ns["tokens_per_sec"], 1e-9), 3),
                "ttft_p50_ms": round(m_sp["ttft_p50_s"] * 1e3, 3),
                "ttft_p50_ms_off": round(m_ns["ttft_p50_s"] * 1e3, 3),
                "tpot_p50_ms": round(m_sp["tpot_p50_s"] * 1e3, 3),
                "tpot_p50_ms_off": round(m_ns["tpot_p50_s"] * 1e3, 3),
                "wall_s": round(wall_sp, 3),
                "wall_s_off": round(wall_ns, 3),
                "parity_on_vs_off": sp_parity,
                "recompiles_after_warmup":
                    sum(eng_sp.recompiles.values())
                    + sum(eng_ns.recompiles.values()),
                "compile_counts": eng_sp.compile_counts_detailed(),
            }
            sp = record["speculative_serving"]
            log(f"speculative serving: accept_rate={sp['accept_rate']} "
                f"{sp['tokens_per_sec']} vs {sp['tokens_per_sec_off']} tok/s "
                f"({sp['decode_speedup']}x), parity={sp_parity}")

        if "hot_swap" in skip_sections:
            log("hot_swap: skipped via CHAINERMN_TPU_SERVE_SKIP_SECTIONS")
        else:
            # -- hot swap: online weight publish through the version fence - #
            # ISSUE 10 serving-continuity probe: n_swaps publishes land in the
            # base engine while it decodes. Each cycle fills the pool, fences
            # a swap mid-stream (publish_async: this thread drives step(), so
            # a blocking publish would deadlock against its own fence), keeps
            # stepping until the swap lands, then submits post-swap work. The
            # record carries swap latency p50/max, the tokens/s dip inside the
            # swap windows vs steady state, the version ledger, and the
            # zero-recompile invariant across every swap.
            from chainermn_tpu.deploy import WeightPublisher

            n_swaps = int(e("CHAINERMN_TPU_SERVE_SWAPS", "3"))
            hs_sched = FCFSScheduler(engine)
            hs_pub = WeightPublisher(engine, hs_sched)
            hs_counts = engine.compile_counts_detailed()
            new_params = jax.tree_util.tree_map(lambda l: l * 1.001, params)
            base_version = engine.weight_version
            swap_total, swap_fence, swap_commit = [], [], []
            window_tokens = window_wall = 0.0
            versions_ok = True
            hs_done = 0
            hs_total = 0
            t0 = time.time()
            for k in range(n_swaps):
                pre = [hs_sched.submit(
                    rng.randint(1, vocab, rng.randint(
                        1, prefill_len + 1)).astype(np.int32), max_new)
                    for _ in range(n_slots)]
                hs_sched.step()            # admit the pool on the OLD weights
                handle = hs_pub.publish_async(new_params)
                t_sw = time.time()
                while not handle.done:     # fence drains, swap lands mid-loop
                    window_tokens += hs_sched.step()
                window_wall += time.time() - t_sw
                post = [hs_sched.submit(
                    rng.randint(1, vocab, rng.randint(
                        1, prefill_len + 1)).astype(np.int32), max_new)
                    for _ in range(2)]
                hs_sched.run_until_idle()
                swap_total.append(handle.total_s)
                swap_fence.append(handle.fence_s)
                swap_commit.append(handle.commit_s)
                want_pre = base_version + k
                versions_ok = versions_ok and all(
                    r.weight_version == want_pre for r in pre) and all(
                    r.weight_version == want_pre + 1 for r in post)
                hs_total += len(pre) + len(post)
                hs_done += sum(r.state.value == "done" for r in pre + post)
            wall_hs = time.time() - t0
            hs_m = hs_sched.metrics.report()
            steady_tps = hs_m["tokens_per_sec"]
            window_tps = window_tokens / max(window_wall, 1e-9)
            assert engine.compile_counts_detailed() == hs_counts, "recompiled!"
            record["hot_swap"] = {
                "swaps": n_swaps,
                "swap_total_s_p50": round(
                    float(np.percentile(swap_total, 50)), 6),
                "swap_total_s_max": round(float(max(swap_total)), 6),
                "swap_fence_s_p50": round(
                    float(np.percentile(swap_fence, 50)), 6),
                "swap_commit_s_p50": round(
                    float(np.percentile(swap_commit, 50)), 6),
                "tokens_per_sec_steady": steady_tps,
                "tokens_per_sec_during_swap": round(window_tps, 2),
                "throughput_dip_frac": round(
                    1.0 - window_tps / max(steady_tps, 1e-9), 4),
                "requests": hs_total,
                "requests_done": hs_done,
                "weight_version": engine.weight_version,
                "versions_correct": versions_ok,
                "wall_s": round(wall_hs, 3),
                "recompiles_after_warmup": sum(engine.recompiles.values()),
            }
            hsr = record["hot_swap"]
            log(f"hot swap: {n_swaps} swaps, total_p50="
                f"{hsr['swap_total_s_p50'] * 1e3:.1f}ms (fence "
                f"{hsr['swap_fence_s_p50'] * 1e3:.1f}ms), dip="
                f"{hsr['throughput_dip_frac']}, versions_ok={versions_ok}, "
                f"recompiles={hsr['recompiles_after_warmup']}")

        if "fleet_serving" in skip_sections:
            log("fleet_serving: skipped via CHAINERMN_TPU_SERVE_SKIP_SECTIONS")
        else:
            # -- fleet: N replicas vs 1 at equal total KV budget (ISSUE 8) - #
            # The SAME prefix-heavy workload through a FleetRouter over
            # fl_n replicas of n_slots/fl_n slots each (total KV budget ==
            # the solo prefix engine above, whose numbers are the baseline),
            # plus the kill-one-replica continuity probe: replica 0 is
            # hard-killed once it owns live work — its queued/in-flight
            # requests must re-route (replayed, stream-dedup'd) or end
            # cleanly ERRORED per deadline policy; none may be lost.
            from chainermn_tpu.fleet import FleetRouter
            from chainermn_tpu.serving.scheduler import DeadlineExceededError

            fl_n = int(e("CHAINERMN_TPU_SERVE_FLEET_REPLICAS", "2"))
            fl_slots = max(1, n_slots // fl_n)
            fl_engines = [ServingEngine(
                model, params, n_slots=fl_slots, prefill_buckets=buckets,
                prefill_batch=batch_k, prefix_cache_blocks=n_blocks,
                prefix_block_size=block, prefix_min_insert_blocks=min_insert)
                for _ in range(fl_n)]
            router = FleetRouter(fl_engines, affinity=True)
            fl_col = None
            try:
                assert router.wait_ready(600), "fleet warmup timed out"
                # continuous telemetry rides the fleet run too (ISSUE 15):
                # per-replica sensors + health scoring + routing penalty,
                # sampled by a background collector for the whole probe
                from chainermn_tpu.monitor.health import fleet_health

                fl_col = fleet_health(router, cadence_s=ts_cadence,
                                      stall_timeout_s=60.0)
                fl_col.start()
                t0 = time.time()
                frs = [router.submit(prompt, n) for prompt, n in jobs]
                kill_deadline = time.time() + 60
                while time.time() < kill_deadline:
                    snap0 = router.replicas[0].snapshot()
                    if snap0.queue_depth + snap0.active_slots > 0:
                        break
                    if all(fr.finished for fr in frs):
                        break
                    time.sleep(0.001)
                router.kill_replica(0)
                finished = [fr.wait(timeout=600) for fr in frs]
                wall_fl = time.time() - t0
                # the health verdict is scored on the collector cadence: give
                # it a bounded window to observe the quarantine before the
                # report is captured (deterministic, not sleep-and-hope)
                h_deadline = time.time() + 30
                while time.time() < h_deadline:
                    h = router.fleet_report().get("health") or {}
                    if h.get("replicas", {}).get("0", {}).get(
                            "state") == "critical":
                        break
                    time.sleep(ts_cadence)
                rep = router.fleet_report()
                fl_parity = True
                for i in (0, 1):
                    prompt, n = jobs[i]
                    if frs[i].state.value != "done":
                        continue
                    ref = np.asarray(generate(model, params,
                                              jnp.asarray(prompt)[None], n)[0])
                    fl_parity = fl_parity and bool(
                        np.array_equal(frs[i].output, ref))
                lost = [fr.id for fr in frs
                        if not fr.finished
                        or (fr.state.value != "done"
                            and not isinstance(fr.error, DeadlineExceededError))]
                survivors = [r for r in router.replicas
                             if r.state.value != "quarantined"]
                pooled = rep["pooled"]
                pooled_ttft = pooled["histograms"].get(
                    "serving_ttft_seconds", {})
                fl_tokens = pooled["counters"].get("serving_tokens_total", 0)
                record["fleet_serving"] = {
                    "replicas": fl_n,
                    "slots_per_replica": fl_slots,
                    "solo_slots": n_slots,
                    "requests": len(jobs),
                    "done": sum(fr.state.value == "done" for fr in frs),
                    "all_terminal": all(finished),
                    "no_request_lost": not lost,
                    "killed_replica_quarantined":
                        router.replicas[0].state.value == "quarantined",
                    "capacity_after_kill": rep["capacity"],
                    "reroutes": rep["reroutes_total"],
                    "shed": rep["shed_total"],
                    "route_fallbacks": rep["route_fallbacks_total"],
                    "affinity_hit_rate": rep["affinity"]["hit_rate"],
                    "tokens_per_sec": round(fl_tokens / max(wall_fl, 1e-9), 2),
                    "tokens_per_sec_solo": m_on["tokens_per_sec"],
                    "ttft_p50_ms": round(
                        pooled_ttft.get("p50_s", 0.0) * 1e3, 3),
                    "ttft_p99_ms": round(
                        pooled_ttft.get("p99_s", 0.0) * 1e3, 3),
                    "ttft_p50_ms_solo": round(m_on["ttft_p50_s"] * 1e3, 3),
                    "wall_s": round(wall_fl, 3),
                    "parity_vs_solo_generate": fl_parity,
                    "recompiles_after_warmup_survivors": sum(
                        sum(r.engine.recompiles.values()) for r in survivors),
                    "replica_states": {k: v["state"]
                                       for k, v in rep["replicas"].items()},
                    # the health monitor's verdicts at probe end: the killed
                    # replica must have gone critical, survivors healthy
                    "health": rep.get("health"),
                    "ts_series": len(fl_col.store.names()),
                    "ts_ticks": fl_col.ticks,
                }
                # rolling publish through the surviving replicas: the
                # quarantined kill-probe victim is skipped, everyone still
                # accepting takes the new version with zero recompiles
                pub_out = router.publish(new_params, timeout=120.0)
                rep2 = router.fleet_report()
                record["fleet_serving"]["publish"] = {
                    "ok": pub_out["ok"],
                    "outcomes": pub_out["replicas"],
                    "weight_versions": {
                        k: v["weight_version"]
                        for k, v in rep2["replicas"].items()},
                    "recompiles_after_publish_survivors": sum(
                        sum(r.engine.recompiles.values()) for r in survivors),
                }
            finally:
                if fl_col is not None:
                    fl_col.stop()
                router.close()
            fl = record["fleet_serving"]
            log(f"fleet serving: {fl['replicas']}x{fl['slots_per_replica']} "
                f"slots, done {fl['done']}/{fl['requests']} through a "
                f"mid-run replica kill (reroutes={fl['reroutes']}, "
                f"lost={not fl['no_request_lost']}), affinity "
                f"hit_rate={fl['affinity_hit_rate']}, parity={fl_parity}")

        if "fleet_autoscale" in skip_sections:
            log("fleet_autoscale: skipped via CHAINERMN_TPU_SERVE_SKIP_SECTIONS")
        else:
            # ---- fleet autoscale: diurnal arrivals (ISSUE 16) ------------- #
            # A compressed diurnal cycle: sinusoidal arrival rate over one
            # window (trough -> peak -> trough) against a fleet that starts
            # at min_replicas with the closed-loop controller LIVE. Replica
            # count must track load — scale up under the peak, retire back
            # to the floor in the trough — with zero requests lost.
            import math

            from chainermn_tpu.fleet import AutoscalePolicy, FleetController

            as_window = float(e("CHAINERMN_TPU_SERVE_AS_WINDOW", "6.0"))
            # arrival rates are expressed as MULTIPLES of one replica's
            # measured service rate, so the peak is a genuine overload on
            # any machine (a fixed req/s would be a no-op on a fast box)
            as_base_x = float(e("CHAINERMN_TPU_SERVE_AS_BASE_X", "0.3"))
            as_peak_x = float(e("CHAINERMN_TPU_SERVE_AS_PEAK_X", "3.0"))
            as_cap = int(e("CHAINERMN_TPU_SERVE_AS_MAX_REQUESTS", "400"))
            as_min = int(e("CHAINERMN_TPU_SERVE_AS_MIN", "1"))
            as_max = int(e("CHAINERMN_TPU_SERVE_AS_MAX", "3"))
            as_prefill, as_new = 16, 12

            def as_engine():
                # deliberately small: ONE slot per replica, so the diurnal
                # peak genuinely exceeds a single replica's service rate
                return ServingEngine(model, params, n_slots=1,
                                     prefill_len=as_prefill,
                                     cache_len=as_prefill + as_new + 4)

            router2 = FleetRouter([as_engine() for _ in range(as_min)])
            ctrl = as_col = None
            try:
                assert router2.wait_ready(600), "autoscale warmup timed out"
                rng2 = np.random.RandomState(7)
                # calibrate: sequential service time of this request shape on
                # the floor fleet — the sinusoid's amplitude is set off it
                t_cal = time.time()
                for _ in range(3):
                    p2 = rng2.randint(1, vocab, size=8).astype(np.int32)
                    router2.submit(p2, as_new).wait(timeout=600)
                svc_s = max((time.time() - t_cal) / 3.0, 1e-3)
                as_base = as_base_x / svc_s
                as_peak = as_peak_x / svc_s
                as_col = fleet_health(router2, cadence_s=ts_cadence,
                                      stall_timeout_s=60.0)
                as_col.start()
                ctrl = FleetController(
                    router2, as_col, engine_factory=as_engine,
                    autoscale=AutoscalePolicy(
                        min_replicas=as_min, max_replicas=as_max,
                        queue_high=1.0, idle_low=0.25, up_after_s=0.2,
                        down_after_s=0.8, cooldown_s=0.3),
                    cadence_s=0.05, sensor_kw=dict(stall_timeout_s=60.0))
                ctrl.start()
                t0 = time.time()
                as_frs, caps = [], []
                while ((el := time.time() - t0) < as_window
                       and len(as_frs) < as_cap):
                    rate = as_base + (as_peak - as_base) * 0.5 * (
                        1.0 - math.cos(2.0 * math.pi * el / as_window))
                    # ~50ms arrival chunks: sleep() granularity stays sane
                    # even when the calibrated peak is hundreds of req/s
                    burst = max(1, int(rate * 0.05))
                    for _ in range(burst):
                        p2 = rng2.randint(
                            1, vocab, size=rng2.randint(4, 9)).astype(np.int32)
                        as_frs.append(router2.submit(p2, as_new))
                    caps.append(router2.capacity)
                    time.sleep(burst / max(rate, 0.5))
                as_done = [fr.wait(timeout=600) for fr in as_frs]
                # the trough: give the controller a bounded window to see
                # sustained idleness and retire back down to the floor
                down_deadline = time.time() + 60
                while (time.time() < down_deadline
                       and router2.capacity > as_min):
                    time.sleep(0.05)
                caps.append(router2.capacity)
                wall_as = round(time.time() - t0, 3)
                crep = ctrl.report()
                as_lost = [fr.id for fr in as_frs
                           if not fr.finished or fr.state.value != "done"]
                record["fleet_autoscale"] = {
                    "window_s": as_window,
                    "service_s_calibrated": round(svc_s, 4),
                    "arrival_base_hz": round(as_base, 2),
                    "arrival_peak_hz": round(as_peak, 2),
                    "requests": len(as_frs),
                    "done": sum(fr.state.value == "done" for fr in as_frs),
                    "all_terminal": all(as_done),
                    "no_request_lost": not as_lost,
                    "min_replicas": as_min,
                    "max_replicas": as_max,
                    "peak_capacity": max(caps),
                    "final_capacity": router2.capacity,
                    "scale_ups": crep["autoscale"]["scale_ups"],
                    "scale_downs": crep["autoscale"]["scale_downs"],
                    "replica_count_tracks_load": bool(
                        max(caps) > as_min and router2.capacity == as_min),
                    "recompiles_after_warmup": sum(
                        sum(r.engine.recompiles.values())
                        for r in router2.replicas if r.accepting),
                    "decisions": crep["decisions"],
                    "wall_s": wall_as,
                }
            finally:
                if ctrl is not None:
                    ctrl.stop()
                if as_col is not None:
                    as_col.stop()
                router2.close()
            fa = record["fleet_autoscale"]
            log(f"fleet autoscale: {fa['requests']} diurnal arrivals over "
                f"{fa['window_s']}s, capacity {fa['min_replicas']}->"
                f"{fa['peak_capacity']}->{fa['final_capacity']} "
                f"(ups={fa['scale_ups']}, downs={fa['scale_downs']}), "
                f"lost={not fa['no_request_lost']}")

        # ---- cost accounting: tenant ledger ON vs OFF, warm engine ---- #
        # ISSUE 17 acceptance: the per-request resource ledger must (a)
        # conserve — attributed device-seconds match the measured wall
        # time of every dispatch within ±10%; (b) cost <2% of serving
        # throughput; (c) let a deterministic threshold detector name the
        # bursty tenant. Two tenants share the warm base engine: "quiet"
        # submits a quarter of the jobs with short decodes, "bulk" the
        # rest with long ones. The SAME job list runs twice through fresh
        # schedulers — accounting OFF, then ON — so the wall-clock delta
        # isolates the ledger's host-side dict arithmetic.
        from chainermn_tpu.monitor._state import get_event_log
        from chainermn_tpu.monitor.costs import standard_tenant_sensors
        from chainermn_tpu.monitor.timeseries import Collector

        ca_jobs = [
            (rng.randint(1, vocab,
                         rng.randint(1, prefill_len + 1)).astype(np.int32),
             int(rng.randint(max(1, max_new // 2), max_new + 1)) if i % 4
             else int(rng.randint(1, max(2, max_new // 4))),
             "bulk" if i % 4 else "quiet")
            for i in range(n_requests)
        ]
        ca_counts = engine.compile_counts_detailed()

        def run_ca_workload(ca_on):
            s = FCFSScheduler(engine, cost_accounting=ca_on)
            col = None
            if ca_on:
                col = Collector(cadence_s=999.0)   # manual ticks only
                sigs, dets = standard_tenant_sensors(
                    "bulk", s.metrics.instance,
                    tenants=("bulk", "quiet"),
                    share_threshold=0.6, tag="bench")
                for sg in sigs:
                    col.add_signal(sg)
                for dt in dets:
                    col.add_detector(dt)
                # prime: one tiny request per tenant mints the per-tenant
                # counters, so the pre-burst tick anchors their rate
                # baselines (a counter's first sample derives no rate)
                for t in ("bulk", "quiet"):
                    s.submit(rng.randint(1, vocab, 2).astype(np.int32),
                             1, tenant=t)
                s.run_until_idle()
                col.tick()
            t0 = time.time()
            reqs = [s.submit(p, n, tenant=t) for p, n, t in ca_jobs]
            s.run_until_idle()
            wall = time.time() - t0
            summary = col.tick() if col is not None else None
            return s, reqs, wall, summary

        s_ca_off, reqs_ca_off, wall_ca_off, _ = run_ca_workload(False)
        assert s_ca_off.costs is None   # OFF really strips the ledger
        s_ca, reqs_ca, wall_ca_on, ca_tick = run_ca_workload(True)
        ca_parity = all(
            bool(np.array_equal(a.output, b.output))
            for a, b in zip(reqs_ca, reqs_ca_off))
        assert engine.compile_counts_detailed() == ca_counts, "recompiled!"
        cost_rep = s_ca.metrics.costs.report()
        ca_dt = cost_rep["device_time"]
        assert ca_dt["conservation_error"] <= 0.10, ca_dt
        assert ca_dt["max_dispatch_error"] <= 0.10, ca_dt
        nn = ca_tick["detectors"]["noisy_neighbor:bench"]
        nn_events = [ev for ev in get_event_log().tail(256)
                     if ev.get("kind") == "noisy_neighbor"]
        record["cost_accounting"] = {
            "wall_s_on": round(wall_ca_on, 3),
            "wall_s_off": round(wall_ca_off, 3),
            "accounting_overhead_frac": round(
                wall_ca_on / max(wall_ca_off, 1e-9) - 1.0, 4),
            "parity_on_vs_off": ca_parity,
            "recompiles_after_warmup": 0,
            "dispatches": ca_dt["dispatches"],
            "conservation_error": ca_dt["conservation_error"],
            "max_dispatch_error": ca_dt["max_dispatch_error"],
            "goodput": cost_rep["goodput"],
            "tenant_device_s": {
                t: row["device_total_s"]
                for t, row in cost_rep["tenants"].items()},
            "queue_wait_s": {
                t: row["queue_wait_s"]
                for t, row in cost_rep["tenants"].items()},
            "bulk_share": nn.get("value"),
            "noisy_neighbor_fired": bool(nn.get("firing")),
            "noisy_neighbor_tenant": (
                nn_events[-1].get("tenant") if nn_events else None),
        }
        ca = record["cost_accounting"]
        log(f"cost accounting: overhead={ca['accounting_overhead_frac']} "
            f"conservation={ca['conservation_error']} "
            f"(max_dispatch={ca['max_dispatch_error']} over "
            f"{ca['dispatches']} dispatches), goodput_useful="
            f"{ca['goodput']['useful']}, noisy_neighbor="
            f"{ca['noisy_neighbor_tenant']} "
            f"(share={ca['bulk_share']}), parity={ca_parity}")

        # ---- overload fairness: classes + weighted DRR vs FIFO -------- #
        # ISSUE 18 acceptance: drive the warm engine ~3x past its service
        # rate (a bursty tenant's interactive stream plus a batch tier
        # queued behind it). Plain FIFO makes the quiet tenant's
        # interactive TTFT collapse behind the backlog; fair admission
        # (strict interactive-before-batch + weighted DRR) holds it near
        # the unloaded baseline. The scheduler-owned brownout ladder
        # steps up under the sustained interactive backlog and fully
        # unwinds as it drains. Both overload runs see the SAME arrival
        # order, so token parity ON-vs-OFF proves admission order never
        # changes a stream; the warm engine never recompiles.
        from chainermn_tpu.serving.fairness import (
            BrownoutPolicy,
            FairAdmission,
        )
        from chainermn_tpu.serving.scheduler import RequestState

        of_nq = max(2, n_requests // 6)     # quiet interactive jobs
        of_rng = np.random.RandomState(18)

        def of_prompt():
            return of_rng.randint(
                1, vocab, of_rng.randint(max(1, prefill_len // 2),
                                         prefill_len + 1)).astype(np.int32)

        quiet_jobs = [(of_prompt(), max_new, "quiet", "interactive")
                      for _ in range(of_nq)]
        burst_jobs = [(of_prompt(), max_new, "burst", "interactive")
                      for _ in range(3 * of_nq)]
        batch_jobs = [(of_prompt(), max_new, "burst", "batch")
                      for _ in range(2 * of_nq)]
        # the arrival order both overload runs share: the batch backlog
        # is already queued, then the burst interleaves 3:1 with quiet
        mixed = list(batch_jobs)
        qi = iter(quiet_jobs)
        for i, job in enumerate(burst_jobs):
            mixed.append(job)
            if i % 3 == 2:
                nxt = next(qi, None)
                if nxt is not None:
                    mixed.append(nxt)
        mixed.extend(qi)

        def of_run(sched, jobs, track=None):
            t_first = {}
            reqs = []
            for prompt, n, tenant, priority in jobs:
                key = len(reqs)

                def cb(tok, _k=key):
                    t_first.setdefault(_k, time.perf_counter())
                reqs.append(sched.submit(prompt, n, tenant=tenant,
                                         priority=priority, stream_cb=cb))
            max_level = 0
            while sched.has_work:
                sched.step()
                if track is not None:
                    max_level = max(max_level, track.level)
            ttft = [t_first[i] - r.t_submit for i, r in enumerate(reqs)]
            return reqs, ttft, max_level

        def of_quiet_p99(jobs, ttft):
            vals = [t for j, t in zip(jobs, ttft)
                    if j[2] == "quiet" and j[3] == "interactive"]
            return float(np.percentile(np.asarray(vals), 99))

        of_counts = engine.compile_counts_detailed()
        # unloaded baseline: the quiet tenant alone on the warm engine
        s_of_base = FCFSScheduler(engine)
        _, base_ttft, _ = of_run(s_of_base, quiet_jobs)
        of_base_p99 = of_quiet_p99(quiet_jobs, base_ttft)
        # FIFO under overload: the pre-PR-18 scheduler, byte-identical
        s_of_fifo = FCFSScheduler(engine)
        fifo_reqs, fifo_ttft, _ = of_run(s_of_fifo, mixed)
        of_fifo_p99 = of_quiet_p99(mixed, fifo_ttft)
        # fair admission + brownout under the SAME arrivals. max_level=2
        # keeps L3's token cap and L4's shed out of play, so accepted
        # requests are EXACTLY the FIFO run's (parity + nothing lost);
        # quantum below typical request cost makes the 4:1 weights gate.
        of_bo = BrownoutPolicy(
            max_level=2, queue_high=float(max(2, n_slots // 2)),
            up_after_s=0.01, down_after_s=0.05, cooldown_s=0.03)
        of_fair = FairAdmission(
            tenant_weights={"quiet": 4.0, "burst": 1.0},
            quantum_tokens=2.0)
        s_of_fair = FCFSScheduler(engine, fair=of_fair, brownout=of_bo)
        fair_reqs, fair_ttft, of_max_level = of_run(s_of_fair, mixed,
                                                    track=of_bo)
        of_fair_p99 = of_quiet_p99(mixed, fair_ttft)
        # idle + calm: sustained zero interactive depth unwinds the
        # ladder one hysteresis window at a time
        of_deadline = time.time() + 30.0
        while of_bo.level > 0 and time.time() < of_deadline:
            s_of_fair.step()
            time.sleep(0.005)
        of_parity = all(
            bool(np.array_equal(a.output, b.output))
            for a, b in zip(fair_reqs, fifo_reqs))
        of_lost = not all(r.state is RequestState.DONE
                          for r in fifo_reqs + fair_reqs)
        assert engine.compile_counts_detailed() == of_counts, "recompiled!"
        of_cp = s_of_fair.metrics._c_class_preempt
        record["overload_fairness"] = {
            "slots": n_slots,
            "jobs": {"quiet_interactive": of_nq,
                     "burst_interactive": 3 * of_nq,
                     "batch": 2 * of_nq},
            "overload_factor": round(6 * of_nq / max(of_nq, 1), 2),
            "quiet_p99_unloaded": round(of_base_p99, 4),
            "quiet_p99_fifo": round(of_fifo_p99, 4),
            "quiet_p99_fair": round(of_fair_p99, 4),
            "fifo_collapse_factor": round(
                of_fifo_p99 / max(of_base_p99, 1e-9), 2),
            "quiet_slowdown_factor": round(
                of_fair_p99 / max(of_base_p99, 1e-9), 2),
            "quiet_goodput_tokens": int(of_nq * max_new),
            "brownout": {
                "max_level": int(of_max_level),
                "final_level": int(of_bo.level),
                "steps": of_bo.to_json()["steps"],
            },
            "preempted_interactive": int(of_cp["interactive"].value),
            "preempted_batch": int(of_cp["batch"].value),
            "token_parity_on_vs_off": of_parity,
            "no_request_lost": not of_lost,
            "recompiles_after_warmup": 0,
            "conservation_error": round(
                s_of_fair.costs.conservation_error, 9),
        }
        of = record["overload_fairness"]
        log(f"overload fairness: quiet TTFT p99 unloaded="
            f"{of['quiet_p99_unloaded']}s fifo={of['quiet_p99_fifo']}s "
            f"(x{of['fifo_collapse_factor']}) fair="
            f"{of['quiet_p99_fair']}s (x{of['quiet_slowdown_factor']}), "
            f"brownout {of['brownout']['max_level']}->"
            f"{of['brownout']['final_level']}, parity={of_parity}, "
            f"lost={of_lost}")

        # ---- chunked prefill: decode stall ON vs OFF ------------------ #
        # ISSUE 19 acceptance: with monolithic prefill, every long-prompt
        # admission stalls every decoding slot for the full top-bucket
        # prefill; chunked prefill bounds the stall to one chunk's bucket.
        # The SAME victim+aggressor arrival runs twice on one warm paged
        # engine — decode-gap p99 across the victims' streams must be
        # >= 2x better with chunking ON, token streams identical, zero
        # recompiles (chunks ride the warmup buckets).
        cp_chunk = int(e("CHAINERMN_TPU_SERVE_CHUNK_TOKENS", "16"))
        cp_nv = int(e("CHAINERMN_TPU_SERVE_CP_VICTIMS", "3"))
        cp_na = int(e("CHAINERMN_TPU_SERVE_CP_LONG", "2"))
        # the aggressor prompts get 8x the serving model's window: on CPU
        # a dispatch costs ~same as a small prefill, so the monolithic
        # top-bucket prefill has to DWARF one decode step (not just beat
        # it) for the stall to be the signal, not the call overhead
        cp_prefill = 8 * prefill_len
        cp_new = max(8, max_new)
        cp_model = TransformerLM(
            vocab_size=vocab, d_model=d_model, n_heads=n_heads,
            n_layers=n_layers, max_len=cp_prefill + cp_new)
        cp_params = cp_model.init(
            jax.random.PRNGKey(3), jnp.zeros((1, cp_prefill), jnp.int32))
        cp_rng = np.random.RandomState(19)
        cp_eng = ServingEngine(
            cp_model, cp_params, n_slots=cp_nv + 1,
            prefill_buckets=(cp_chunk, cp_prefill), prefill_batch=1,
            paged=True, kv_block_size=cp_chunk,
            kv_blocks=2 * (cp_nv + 1) * (-(-(cp_prefill + cp_new)
                                           // cp_chunk)),
            cache_len=cp_prefill + cp_new)
        cp_eng.warmup()
        cp_counts = cp_eng.compile_counts_detailed()
        victims = [(cp_rng.randint(1, vocab, cp_chunk - 2)
                    .astype(np.int32), cp_new) for _ in range(cp_nv)]
        aggressors = [(cp_rng.randint(1, vocab, cp_prefill - 1)
                       .astype(np.int32), 2) for _ in range(cp_na)]

        def cp_run(chunk):
            s = FCFSScheduler(cp_eng, chunk_tokens_per_step=chunk)
            stamps = [[] for _ in victims]
            vreqs = [
                s.submit(p, n, rng=jax.random.PRNGKey(100 + i),
                         stream_cb=lambda tok, _i=i: stamps[_i].append(
                             time.perf_counter()))
                for i, (p, n) in enumerate(victims)]
            while not all(stamps):          # victims all decoding first
                s.step()
            areqs = [s.submit(p, n, rng=jax.random.PRNGKey(200 + i))
                     for i, (p, n) in enumerate(aggressors)]
            while s.has_work:
                s.step()
            gaps = [b - a for ts in stamps
                    for a, b in zip(ts, ts[1:])]
            return ([r.tokens for r in vreqs + areqs],
                    float(np.percentile(np.asarray(gaps), 99)))

        cp_toks_off, cp_p99_off = cp_run(None)
        cp_toks_on, cp_p99_on = cp_run(cp_chunk)
        cp_parity = cp_toks_on == cp_toks_off
        assert cp_eng.compile_counts_detailed() == cp_counts, "recompiled!"
        record["chunked_prefill_serving"] = {
            "chunk_tokens": cp_chunk,
            "victims": cp_nv,
            "long_prompts": cp_na,
            "long_prompt_len": cp_prefill - 1,
            "decode_gap_p99_ms_off": round(cp_p99_off * 1e3, 3),
            "decode_gap_p99_ms_on": round(cp_p99_on * 1e3, 3),
            "stall_improvement": round(cp_p99_off / max(cp_p99_on, 1e-9),
                                       2),
            "token_parity_on_vs_off": cp_parity,
            "recompiles_after_warmup": 0,
        }
        cp = record["chunked_prefill_serving"]
        log(f"chunked prefill: victim decode-gap p99 "
            f"off={cp['decode_gap_p99_ms_off']}ms "
            f"on={cp['decode_gap_p99_ms_on']}ms "
            f"(x{cp['stall_improvement']}), parity={cp_parity}")

        # ---- disaggregated prefill/decode tiers ----------------------- #
        # 1P+1D with KV migration vs the same fleet symmetric: every
        # request prefills on the P tier, its blocks host-bounce to the D
        # tier, and the stream finishes there — same tokens either way,
        # nothing lost, no recompiles. The record carries both configs'
        # latency splits and the migration counters.
        from chainermn_tpu.fleet import FleetRouter
        from chainermn_tpu.monitor._state import get_registry

        dg_n = int(e("CHAINERMN_TPU_SERVE_DG_REQUESTS", "6"))
        dg_rng = np.random.RandomState(20)
        dg_jobs = [(dg_rng.randint(1, vocab, prefill_len - 1)
                    .astype(np.int32), max_new) for _ in range(dg_n)]

        def dg_engine():
            return ServingEngine(
                model, params, n_slots=2,
                prefill_buckets=(cp_chunk, prefill_len), prefill_batch=1,
                paged=True, kv_block_size=cp_chunk,
                kv_blocks=6 * (-(-(prefill_len + max_new) // cp_chunk)),
                cache_len=prefill_len + max_new)

        def dg_run(**tiers):
            router = FleetRouter([dg_engine(), dg_engine()], **tiers)
            try:
                assert router.wait_ready(600)
                t0 = time.perf_counter()
                frs = [router.submit(p, n,
                                     rng=jax.random.PRNGKey(300 + i))
                       for i, (p, n) in enumerate(dg_jobs)]
                done = all(fr.wait(300) for fr in frs)
                wall = time.perf_counter() - t0
                rep = router.fleet_report()
                for r in router.replicas:
                    assert r.engine.recompiles == {}, "recompiled!"
                return ([list(fr.tokens) for fr in frs], done, wall,
                        rep["tiers"])
            finally:
                router.close()

        dg_mig0 = sum(
            v for k, v in get_registry().snapshot()["counters"].items()
            if k.startswith("kv_migrations_total"))
        dg_toks, dg_done, dg_wall, dg_tiers = dg_run(
            prefill_replicas=1, decode_replicas=1,
            chunk_tokens_per_step=cp_chunk)
        dg_migrations = sum(
            v for k, v in get_registry().snapshot()["counters"].items()
            if k.startswith("kv_migrations_total")) - dg_mig0
        sym_toks, sym_done, sym_wall, _ = dg_run()
        record["disagg_serving"] = {
            "requests": dg_n,
            "tiers": dg_tiers,
            "migrations": int(dg_migrations),
            "wall_s_disagg": round(dg_wall, 3),
            "wall_s_symmetric": round(sym_wall, 3),
            "token_parity_vs_symmetric": dg_toks == sym_toks,
            "no_request_lost": bool(dg_done and sym_done),
            "recompiles_after_warmup": 0,
        }
        dg = record["disagg_serving"]
        log(f"disagg serving: {dg_n} reqs 1P+1D wall="
            f"{dg['wall_s_disagg']}s (symmetric="
            f"{dg['wall_s_symmetric']}s), migrations="
            f"{dg['migrations']}, parity={dg['token_parity_vs_symmetric']}"
            f", lost={not dg['no_request_lost']}")

        # ---- fleet-wide KV reuse: cross-replica prefix sharing -------- #
        # 3 paged replicas, every request carrying one shared system
        # prompt, and a zero-tolerance imbalance policy so the holder's
        # own load pushes traffic to its peers — the affinity-miss-heavy
        # arrival sharing exists for. ON: the holder exports the prefix
        # blocks ONCE through the fused gather, the host payload LRU
        # serves every later adopter, and peers prefill only their ragged
        # tails. OFF: every miss re-prefills the whole prompt. Same
        # tokens either way; the record carries TTFT p50 both ways plus
        # the fleet prefill tokens/FLOPs the shares avoided.
        from chainermn_tpu.fleet.routing import RoutingPolicy
        from chainermn_tpu.monitor._state import get_event_log

        ps_n = int(e("CHAINERMN_TPU_SERVE_PS_REQUESTS", "9"))
        ps_rng = np.random.RandomState(22)
        ps_shared = ps_rng.randint(1, vocab, prefill_len - 4) \
            .astype(np.int32)
        ps_jobs = [np.concatenate([ps_shared,
                                   ps_rng.randint(1, vocab, 1 + (i % 4))
                                   .astype(np.int32)])
                   for i in range(ps_n)]
        ps_params = int(sum(x.size
                            for x in jax.tree_util.tree_leaves(params)))

        # small blocks so the shared prefix spans MANY trie blocks: the
        # share trigger needs the fleet trie to know >=
        # prefix_share_min_blocks of it, and the fused transfer gets a
        # real multi-block payload. Overridable so CI can pick a bigger
        # block (fewer warmup-bucketed migration programs to compile).
        ps_block = int(e("CHAINERMN_TPU_SERVE_PS_BLOCK", "4"))

        def ps_engine():
            return ServingEngine(
                model, params, n_slots=2,
                prefill_buckets=(4, prefill_len), prefill_batch=1,
                paged=True, kv_block_size=ps_block,
                kv_blocks=6 * (-(-(prefill_len + max_new) // ps_block)),
                cache_len=prefill_len + max_new)

        def ps_fleet(share):
            return FleetRouter(
                [ps_engine() for _ in range(3)],
                policy=RoutingPolicy(max_imbalance=0.0),
                share_prefixes=share, prefix_share_min_blocks=2)

        def ps_run(router):
            assert router.wait_ready(600)
            evs0 = get_event_log().tail(1)
            seq0 = evs0[-1]["i"] if evs0 else -1
            t_submit, t_first, frs = {}, {}, []
            for i, p in enumerate(ps_jobs):
                def cb(tok, _i=i):
                    t_first.setdefault(_i, time.perf_counter())
                t_submit[i] = time.perf_counter()
                frs.append(router.submit(
                    p, max_new, rng=jax.random.PRNGKey(400 + i),
                    stream_cb=cb))
                if i == 0:
                    # the holder serves the system prompt once
                    # BEFORE the burst: sharing targets the steady
                    # state where the prefix is already resident
                    # somewhere, so the burst's misses find a
                    # populated trie to adopt from
                    assert frs[0].wait(300)
            done = all(fr.wait(300) for fr in frs)
            ttfts = [t_first[i] - t_submit[i] for i in range(ps_n)]
            cached = sum(ev.get("cached", 0)
                         for ev in get_event_log().tail()
                         if ev["i"] > seq0
                         and ev["kind"] == "slot_admit")
            rep = router.fleet_report()["kv_reuse"]
            for r in router.replicas:
                assert r.engine.recompiles == {}, "recompiled!"
            return ([list(fr.tokens) for fr in frs], done,
                    float(np.percentile(np.asarray(ttfts), 50)),
                    int(cached), rep)

        ps_router = ps_fleet(False)
        try:
            ps_toks_off, ps_done_off, ps_p50_off, ps_cached_off, _ = \
                ps_run(ps_router)
        finally:
            ps_router.close()
        ps_router = ps_fleet(True)
        try:
            ps_toks_on, ps_done_on, ps_p50_on, ps_cached_on, ps_rep = \
                ps_run(ps_router)
            ps_saved = max(0, ps_cached_on - ps_cached_off)

            # rebalance probe, riding the already-warm ON fleet: a
            # throttled stream keeps one request mid-decode while the
            # router drains it to a peer through the fused path — the
            # stream finishes token-exactly on its new home, nothing
            # lost. (ps_rep was snapshotted above, so the probe's own
            # counters don't leak into the share numbers.)
            rb_prompt = ps_jobs[0]
            rb_ref = ps_router.generate(rb_prompt, max_new,
                                        rng=jax.random.PRNGKey(500),
                                        timeout=300)
            rb_ref_tail = [int(t) for t in rb_ref[len(rb_prompt):]]
            rb_fr = ps_router.submit(
                rb_prompt, max_new, rng=jax.random.PRNGKey(500),
                stream_cb=lambda tok: time.sleep(0.01))
            while not (rb_fr.tokens or rb_fr.finished):
                time.sleep(0.002)
            rb_src = rb_fr.replica_id
            rb_dest_pick = (rb_src + 1) % len(ps_router.replicas)
            rb_ticket = ps_router.rebalance_decode(rb_src, rb_dest_pick)
            rb_moved = (bool(rb_ticket.wait(30))
                        if rb_ticket is not None else False)
            rb_done = rb_fr.wait(300)
            rb_parity = [int(t) for t in rb_fr.tokens] == rb_ref_tail
            rb_dest = rb_fr.replica_id
        finally:
            ps_router.close()

        record["fleet_prefix_share"] = {
            "replicas": 3,
            "requests": ps_n,
            "shared_prefix_tokens": int(len(ps_shared)),
            "ttft_p50_ms_on": round(ps_p50_on * 1e3, 3),
            "ttft_p50_ms_off": round(ps_p50_off * 1e3, 3),
            "ttft_p50_speedup": round(ps_p50_off / max(ps_p50_on, 1e-9),
                                      2),
            "shares": int(ps_rep["shares"]),
            "payload_cache": ps_rep["payload_cache"],
            "prefill_tokens_saved": int(ps_saved),
            "prefill_flops_saved": float(2 * ps_params * ps_saved),
            "token_parity_on_vs_off": ps_toks_on == ps_toks_off,
            "no_request_lost": bool(ps_done_on and ps_done_off),
            "recompiles_after_warmup": 0,
            "rebalance_probe": {
                "moved": bool(rb_moved),
                "src_replica": rb_src,
                "dest_replica": rb_dest,
                "token_parity": bool(rb_parity),
                "no_request_lost": bool(rb_done),
            },
        }
        psr = record["fleet_prefix_share"]
        log(f"fleet prefix share: {ps_n} reqs x3 replicas ttft_p50 "
            f"{psr['ttft_p50_ms_on']}ms (on) vs "
            f"{psr['ttft_p50_ms_off']}ms (off), shares={psr['shares']}, "
            f"tokens_saved={psr['prefill_tokens_saved']}, "
            f"parity={psr['token_parity_on_vs_off']}; rebalance "
            f"moved={psr['rebalance_probe']['moved']} "
            f"parity={psr['rebalance_probe']['token_parity']}")

        from chainermn_tpu.monitor import snapshot as monitor_snapshot

        record["monitor"] = monitor_snapshot()
    except Exception as exc:  # one parseable line, never a bare traceback
        log(f"serving bench failed: {type(exc).__name__}: {exc}")
        record = {
            "metric": "serving_decode_throughput",
            "value": None,
            "unit": "tokens/sec",
            "mode": "serving",
            "error": type(exc).__name__,
            "detail": str(exc)[-500:],
        }
        print(json.dumps(record))
        raise SystemExit(1)
    print(json.dumps(record))
    _scratch_write(record)


def monitor_main() -> None:
    """``bench.py --mode monitor``: telemetry-subsystem smoke cell.

    Proves, in one JSON record, the two monitor acceptance criteria that
    need a live workload: (1) **overhead** — the same compiled LM train
    step timed bare vs through ``monitor.instrument`` (events + metrics +
    recompile tracking), reported as ``overhead_frac`` (<2% is the
    production target; the CI assertion uses a generous bound because
    millisecond CPU steps are noisy); (2) **flight recorder** — a serving
    burst runs with monitoring on, then a simulated hang inside a
    watchdog-armed window must dump the last events (slot admits/retires
    included) + per-device memory stats. The record also embeds the full
    registry ``snapshot`` like every other mode.

    Knobs: ``CHAINERMN_TPU_MONITOR_STEPS`` (timed steps per side, default
    30) and the ``CHAINERMN_TPU_SERVE_*`` sizes shared with serving mode.
    The ``slow``-marked soak variant in tests/test_bench_smoke.py raises
    the step/request counts through these.
    """
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    import io

    import numpy as np

    import jax

    plat = os.environ.get("CHAINERMN_TPU_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    enable_compilation_cache(jax)

    import jax.numpy as jnp
    import optax

    import chainermn_tpu
    from chainermn_tpu import monitor
    from chainermn_tpu.extensions import Watchdog
    from chainermn_tpu.models import TransformerLM
    from chainermn_tpu.serving import FCFSScheduler, ServingEngine
    from chainermn_tpu.training import jit_lm_train_step

    e = os.environ.get
    n_steps = int(e("CHAINERMN_TPU_MONITOR_STEPS", "30"))
    n_slots = int(e("CHAINERMN_TPU_SERVE_SLOTS", "4"))
    n_requests = int(e("CHAINERMN_TPU_SERVE_REQUESTS", "12"))
    prefill_len = int(e("CHAINERMN_TPU_SERVE_PREFILL_LEN", "8"))
    max_new = int(e("CHAINERMN_TPU_SERVE_MAX_NEW", "8"))
    vocab = int(e("CHAINERMN_TPU_SERVE_VOCAB", "64"))
    d_model = int(e("CHAINERMN_TPU_SERVE_DMODEL", "64"))
    n_layers = int(e("CHAINERMN_TPU_SERVE_LAYERS", "2"))
    n_heads = int(e("CHAINERMN_TPU_SERVE_HEADS", "4"))

    devs = _devices_or_fail_fast(jax, mode="monitor",
                                 metric="monitor_smoke",
                                 unit="monitored_steps")
    log(f"monitor smoke: devices={len(devs)} kind={devs[0].device_kind!r} "
        f"steps={n_steps} requests={n_requests}")
    try:
        # ---- overhead: bare jitted step vs instrumented wrapper -------- #
        lm = TransformerLM(vocab_size=vocab, d_model=d_model,
                           n_heads=n_heads, n_layers=n_layers,
                           max_len=prefill_len + max_new)
        comm = chainermn_tpu.create_communicator("tpu")
        tokens = jnp.zeros((8 * max(len(devs), 1), 16), jnp.int32)
        targets = jnp.zeros_like(tokens)
        params = comm.bcast_data(
            lm.init(jax.random.PRNGKey(0), tokens[:1]))
        opt = optax.sgd(0.1)
        opt_state = jax.device_put(opt.init(params), comm.named_sharding())
        bare = jit_lm_train_step(lm, opt, comm, donate=False,
                                 monitored=False)
        mon = monitor.instrument(bare, "lm_train_step")  # same jit cache

        def timed(step, k):
            best = None
            for _ in range(2):  # best-of-2 damps scheduler noise
                t0 = time.perf_counter()
                for _ in range(k):
                    p, s, loss, _ = step(params, opt_state, tokens, targets)
                float(loss)
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            return best

        timed(bare, 3)  # compile + warm both paths (same executable)
        timed(mon, 3)
        t_bare = timed(bare, n_steps)
        t_mon = timed(mon, n_steps)
        overhead = (t_mon - t_bare) / t_bare
        log(f"monitored step overhead: {overhead:+.2%} "
            f"({t_mon / n_steps * 1e3:.3f} vs {t_bare / n_steps * 1e3:.3f} "
            "ms/step)")

        # ---- serving burst + simulated hang -> flight recorder --------- #
        sink = io.StringIO()
        # engine watchdog: genuinely armed around every device call, but
        # sized not to fire on warmup compiles (this cell proves wiring,
        # not hangs); the short-fuse dog below simulates the actual hang
        engine_dog = Watchdog(timeout=120.0, on_timeout="warn", _sink=sink)
        dog = Watchdog(timeout=0.25, on_timeout="warn", _sink=sink)
        eng_params = lm.init(jax.random.PRNGKey(0),
                             jnp.zeros((1, prefill_len), jnp.int32))
        engine = ServingEngine(lm, eng_params, n_slots=n_slots,
                               prefill_len=prefill_len, watchdog=engine_dog)
        sched = FCFSScheduler(engine)
        rng = np.random.RandomState(0)
        for _ in range(n_requests):
            prompt = rng.randint(1, vocab, rng.randint(1, prefill_len + 1))
            sched.submit(prompt.astype(np.int32),
                         int(rng.randint(1, max_new + 1)))
        sched.run_until_idle()
        with dog.step("simulated hang (monitor smoke)"):
            time.sleep(0.6)   # > timeout: watchdog fires and dumps
        flight = sink.getvalue()
        flight_events = sum(
            1 for line in flight.splitlines() if line.startswith("{"))

        # ---- tracing + SLO + HTTP scrape surface ----------------------- #
        # The burst above ran through the default tracer (the scheduler
        # opens a trace per request), so the ring already holds serving
        # span trees; declare a generous TTFT SLO over the live registry,
        # stand the stdlib endpoint up on an ephemeral port, and scrape
        # all four routes the way a Prometheus/Perfetto consumer would.
        from urllib.request import urlopen

        from chainermn_tpu.monitor import http as monitor_http
        from chainermn_tpu.monitor.slo import LatencyObjective, SLOEngine
        from chainermn_tpu.monitor.trace import get_tracer

        tracer = get_tracer()
        serving_traces = tracer.finished(kind="serving")
        slo = SLOEngine()
        slo.add(LatencyObjective("ttft_p99", "serving_ttft_seconds",
                                 threshold_s=30.0, windows=(60.0, 300.0)))
        slo_report = slo.evaluate()
        with monitor_http.serve(port=0, slo=slo) as srv:
            http_block = {"port": srv.port}
            metrics_txt = urlopen(srv.url + "/metrics",
                                  timeout=10).read().decode()
            http_block["metrics_ok"] = "serving_ttft_seconds" in metrics_txt
            tr = json.loads(urlopen(srv.url + "/traces", timeout=10).read())
            trace_events = tr.get("traceEvents", [])
            http_block["trace_events"] = len(trace_events)
            http_block["traces_ok"] = bool(trace_events) and all(
                ev.get("ph") in ("X", "M") and "pid" in ev and "tid" in ev
                for ev in trace_events)
            slo_http = json.loads(urlopen(srv.url + "/slo",
                                          timeout=10).read())
            http_block["slo_ok"] = "ttft_p99" in slo_http
            evs = json.loads(urlopen(srv.url + "/events", timeout=10).read())
            http_block["events_ok"] = bool(evs.get("events"))
        snap = monitor.snapshot()
        steps_counted = sum(
            v for k, v in snap["counters"].items()
            if k.startswith("steps_total"))
        record = {
            "metric": "monitor_smoke",
            "value": steps_counted,
            "unit": "monitored_steps",
            "mode": "monitor",
            "n_chips": len(devs),
            "device_kind": devs[0].device_kind,
            "overhead_frac": round(overhead, 4),
            "step_time_ms": round(t_bare / n_steps * 1e3, 3),
            "watchdog_fired": dog.fired,
            "flight_events_in_dump": flight_events,
            "flight_has_slot_admit": '"kind": "slot_admit"' in flight,
            "flight_has_slot_retire": '"kind": "slot_retire"' in flight,
            "flight_has_memory": "device memory" in flight,
            "serving": sched.metrics.report(),
            "recompiles": engine.compile_counts(),
            "trace": {
                "serving_traces": len(serving_traces),
                "spans_example": ([s.name for s in serving_traces[0].spans]
                                  if serving_traces else []),
            },
            "http": http_block,
            "slo": {k: {"max_burn_rate": v["max_burn_rate"],
                        "compliant": v["compliant"]}
                    for k, v in slo_report.items()},
            "monitor": snap,
        }
    except Exception as exc:  # one parseable line, never a bare traceback
        log(f"monitor smoke failed: {type(exc).__name__}: {exc}")
        record = {
            "metric": "monitor_smoke",
            "value": None,
            "unit": "monitored_steps",
            "mode": "monitor",
            "error": type(exc).__name__,
            "detail": str(exc)[-500:],
        }
        print(json.dumps(record))
        raise SystemExit(1)
    print(json.dumps(record))
    _scratch_write(record)


def resilience_main() -> None:
    """``bench.py --mode resilience``: fault-injection / recovery cell.

    One JSON record proving the resilience loop live, with the numbers the
    ISSUE names: **checkpoint save/restore latency** (the recovery path's
    I/O cost), **MTTR** — wall-clock from an injected crash at a chosen
    training step to the first completed post-resume step — plus a
    **bit-exactness** verdict (the faulted run's final loss must equal an
    uninterrupted reference run's, RNG/iterator state round-tripping
    through the snapshot), and the serving degradation counts
    (rejected / shed / errored / restarts) from a burst driven into a
    bounded queue with an injected engine raise. Embeds the registry
    ``snapshot`` like every other mode.

    Knobs: ``CHAINERMN_TPU_RESIL_STEPS`` (default 16),
    ``CHAINERMN_TPU_RESIL_FAULT_STEP`` (default 9),
    ``CHAINERMN_TPU_RESIL_SAVE_EVERY`` (default 4) and the
    ``CHAINERMN_TPU_SERVE_*`` sizes shared with serving mode.
    """
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    import tempfile

    import numpy as np

    import jax

    plat = os.environ.get("CHAINERMN_TPU_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    enable_compilation_cache(jax)

    import jax.numpy as jnp
    import optax

    import chainermn_tpu
    from chainermn_tpu import monitor
    from chainermn_tpu.models import TransformerLM
    from chainermn_tpu.resilience import FaultInjector, resilient_fit
    from chainermn_tpu.serving import (
        QueueFullError,
        RequestState,
        ServingEngine,
    )
    from chainermn_tpu.training import jit_lm_train_step

    e = os.environ.get
    n_steps = int(e("CHAINERMN_TPU_RESIL_STEPS", "16"))
    fault_step = int(e("CHAINERMN_TPU_RESIL_FAULT_STEP", "9"))
    save_every = int(e("CHAINERMN_TPU_RESIL_SAVE_EVERY", "4"))
    n_slots = int(e("CHAINERMN_TPU_SERVE_SLOTS", "2"))
    prefill_len = int(e("CHAINERMN_TPU_SERVE_PREFILL_LEN", "8"))
    max_new = int(e("CHAINERMN_TPU_SERVE_MAX_NEW", "8"))
    vocab = int(e("CHAINERMN_TPU_SERVE_VOCAB", "64"))
    d_model = int(e("CHAINERMN_TPU_SERVE_DMODEL", "32"))
    n_layers = int(e("CHAINERMN_TPU_SERVE_LAYERS", "1"))
    n_heads = int(e("CHAINERMN_TPU_SERVE_HEADS", "4"))
    seq_len = 16

    devs = _devices_or_fail_fast(jax, mode="resilience",
                                 metric="resilience_mttr", unit="mttr_ms")
    log(f"resilience smoke: devices={len(devs)} "
        f"kind={devs[0].device_kind!r} steps={n_steps} "
        f"fault_step={fault_step}")
    try:
        # ---- auto-resume training: crash at fault_step, recover -------- #
        lm = TransformerLM(vocab_size=vocab, d_model=d_model,
                           n_heads=n_heads, n_layers=n_layers,
                           max_len=seq_len)
        comm = chainermn_tpu.create_communicator("tpu")
        rng = np.random.RandomState(0)
        toks = rng.randint(1, vocab, (64, seq_len)).astype(np.int32)
        tgts = np.roll(toks, -1, axis=1)
        batch = 2 * max(len(devs), 1)
        params0 = comm.bcast_data(
            lm.init(jax.random.PRNGKey(0), jnp.asarray(toks[:1])))
        # multi-node wrapper: grads allreduced before the update, so every
        # device's replica stays bitwise identical — the property that
        # makes a replica-0 snapshot restore bit-exact
        opt = chainermn_tpu.create_multi_node_optimizer(
            optax.sgd(0.1), comm)
        jitted = jit_lm_train_step(lm, opt, comm, donate=False)

        def step_fn(state, batch_idx):
            sel = np.asarray(batch_idx)
            p, s, loss, _ = jitted(state["params"], state["opt"],
                                   jnp.asarray(toks[sel]),
                                   jnp.asarray(tgts[sel]))
            return {"params": p, "opt": s, "loss": float(loss)}

        def init_state():
            return {"params": params0,
                    "opt": jax.device_put(opt.init(params0),
                                          comm.named_sharding()),
                    "loss": None}

        def restore_hook(state):
            # snapshots hold host arrays; put them back on the mesh with
            # the original (replicated) shardings so the resumed step
            # reuses the same executable -> bit-exact trajectory
            return {"params": jax.device_put(state["params"],
                                             comm.named_sharding()),
                    "opt": jax.device_put(state["opt"],
                                          comm.named_sharding()),
                    "loss": state["loss"]}

        def run(path, injector=None):
            ckpt = chainermn_tpu.create_multi_node_checkpointer(
                "bench", comm, path=path)
            it = chainermn_tpu.SerialIterator(
                list(range(len(toks))), batch_size=batch, shuffle=True,
                seed=7)
            if injector is None:
                return resilient_fit(step_fn, init_state(), it, n_steps,
                                     ckpt, save_every=save_every,
                                     restore_hook=restore_hook)
            with injector:
                return resilient_fit(step_fn, init_state(), it, n_steps,
                                     ckpt, save_every=save_every,
                                     restore_hook=restore_hook,
                                     dump_on_failure=False)

        with tempfile.TemporaryDirectory() as ref_dir:
            ref_state, ref_report = run(ref_dir)
        inj = FaultInjector(seed=0)
        inj.arm("trainer.step", kind="raise", after=fault_step, times=1)
        with tempfile.TemporaryDirectory() as crash_dir:
            state, report = run(crash_dir, injector=inj)
        bit_exact = bool(state["loss"] == ref_state["loss"])
        mttr_s = report["mttr_s"][0] if report["mttr_s"] else None
        ck = report["checkpoint_stats"]
        log(f"crash at step {fault_step}: restores={report['restores']} "
            f"mttr={mttr_s:.3f}s save={ck['save'] * 1e3:.1f}ms "
            f"load={ck['load'] * 1e3:.1f}ms bit_exact={bit_exact}")

        # ---- serving degradation burst (deterministic scenario) -------- #
        from chainermn_tpu.serving import FCFSScheduler

        eng_params = lm.init(jax.random.PRNGKey(0),
                             jnp.zeros((1, prefill_len), jnp.int32))
        engine = ServingEngine(lm, eng_params, n_slots=n_slots,
                               prefill_len=prefill_len,
                               cache_len=prefill_len + max_new)
        sched = FCFSScheduler(engine, max_queue=4)

        def prompt():
            return rng.randint(
                1, vocab, rng.randint(1, prefill_len + 1)).astype(np.int32)

        reqs = []
        for _ in range(n_slots):           # occupy every slot
            reqs.append(sched.submit(prompt(), max_new))
        sched.step()
        for _ in range(3):                 # doomed: shed before admission
            reqs.append(sched.submit(prompt(), 2, deadline_s=0.01))
        rejected = 0
        for _ in range(3):                 # overflow the bounded queue
            try:
                reqs.append(sched.submit(prompt(), 2))
            except QueueFullError:
                rejected += 1
        time.sleep(0.05)                   # the doomed deadlines expire
        sinj = FaultInjector(seed=0)
        sinj.arm("serving.decode", kind="raise", times=1)
        with sinj:                         # in-flight fail -> warm restart
            sched.run_until_idle()
        terminal = all(
            r.state in (RequestState.DONE, RequestState.ERRORED,
                        RequestState.CANCELLED) for r in reqs)
        sm = sched.metrics.report()

        snap = monitor.snapshot()
        record = {
            "metric": "resilience_mttr",
            "value": round(mttr_s * 1e3, 3) if mttr_s is not None else None,
            "unit": "ms",
            "mode": "resilience",
            "n_chips": len(devs),
            "device_kind": devs[0].device_kind,
            "bit_exact_resume": bit_exact,
            "checkpoint_save_ms": round(ck["save"] * 1e3, 3),
            "checkpoint_load_ms": round(ck["load"] * 1e3, 3),
            "trainer": {
                "steps": report["steps"],
                "failures": report["failures"],
                "restores": report["restores"],
                "fault_step": fault_step,
                "save_every": save_every,
            },
            "serving": {
                "submitted": len(reqs),
                "rejected": rejected,
                "shed": sm["requests_shed"],
                "errored": sm["requests_errored"],
                "engine_restarts": sm["engine_restarts"],
                "all_terminal": terminal,
            },
            "faults_injected": len(inj.fired_log) + len(sinj.fired_log),
            "monitor": snap,
        }
    except Exception as exc:  # one parseable line, never a bare traceback
        log(f"resilience smoke failed: {type(exc).__name__}: {exc}")
        record = {
            "metric": "resilience_mttr",
            "value": None,
            "unit": "ms",
            "mode": "resilience",
            "error": type(exc).__name__,
            "detail": str(exc)[-500:],
        }
        print(json.dumps(record))
        raise SystemExit(1)
    print(json.dumps(record))
    _scratch_write(record)


def pipeline_main() -> None:
    """``bench.py --mode pipeline``: async hot-loop overlap proof.

    One JSON record demonstrating the ``dataflow`` claim: with a loader
    that takes ``d`` ms per batch, the SYNCHRONOUS loop (draw batch ->
    device_put -> step -> ``float(loss)`` per step) pays ``step + d`` per
    iteration, while the pipelined loop (``DevicePrefetcher`` producer
    thread + ``training.fit`` dispatch-ahead with batched loss fetches)
    pays ~``max(step, d)`` — the loader delay and the H2D transfer hide
    under device compute, and the per-step host sync disappears
    (``loss_fetch_total`` counts one fetch per ``fetch_every`` steps).
    Both loops consume the identical batch stream from identical initial
    state with the SAME compiled executable, so their losses must match
    float-for-float and the executable count stays 1 (zero recompiles
    after warmup). Also measured: async checkpointing's critical-path
    cost (the ``save_async`` enqueue = one device_get) vs the full save
    duration that moved off-thread.

    Knobs: ``CHAINERMN_TPU_PIPE_STEPS`` (default 30),
    ``CHAINERMN_TPU_PIPE_DELAY_MS`` (default: auto, ~1.5x the measured
    bare step), ``CHAINERMN_TPU_PIPE_FETCH_EVERY`` (default 8),
    ``CHAINERMN_TPU_PIPE_DEPTH`` (default 2), plus the
    ``CHAINERMN_TPU_SERVE_*`` model sizes shared with the other modes.
    """
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    import tempfile

    import numpy as np

    import jax

    plat = os.environ.get("CHAINERMN_TPU_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    enable_compilation_cache(jax)

    import jax.numpy as jnp
    import optax

    import chainermn_tpu
    from chainermn_tpu import monitor
    from chainermn_tpu.dataflow import DevicePrefetcher
    from chainermn_tpu.models import TransformerLM
    from chainermn_tpu.monitor import get_registry
    from chainermn_tpu.training import fit, jit_lm_train_step

    e = os.environ.get
    n_steps = int(e("CHAINERMN_TPU_PIPE_STEPS", "30"))
    fetch_every = int(e("CHAINERMN_TPU_PIPE_FETCH_EVERY", "8"))
    depth = int(e("CHAINERMN_TPU_PIPE_DEPTH", "2"))
    delay_env = e("CHAINERMN_TPU_PIPE_DELAY_MS", "")
    seq_len = int(e("CHAINERMN_TPU_PIPE_SEQ_LEN", "16"))
    vocab = int(e("CHAINERMN_TPU_SERVE_VOCAB", "64"))
    d_model = int(e("CHAINERMN_TPU_SERVE_DMODEL", "64"))
    n_layers = int(e("CHAINERMN_TPU_SERVE_LAYERS", "2"))
    n_heads = int(e("CHAINERMN_TPU_SERVE_HEADS", "4"))

    devs = _devices_or_fail_fast(jax, mode="pipeline",
                                 metric="pipeline_overlap_step_time",
                                 unit="ms/step")
    log(f"pipeline bench: devices={len(devs)} kind={devs[0].device_kind!r} "
        f"steps={n_steps} fetch_every={fetch_every} depth={depth}")
    try:
        lm = TransformerLM(vocab_size=vocab, d_model=d_model,
                           n_heads=n_heads, n_layers=n_layers,
                           max_len=seq_len)
        comm = chainermn_tpu.create_communicator("tpu")
        batch = 2 * max(len(devs), 1)
        pool = np.random.RandomState(0).randint(
            1, vocab, (8 * batch, seq_len)).astype(np.int32)
        params0 = comm.bcast_data(
            lm.init(jax.random.PRNGKey(0), jnp.asarray(pool[:1])))
        opt = chainermn_tpu.create_multi_node_optimizer(optax.sgd(0.1), comm)
        # donate=False: the same params/opt arrays seed both loops
        step = jit_lm_train_step(lm, opt, comm, donate=False,
                                 monitored=False)
        data_sharding = comm.named_sharding(*comm.data_spec)

        def fresh():
            return (jax.device_put(params0, comm.named_sharding()),
                    jax.device_put(opt.init(params0),
                                   comm.named_sharding()))

        def batches(delay_s):
            # the injected loader: d seconds of host-side work per batch,
            # deterministic batch sequence (same seed for both loops)
            r = np.random.RandomState(1)
            while True:
                if delay_s:
                    time.sleep(delay_s)
                sel = r.randint(0, len(pool), batch)
                yield pool[sel], np.roll(pool[sel], -1, axis=1)

        def put(b):
            return jax.device_put(
                (jnp.asarray(b[0]), jnp.asarray(b[1])), data_sharding)

        # ---- bare step time (no loader delay, dispatch-ahead) ---------- #
        params, opt_state = fresh()
        gen = batches(0.0)
        for _ in range(3):  # compile + warm
            x, y = put(next(gen))
            params, opt_state, loss, _ = step(params, opt_state, x, y)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(n_steps):
            x, y = put(next(gen))
            params, opt_state, loss, _ = step(params, opt_state, x, y)
        float(loss)  # closing fetch (PERF.md relay-ack hazard)
        bare_ms = (time.perf_counter() - t0) / n_steps * 1e3

        delay_ms = float(delay_env) if delay_env else max(1.5 * bare_ms,
                                                          20.0)
        d = delay_ms / 1e3

        # ---- synchronous loop: step + d per iteration ------------------ #
        params, opt_state = fresh()
        gen = batches(d)
        sync_losses = []
        t0 = time.perf_counter()
        for _ in range(n_steps):
            x, y = put(next(gen))
            params, opt_state, loss, _ = step(params, opt_state, x, y)
            sync_losses.append(float(loss))  # the per-step host sync
        sync_ms = (time.perf_counter() - t0) / n_steps * 1e3

        # ---- pipelined loop: ~max(step, d) per iteration --------------- #
        reg = get_registry()
        c_fetch = reg.counter("loss_fetch_total", {"loop": "pipeline"})
        fetches_before = c_fetch.value
        params, opt_state = fresh()
        pre = DevicePrefetcher(
            batches(d), depth=depth, sharding=data_sharding,
            transform=lambda b: (jnp.asarray(b[0]), jnp.asarray(b[1])),
            name="pipeline")
        # steady state: let the producer fill the queue before the clock
        # starts (the first-fill delay is a one-time cost, paid while the
        # sync loop's FIRST batch would also still be loading)
        fill_deadline = time.perf_counter() + depth * d + 2.0
        while (pre._q.qsize() < depth
               and time.perf_counter() < fill_deadline):
            pre._ensure_started()
            time.sleep(0.005)
        t0 = time.perf_counter()
        params, opt_state, pipe_losses = fit(
            step, params, opt_state, pre, n_steps,
            fetch_every=fetch_every, name="pipeline")
        pipe_ms = (time.perf_counter() - t0) / n_steps * 1e3
        pre.close()
        fetch_events = c_fetch.value - fetches_before

        # ---- async checkpoint: critical-path cost vs moved-off work ---- #
        with tempfile.TemporaryDirectory() as ckdir:
            ck = chainermn_tpu.create_multi_node_checkpointer(
                "pipe", comm, path=ckdir)
            state = {"params": params, "opt": opt_state}
            t0 = time.perf_counter()
            ck.save(state, 1)
            sync_save_ms = (time.perf_counter() - t0) * 1e3
            t0 = time.perf_counter()
            ck.save_async(state, 2)
            enqueue_ms = (time.perf_counter() - t0) * 1e3
            ck.wait_async()
            async_save_ms = ck.stats["save_async"][-1] * 1e3
            ck.finalize()

        max_ideal = max(bare_ms, delay_ms)
        snap = monitor.snapshot()
        h2d = next((v for k, v in snap["histograms"].items()
                    if k.startswith("prefetch_h2d_seconds")
                    and 'name="pipeline"' in k), {})
        record = {
            "metric": "pipeline_overlap_step_time",
            "value": round(pipe_ms, 3),
            "unit": "ms/step",
            "mode": "pipeline",
            "n_chips": len(devs),
            "device_kind": devs[0].device_kind,
            "n_steps": n_steps,
            "fetch_every": fetch_every,
            "prefetch_depth": depth,
            "bare_step_ms": round(bare_ms, 3),
            "loader_delay_ms": round(delay_ms, 3),
            "sync_step_ms": round(sync_ms, 3),
            "pipelined_step_ms": round(pipe_ms, 3),
            "max_step_delay_ms": round(max_ideal, 3),
            # sync/pipelined: how much wall the overlap bought
            "overlap_ratio": round(sync_ms / pipe_ms, 4),
            # max(step,d)/pipelined: 1.0 = perfect overlap (acceptance:
            # pipelined <= 1.15 x max(step, d) in steady state)
            "pipeline_efficiency": round(max_ideal / pipe_ms, 4),
            "within_1p15_of_ideal": bool(pipe_ms <= 1.15 * max_ideal),
            "losses_bit_identical": bool(sync_losses == pipe_losses),
            "loss_fetch_events": int(fetch_events),
            "h2d_ms_p50": round(h2d.get("p50_s", 0.0) * 1e3, 3),
            "async_save_enqueue_ms": round(enqueue_ms, 3),
            "async_save_ms": round(async_save_ms, 3),
            "sync_save_ms": round(sync_save_ms, 3),
            # the jit cache must hold exactly the warmup executable
            "executables": int(step._cache_size()),
            "monitor": snap,
        }
    except Exception as exc:  # one parseable line, never a bare traceback
        log(f"pipeline bench failed: {type(exc).__name__}: {exc}")
        record = {
            "metric": "pipeline_overlap_step_time",
            "value": None,
            "unit": "ms/step",
            "mode": "pipeline",
            "error": type(exc).__name__,
            "detail": str(exc)[-500:],
        }
        print(json.dumps(record))
        raise SystemExit(1)
    print(json.dumps(record))
    _scratch_write(record)


def _failure_record(err_class: str, detail: str, attempts_run: int) -> dict:
    rec = {
        "metric": "resnet50_imagenet_train_throughput",
        "value": None,
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "error": err_class,
        "detail": detail[-500:],
        "attempts": attempts_run,
        "device_kind": None,
    }
    # A wedged tunnel at record time should not make the round's record
    # evidence-free: embed the newest measured run so a value=null record
    # still carries the round's real measurement and when it was taken.
    # Primary source is scripts/last_measured.json, written by
    # _persist_measured at success time — NOT bench_stdout.txt, which a
    # chip_watch.sh-style `> scripts/bench_stdout.txt` redirection
    # truncates at launch (i.e. exactly when the tunnel wedges, that file
    # is empty). The stdout file is kept as a reverse-scan fallback for
    # records that predate _persist_measured, skipping trailing
    # value=null failure lines.
    for path, mode in (( _LAST_MEASURED_PATH, "json"),
                       (os.path.join(os.path.dirname(_LAST_MEASURED_PATH),
                                     "bench_stdout.txt"), "scan")):
        try:
            with open(path) as f:
                lines = f.read().strip().splitlines()
            for line in reversed(lines):
                try:
                    last = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if (isinstance(last, dict) and last.get("value") is not None
                        and "TPU" in str(last.get("device_kind", ""))):
                    rec["last_measured"] = last
                    rec["last_measured_age_s"] = round(
                        time.time() - os.path.getmtime(path), 1
                    )
                    return rec
        except Exception:
            continue
    return rec


_LAST_MEASURED_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "scripts", "last_measured.json"
)


def _persist_measured(json_line: str) -> None:
    """Keep the newest successful TPU measurement in a file no launcher
    redirection can truncate, for _failure_record's evidence embed.

    TPU-only on purpose: the CI smoke test runs this same parent on a tiny
    CPU mesh in the repo cwd, and its record must never displace the
    round's real-chip evidence (it did once — caught when the suite
    overwrote the window-1 record with value=102 img/s, device=cpu)."""
    try:
        rec = json.loads(json_line)
        if (isinstance(rec, dict) and rec.get("value") is not None
                and "TPU" in str(rec.get("device_kind", ""))):
            with open(_LAST_MEASURED_PATH, "w") as f:
                f.write(json_line.strip() + "\n")
    except Exception:
        pass


def _budget_plan(env: dict) -> tuple:
    """(attempts, attempt_timeout_s) for the parent's retry loop.

    Pinned values win. Otherwise the shape depends on the persistent
    cache: a cold conv7 ResNet-50 compile through the axon tunnel runs
    ~11-12 min (measured, round-5 window 1) — LONGER than the default
    720s attempt, so on a fresh /tmp the 5x720 ladder is a guaranteed
    double-TERM (the round-4 record's exact failure). Cold -> spend the
    same total budget as ONE long attempt: ~12 min compile + 50 measured
    steps fits, and the cache makes every later run (retries, the
    driver's next invocation) fast. Warm detection: the cache's entries
    are opaque hashes, so the child drops a headline_<stem>_<per-chip-
    batch>.ok marker beside them after each successful compile that
    demonstrably engaged the cache; warm = the 256 headline rung (or the
    explicitly requested batch) is known-cached (batch 128 compiles in
    27s either way, so the cold single attempt still lands a record
    fast when 256 turns out broken)."""
    attempts = int(env.get("CHAINERMN_TPU_BENCH_ATTEMPTS", "5"))
    attempt_timeout = float(env.get("CHAINERMN_TPU_BENCH_TIMEOUT", "720"))
    if ("CHAINERMN_TPU_BENCH_TIMEOUT" in env
            or "CHAINERMN_TPU_BENCH_ATTEMPTS" in env):
        return attempts, attempt_timeout
    cache_dir = env.get(
        "CHAINERMN_TPU_BENCH_CACHE", "/tmp/chainermn_tpu_jax_cache")
    stem = env.get("CHAINERMN_TPU_BENCH_STEM", "conv7")
    key_batch = int(env.get("CHAINERMN_TPU_BENCH_BATCH", "0")) or 256
    warm = bool(cache_dir) and os.path.exists(
        os.path.join(cache_dir, f"headline_{stem}_{key_batch}.ok"))
    if not warm:
        attempts = 1
        attempt_timeout = float(
            env.get("CHAINERMN_TPU_BENCH_TOTAL_BUDGET", "1500")) - 120.0
        log(f"cold compilation cache: single {attempt_timeout:.0f}s "
            "attempt instead of the retry ladder")
    return attempts, attempt_timeout


def parent_main() -> None:
    delay = float(os.environ.get("CHAINERMN_TPU_BENCH_RETRY_DELAY", "10"))
    # Backend init can HANG (tunnel down) rather than fail fast; a hung child
    # would otherwise make the whole bench silently exceed the driver's
    # budget with no JSON emitted. Timeout covers init + compiles + steps
    # (the sweep's per-child budget is CHAINERMN_TPU_BENCH_CHILD_BUDGET).
    # Defaults deliberately fit well inside the driver's window: round 3's
    # 1800s/attempt + 3600s total outlived it (rc=124, no record). A hung
    # backend that doesn't come up within ~12min per attempt won't come up
    # at 30min either. Cold-cache runs reshape the ladder — see
    # _budget_plan.
    attempts, attempt_timeout = _budget_plan(dict(os.environ))
    # The child's internal sweep deadline must fire BEFORE this parent's
    # attempt timeout, or a healthy child pacing its sweep against a larger
    # default budget gets SIGTERMed mid-sweep and logged as a (phantom)
    # backend hang. Derived per attempt (the timeout shrinks as the total
    # budget drains) unless the caller pinned it explicitly.
    child_budget_pinned = "CHAINERMN_TPU_BENCH_CHILD_BUDGET" in os.environ
    # And a TOTAL cap: a wedged single-tenant tunnel (PERF.md hazard #2)
    # hangs every attempt — unlimited retries would outlive any driver
    # budget and still emit nothing. Stop retrying once the cumulative spend
    # passes the total budget and emit the failure record instead.
    total_budget = float(os.environ.get("CHAINERMN_TPU_BENCH_TOTAL_BUDGET", "1500"))
    t_start = time.time()
    last_tail = ""
    attempts_run = 0

    # Pin the scratch path now and export it so every child of THIS run
    # writes where this parent salvages (see _scratch_path: the pid-scoped
    # default would otherwise differ between parent and child).
    os.environ["CHAINERMN_TPU_BENCH_SCRATCH"] = _scratch_path()
    # Start each run with a clean scratch file: a stale record from an
    # earlier round must never be salvaged as this run's measurement.
    try:
        os.unlink(_scratch_path())
    except OSError:
        pass

    # THE un-losable guarantee: if the driver starts tearing us down
    # (`timeout` sends SIGTERM first), emit the best record we have — a
    # salvaged child measurement beats a failure record beats nothing —
    # *before* the follow-up SIGKILL lands. Budgets above are the first
    # line of defense; this handler is the backstop that round 3 lacked.
    child_box: list = [None]

    def _on_term(signum, frame):
        # Raw os.write only: the signal may land while the main thread is
        # inside the SAME buffered writer (e.g. forwarding child stderr) and
        # a buffered print() here would raise "reentrant call inside
        # BufferedWriter", killing the backstop before it emits anything.
        os.write(2, f"parent received signal {signum}; emitting record\n".encode())
        salvaged = _scratch_salvage()
        if salvaged is not None:
            salvaged["salvaged_on_signal"] = signum
            os.write(1, (json.dumps(salvaged) + "\n").encode())
        else:
            rec = _failure_record(
                "SIGTERM" if signum == signal.SIGTERM else f"signal {signum}",
                last_tail or "driver killed bench before any measurement",
                attempts_run,
            )
            os.write(1, (json.dumps(rec) + "\n").encode())
        child = child_box[0]
        if child is not None and child.poll() is None:
            # Best effort: let the child unwind so the device grant is
            # released (a SIGKILLed lease wedges the single-tenant tunnel,
            # PERF.md hazard #2). The driver's SIGKILL may cut this short.
            child.terminate()
            try:
                child.wait(timeout=20)
            except subprocess.TimeoutExpired:
                pass
        os._exit(0 if salvaged is not None else 1)

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    for i in range(1, attempts + 1):
        remaining = total_budget - (time.time() - t_start)
        if remaining <= 60:
            log(f"bench total budget ({total_budget:.0f}s) exhausted after "
                f"{i - 1} attempts; giving up")
            last_tail = last_tail or "total budget exhausted (tunnel wedged?)"
            break
        attempt_timeout = min(attempt_timeout, remaining)
        if not child_budget_pinned:
            # strictly inside the (possibly just-clamped) attempt timeout:
            # the 90s margin normally, 80% when the margin would over-shrink
            # a small timeout — max() of two values each < attempt_timeout
            os.environ["CHAINERMN_TPU_BENCH_CHILD_BUDGET"] = str(
                max(attempt_timeout - 90.0, attempt_timeout * 0.8)
            )
        attempts_run = i
        popen = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child"],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        child_box[0] = popen
        try:
            stdout_txt, stderr_txt = popen.communicate(timeout=attempt_timeout)
            proc = subprocess.CompletedProcess(
                popen.args, popen.returncode, stdout_txt, stderr_txt
            )
        except subprocess.TimeoutExpired:
            # TERM first, KILL only as a last resort: a SIGKILLed child
            # cannot run its PJRT teardown, and a lease dying un-released
            # wedges the single-tenant tunnel for every later process
            # (PERF.md hazard #2 — observed: one mid-compile SIGKILL took
            # the chip out for hours). SIGTERM lets Python unwind and the
            # client release the device grant.
            log(f"bench attempt {i}/{attempts} timed out after {attempt_timeout:.0f}s")
            popen.terminate()
            try:
                stdout_txt, stderr_txt = popen.communicate(timeout=60)
            except subprocess.TimeoutExpired:
                log("child ignored SIGTERM for 60s; escalating to SIGKILL")
                popen.kill()
                try:
                    # even SIGKILL may not reap a child stuck in
                    # uninterruptible device I/O — bound the wait and abandon
                    # the pipes rather than hang past the total budget with
                    # no failure record emitted
                    stdout_txt, stderr_txt = popen.communicate(timeout=60)
                except subprocess.TimeoutExpired:
                    log("child unreaped after SIGKILL (D-state?); abandoning")
                    stdout_txt, stderr_txt = "", ""
            if stderr_txt:
                sys.stderr.write(stderr_txt)
            # A child can emit its result and then hang in runtime teardown —
            # a measurement in hand beats re-running the whole benchmark.
            for line in reversed((stdout_txt or "").strip().splitlines()):
                try:
                    if json.loads(line).get("metric"):
                        log("child hung after completing; using its result")
                        _persist_measured(line)
                        print(line)
                        return
                except (json.JSONDecodeError, AttributeError):
                    continue
            last_tail = f"TimeoutExpired after {attempt_timeout:.0f}s (backend hang?)"
            if i < attempts and total_budget - (time.time() - t_start) > 60:
                time.sleep(delay)
                delay = min(delay * 2, 120.0)
            continue
        if proc.stderr:  # forward child diagnostics
            sys.stderr.write(proc.stderr)
            sys.stderr.flush()
        out = (proc.stdout or "").strip()
        if proc.returncode == 0 and out:
            # forward the child's final JSON line untouched
            _persist_measured(out.splitlines()[-1])
            print(out.splitlines()[-1])
            return
        last_tail = ((proc.stderr or "") + "\n" + out)[-3000:].strip()
        retryable = proc.returncode != 0 and (
            any(s in last_tail for s in _RETRYABLE) or not last_tail
        )
        budget_left = total_budget - (time.time() - t_start) > 60
        will_retry = retryable and i < attempts and budget_left
        log(f"bench attempt {i}/{attempts} failed (rc={proc.returncode}); "
            f"{'retrying in %.0fs' % delay if will_retry else 'giving up'}")
        if not retryable:
            break
        if will_retry:
            time.sleep(delay)
            delay = min(delay * 2, 120.0)
    # All attempts exhausted. A partial measurement any child persisted to
    # scratch (e.g. headline landed, then the sweep hung) still counts.
    salvaged = _scratch_salvage()
    if salvaged is not None:
        salvaged["salvaged_after_failure"] = True
        line = json.dumps(salvaged)
        _persist_measured(line)
        print(line)
        return
    # Final failure: one parseable JSON record, not a stack trace.
    err_class = next(
        (s for s in _RETRYABLE + ("TimeoutExpired",) if s in last_tail), "unknown"
    )
    print(json.dumps(_failure_record(err_class, last_tail, attempts_run)))
    raise SystemExit(1)


def _cli_mode(argv) -> str:
    """``--mode serving`` / ``--mode monitor`` / ``--mode resilience`` /
    ``--mode pipeline`` / ``--mode=...`` (default: the ResNet training
    benchmark with its retry-parent machinery)."""
    for i, a in enumerate(argv):
        if a == "--mode" and i + 1 < len(argv):
            return argv[i + 1]
        if a.startswith("--mode="):
            return a.split("=", 1)[1]
    return "train"


def main() -> None:
    mode = _cli_mode(sys.argv[1:])
    if mode == "serving":
        serving_main()
    elif mode == "monitor":
        monitor_main()
    elif mode == "resilience":
        resilience_main()
    elif mode == "pipeline":
        pipeline_main()
    elif mode != "train":
        raise SystemExit(
            f"unknown --mode {mode!r} "
            "(train|serving|monitor|resilience|pipeline)")
    elif "--child" in sys.argv:
        # child stdout carries ONLY the JSON record; everything else is stderr
        child_main()
    else:
        parent_main()


if __name__ == "__main__":
    main()
