#!/bin/bash
# Round-5 battery 2: re-measure the flash stack after the storage-dtype MXU
# fix (commit ce1ad92), in priority order:
#   1. flash_tune.py    -> flash_tune.jsonl  (block-size sweep, NEW kernels)
#   2. onchip_flash.py  -> onchip_flash.jsonl (parity w/ highest-prec oracle
#                          + flash-vs-full timing, NEW kernels)
#   3. onchip_lm.py     -> onchip_lm.jsonl   (LM MFU cells, NEW kernels;
#                          includes the 2048-full cell that hit a transient
#                          HTTP 500 in window 1)
#   4. space_to_depth/256 bench retry (window-1 cell died UNAVAILABLE).
# Same wedge protocol as chip_watch.sh (probe between stages, whole-window
# stage gates, one attempt per stage, battery deadline).
set -u
cd /root/repo
LOG=scripts/battery2.log
START=$(date +%s)
BATTERY_DEADLINE=${BATTERY2_DEADLINE:-14400}
echo "$(date +%FT%T) battery2 start (deadline ${BATTERY_DEADLINE}s)" >> "$LOG"

probe() {
  timeout -s TERM 90 python -c "import jax; d=jax.devices(); assert d[0].platform=='tpu', d" >/dev/null 2>&1
}

can_fit() {
  [ $(( BATTERY_DEADLINE - ( $(date +%s) - START ) )) -ge "$1" ]
}

wait_alive() {
  while true; do
    if [ $(( $(date +%s) - START )) -gt "$BATTERY_DEADLINE" ]; then
      echo "$(date +%FT%T) battery2 deadline passed" >> "$LOG"
      return 1
    fi
    if probe; then return 0; fi
    echo "$(date +%FT%T) probe wedged" >> "$LOG"
    sleep 240
  done
}

if wait_alive && can_fit 1500; then
  echo "$(date +%FT%T) CHIP ALIVE — flash_tune" >> "$LOG"
  ( FLASH_TUNE_BUDGET=1300 timeout -k 120 -s TERM 1500 python scripts/flash_tune.py >> "$LOG" 2>&1; \
    echo "$(date +%FT%T) flash_tune rc=$?" >> "$LOG" )
fi

if wait_alive && can_fit 1700; then
  echo "$(date +%FT%T) CHIP ALIVE — onchip_flash (post-fix)" >> "$LOG"
  ( ONCHIP_FLASH_BUDGET=1500 timeout -k 120 -s TERM 1700 python scripts/onchip_flash.py >> "$LOG" 2>&1; \
    echo "$(date +%FT%T) onchip_flash rc=$?" >> "$LOG" )
fi

if wait_alive && can_fit 1700; then
  echo "$(date +%FT%T) CHIP ALIVE — onchip_lm (post-fix)" >> "$LOG"
  ( ONCHIP_LM_BUDGET=1500 timeout -k 120 -s TERM 1700 python scripts/onchip_lm.py >> "$LOG" 2>&1; \
    echo "$(date +%FT%T) onchip_lm rc=$?" >> "$LOG" )
fi

if wait_alive && can_fit 2000; then
  echo "$(date +%FT%T) CHIP ALIVE — space_to_depth/256 retry" >> "$LOG"
  ( CHAINERMN_TPU_BENCH_STEM=space_to_depth CHAINERMN_TPU_BENCH_BATCH=256 \
    CHAINERMN_TPU_BENCH_SWEEP=0 CHAINERMN_TPU_BENCH_STEPS=50 \
    CHAINERMN_TPU_BENCH_ATTEMPTS=1 CHAINERMN_TPU_BENCH_TIMEOUT=1800 \
    CHAINERMN_TPU_BENCH_TOTAL_BUDGET=1860 \
    timeout -k 120 -s TERM 2000 python bench.py > scripts/s2d_retry.json 2>> "$LOG"; \
    echo "$(date +%FT%T) s2d retry rc=$?" >> "$LOG" )
fi
echo "$(date +%FT%T) battery2 done" >> "$LOG"
