#!/usr/bin/env python
"""AOT bytes/FLOPs roofline for the on-chip LM cells (round-5 diagnosis).

The window-1 LM measurement (scripts/onchip_lm.jsonl) came in at 13.9%
analytic MFU at T=2048 B=8 — and at that shape attention is ~1% of the
step FLOPs, so the matmul tower itself was slow. First-principles HBM
estimates (f32 logits ~2 GB, optimizer state ~5 GB, activations ~8 GB)
do not add up to the 672 ms measured, so this script asks the compiler:
AOT-compile the EXACT ``jit_lm_train_step`` program for the onchip_lm
cell shapes against an abstract v5e and read its own cost accounting —
FLOPs, HBM bytes, arithmetic intensity, roofline ms, MFU ceiling —
the same method that resolved the ResNet MFU question in round 4
(PERF.md "Where the time goes").

Run chip-free (forces the CPU backend for eager ops; the TPU compiler
is reached through the AOT lowering path only). NOTE the axon
remote-compile helper serves AOT compiles too and wedges together with
the device lease — run under a timeout and treat a hang as "service
wedged", not as a bug here.

Appends one record per cell to scripts/lm_roofline_aot.jsonl.
"""

import json
import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(_HERE, "lm_roofline_aot.jsonl")

PEAK_FLOPS = 197e12   # v5e bf16
HBM_GBPS = 819e9

# (seq_len, batch, attention, remat) — the onchip_lm cells plus the B=16
# T=2048 remat probe (token-batch lever; matches onchip_lm's cell: the
# measured answers were ceiling 52% at B=8, 79% at B=16+remat/12.7 GB,
# 98.6% at B=32+remat but 18.8 GB peak = OOM, full attention at B=8
# 27.3 GB = cannot compile at all).
CELLS = [
    (2048, 8, "flash", False),
    (2048, 8, "full", False),
    (8192, 2, "flash", False),
    (2048, 16, "flash", True),
    (2048, 32, "flash", True, True),   # fused chunked CE: the champion
]
# Override, e.g. LM_ROOFLINE_CELLS='[[2048,16,"flash",true]]'
if os.environ.get("LM_ROOFLINE_CELLS"):
    CELLS = [tuple(c) for c in json.loads(os.environ["LM_ROOFLINE_CELLS"])]


def emit(rec):
    rec["t"] = round(time.time(), 1)
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)


def main():
    sys.path.insert(0, os.path.dirname(_HERE))
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import chainermn_tpu
    from chainermn_tpu.models import TransformerLM
    from chainermn_tpu.training import jit_lm_train_step

    topo = topologies.get_topology_desc("v5e:2x2", "tpu")
    mesh = Mesh(np.array(topo.devices[:1]), ("mn",))
    repl = NamedSharding(mesh, P())
    emit({"test": "target", "device_kind": topo.devices[0].device_kind})

    vocab, d_model, n_layers = 32768, 1024, 12
    n_heads = d_model // 64

    comm = chainermn_tpu.create_communicator("tpu", mesh=mesh)
    opt = chainermn_tpu.create_multi_node_optimizer(optax.adamw(3e-4), comm)

    for cell in CELLS:
        t_len, batch, attn, use_remat = cell[:4]
        fused = bool(cell[4]) if len(cell) > 4 else False
        label = attn + ("+remat" if use_remat else "") + (
            "+fused" if fused else "")
        rec = {"cell": [t_len, batch, label], "seq_len": t_len,
               "batch": batch, "attention": attn, "remat": use_remat,
               "fused_ce": fused}
        t0 = time.time()
        try:
            model = TransformerLM(
                vocab_size=vocab, d_model=d_model, n_heads=n_heads,
                n_layers=n_layers, max_len=max(t_len, 2048),
                attention=attn, compute_dtype=jnp.bfloat16,
                remat=use_remat)
            step = jit_lm_train_step(model, opt, comm, donate=False,
                                     fused_ce=fused)

            var_shapes = jax.eval_shape(
                lambda k: model.init(k, jnp.zeros((1, 8), jnp.int32)),
                jax.ShapeDtypeStruct((2,), jnp.uint32))
            to_aval = lambda t: jax.tree_util.tree_map(
                lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype,
                                               sharding=repl), t)
            variables = to_aval(var_shapes)
            opt_state = to_aval(jax.eval_shape(opt.init, var_shapes))
            tok = jax.ShapeDtypeStruct((batch, t_len), jnp.int32,
                                       sharding=repl)

            compiled = step.lower(variables, opt_state, tok, tok).compile()
            rec["compile_s"] = round(time.time() - t0, 1)
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0]
            flops = float(ca.get("flops", 0.0))
            byts = float(ca.get("bytes accessed", 0.0))
            rec["flops"] = flops
            rec["hbm_bytes"] = byts
            rec["arith_intensity"] = round(flops / byts, 1) if byts else None
            t_comp = flops / PEAK_FLOPS
            t_mem = byts / HBM_GBPS
            rec["bound"] = "compute" if t_comp > t_mem else "memory"
            roof_s = max(t_comp, t_mem)
            rec["roofline_ms"] = round(roof_s * 1e3, 2)
            rec["mfu_ceiling"] = round(flops / roof_s / PEAK_FLOPS, 4)
            # token-normalized view for cross-cell comparison
            rec["roofline_tokens_per_sec"] = round(batch * t_len / roof_s, 1)
            try:
                ma = compiled.memory_analysis()
                rec["peak_hbm_gb"] = round(
                    (ma.temp_size_in_bytes + ma.argument_size_in_bytes
                     + ma.output_size_in_bytes) / 2**30, 2)
            except Exception:
                pass
        except Exception as e:
            rec["error"] = f"{type(e).__name__}: {e}"[:300]
        rec["wall_s"] = round(time.time() - t0, 1)
        emit(rec)


if __name__ == "__main__":
    main()
