#!/usr/bin/env python
"""Chip-free AOT evidence for the fused paged-decode kernel (ISSUE 14).

Lowers + compiles ``paged_attend`` against the real TPU compiler for an
abstract v5e target across the serving decode family — S=1 per-token
decode, the speculative verify window (S=k+1), both ``kv_quant`` modes,
and a serving-sized store — recording Mosaic lowering success and the
executable's peak-bytes analysis per cell. The PERF.md discipline: a
kernel claim that "lowers and fits" must be machine-checked on every
kernel change without burning a chip window; the measured tokens/s
numbers come from the driver's real-chip ``bench.py --mode serving``
run, which this artifact de-risks.

Emits one JSON record per cell to scripts/aot_paged_kernel.jsonl.
"""

import json
import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
OUT = os.path.join(_HERE, "aot_paged_kernel.jsonl")


def emit(rec):
    rec["t"] = round(time.time(), 1)
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")  # host only; target abstract

    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import topologies
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from chainermn_tpu.parallel import paged_kernel as pk

    # smallest valid v5e topology is 2x2; the kernel is a single-device
    # program, so the call is wrapped in a fully-replicated shard_map —
    # every chip runs the complete per-chip kernel (Mosaic calls cannot
    # be auto-partitioned outside shard_map)
    topo = topologies.get_topology_desc("v5e:2x2", "tpu")
    mesh = Mesh(np.array(topo.devices).reshape(4), ("replica",))
    repl = NamedSharding(mesh, P())

    # serving-shaped cells: (label, B, S, H, D, block_size, max_blocks)
    # — a 7B-ish decode config and the bench harness's small config,
    # each at S=1 (decode / decode-window body) and S=7 (k=6 verify)
    CELLS = [
        ("7b_decode", 16, 1, 32, 128, 16, 128),
        ("7b_verify_k6", 16, 7, 32, 128, 16, 128),
        ("bench_decode", 12, 1, 4, 16, 8, 8),
        ("bench_verify_k6", 12, 7, 4, 16, 8, 8),
    ]

    for label, b, s, h, d, bs, m in CELLS:
        for quant in ("none", "int8"):
            n_blocks = b * m + 1
            kv_dtype = jnp.int8 if quant == "int8" else jnp.bfloat16
            avals = [
                jax.ShapeDtypeStruct((b, s, h, d), jnp.bfloat16,
                                     sharding=repl),
                jax.ShapeDtypeStruct((n_blocks, bs, h, d), kv_dtype,
                                     sharding=repl),
                jax.ShapeDtypeStruct((n_blocks, bs, h, d), kv_dtype,
                                     sharding=repl),
                jax.ShapeDtypeStruct((b, m), jnp.int32, sharding=repl),
                jax.ShapeDtypeStruct((b,), jnp.int32, sharding=repl),
            ]
            if quant == "int8":
                avals += [jax.ShapeDtypeStruct((n_blocks, bs, h),
                                               jnp.float32, sharding=repl)] * 2

            def fn(q, sk, sv, table, lengths, *scales):
                def body(q, sk, sv, table, lengths, *scales):
                    kw = {}
                    if scales:
                        kw = {"k_scale": scales[0], "v_scale": scales[1]}
                    return pk.paged_attend(q, sk, sv, table, lengths,
                                           interpret=False, **kw)

                return shard_map(
                    body, mesh=mesh, in_specs=(P(),) * len(avals),
                    out_specs=P(), check_rep=False,
                )(q, sk, sv, table, lengths, *scales)

            rec = {"cell": label, "kv_quant": quant, "batch": b,
                   "window": s, "heads": h, "head_dim": d,
                   "block_size": bs, "max_blocks": m}
            t0 = time.time()
            try:
                c = jax.jit(fn).lower(*avals).compile()
                rec["ok"] = True
                try:
                    mem = c.memory_analysis()
                    rec["peak_hbm_mb"] = round(
                        (mem.temp_size_in_bytes
                         + mem.argument_size_in_bytes
                         + mem.output_size_in_bytes) / 2**20, 2)
                except Exception:
                    pass
            except Exception as e:
                rec["ok"] = False
                rec["error"] = f"{type(e).__name__}: {e}"[:300]
            rec["compile_s"] = round(time.time() - t0, 1)
            emit(rec)
    emit({"done": True})


if __name__ == "__main__":
    main()
