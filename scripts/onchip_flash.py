#!/usr/bin/env python
"""On-chip proof of the Pallas flash kernel stack (VERDICT r4 missing #2).

Every CPU test runs the kernels in interpret mode; this script runs them
COMPILED on the real TPU and records:

  1. fwd parity:  flash_attention vs full_attention (causal + non-causal,
     bf16 and f32), max abs error;
  2. bwd parity:  grads of a scalar loss through both paths (dq/dk/dv);
  3. offset-causal parity: traced q_offset/k_offset path (the ring's
     contract) vs a sliced full-attention oracle;
  4. ring_flash + zigzag_flash composition: one shard_map step on a
     1-device mesh (ppermute is identity at world 1, but the kernels and
     the ring-level custom VJP lower and execute compiled);
  5. flash-vs-full wall-clock at T in {2048, 4096, 8192} fwd+bwd — the
     measured counterpart of the AOT 4.3x prediction (PERF.md round 4);
  6. flash-only long-context cells at T in {16384, 32768} — sizes where
     full attention cannot materialize scores and which only compile at
     all after the round-5 kernel grid restructure (context ceiling
     8k -> 128k, PERF.md).

Appends one JSON record per result to scripts/onchip_flash.jsonl the moment
it lands (wedge protocol: partial evidence must survive a teardown).
Exits 0 with a "skipped" record if no TPU is attached.
"""

import functools
import json
import os
import signal
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root (run from anywhere)
OUT = os.path.join(_HERE, "onchip_flash.jsonl")

from bench import enable_compilation_cache  # battery-wide compile cache


def emit(rec):
    rec["t"] = round(time.time(), 1)
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)


def time_grad_step(fn, q, k, v, n):
    """ms/step for jit(grad(sum fn^2)) — warm, enqueue n, close with a
    device->host FETCH (tunnel-safe; see bench.py's note on
    block_until_ready through the relay). One home for the timing idiom so
    every cell measures identically (flash_tune.py imports it for exactly
    that reason — the cross-file ratios only mean something if both files
    time the same way)."""
    import jax
    import jax.numpy as jnp

    def loss(q, k, v):
        return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    g = step(q, k, v)  # compile + warm
    float(jnp.sum(g[0].astype(jnp.float32)))
    t0 = time.time()
    for _ in range(n):
        g = step(q, k, v)
    float(jnp.sum(g[0].astype(jnp.float32)))
    return round((time.time() - t0) / n * 1e3, 3)


def main():
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    deadline = time.time() + float(os.environ.get("ONCHIP_FLASH_BUDGET", "780"))

    import jax

    # Testing hook (same as bench.py): the container's sitecustomize
    # force-registers the axon TPU platform; config update is the only
    # reliable override, JAX_PLATFORMS alone is not.
    plat = os.environ.get("CHAINERMN_TPU_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    enable_compilation_cache(jax)

    import jax.numpy as jnp
    import numpy as np

    devs = jax.devices()
    if devs[0].platform != "tpu":
        emit({"test": "platform", "skipped": f"no TPU ({devs[0].platform})"})
        return
    emit({"test": "platform", "device_kind": devs[0].device_kind})

    from chainermn_tpu.ops.flash_attention import flash_attention
    from chainermn_tpu.parallel.sequence import full_attention

    rng = jax.random.PRNGKey(0)

    def mk(b, t, h, d, dtype):
        ks = jax.random.split(rng, 3)
        return tuple(jax.random.normal(k, (b, t, h, d), dtype) for k in ks)

    # ---- 1+2: fwd + bwd parity, compiled ------------------------------- #
    # The oracle einsums run at precision="highest": at the TPU's DEFAULT
    # precision an "f32" einsum rounds its operands through bf16 passes
    # (~1e-3 abs error), which in the first round-5 window dominated the
    # comparison and flagged the f32 cells ok=false against a 4.5e-4 bar —
    # the error was the oracle's, not the kernel's. f32 tolerances assume a
    # BF16_3X-or-better kernel dot (true f32 inputs are never pre-rounded
    # in the kernel; only the Mosaic dot decomposition contributes).
    for dtype, tol_o, tol_g in ((jnp.float32, 1e-4, 1e-3),
                                (jnp.bfloat16, 2e-2, 8e-2)):
        for causal in (False, True):
            if time.time() > deadline:
                emit({"test": "parity", "dtype": str(dtype.__name__),
                      "causal": causal, "skipped": "budget"})
                continue
            b, t, h, d = 2, 512, 4, 64
            q, k, v = mk(b, t, h, d, dtype)

            def loss_flash(q, k, v):
                o = flash_attention(q, k, v, causal=causal, interpret=False)
                return jnp.sum(o.astype(jnp.float32) ** 2)

            def loss_full(q, k, v):
                o = full_attention(q, k, v, causal=causal,
                                   precision="highest")
                return jnp.sum(o.astype(jnp.float32) ** 2)

            t0 = time.time()
            o_fl = jax.jit(functools.partial(
                flash_attention, causal=causal, interpret=False))(q, k, v)
            o_fu = jax.jit(functools.partial(
                full_attention, causal=causal, precision="highest"))(q, k, v)
            g_fl = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
            g_fu = jax.jit(jax.grad(loss_full, argnums=(0, 1, 2)))(q, k, v)
            err_o = float(jnp.max(jnp.abs(o_fl.astype(jnp.float32)
                                          - o_fu.astype(jnp.float32))))
            # grads scale with T; compare relative to the oracle's magnitude
            errs_g = []
            for a, bb in zip(g_fl, g_fu):
                ref = float(jnp.max(jnp.abs(bb.astype(jnp.float32)))) or 1.0
                errs_g.append(float(jnp.max(jnp.abs(
                    a.astype(jnp.float32) - bb.astype(jnp.float32)))) / ref)
            emit({
                "test": "parity", "dtype": str(dtype.__name__),
                "causal": causal, "shape": [b, t, h, d],
                "max_abs_err_out": err_o,
                "max_rel_err_grads": max(errs_g),
                "ok": bool(err_o < tol_o * t ** 0.5
                           and max(errs_g) < tol_g),
                "wall_s": round(time.time() - t0, 1),
            })

    # ---- 3: offset-causal (ring contract) ------------------------------ #
    if time.time() < deadline:
        t0 = time.time()
        b, t, h, d = 1, 1024, 2, 64
        q, k, v = mk(b, t, h, d, jnp.float32)
        # second half of q attends to ALL of k with global offsets: oracle is
        # rows [512:] of full causal attention over the whole sequence
        q_hi = q[:, 512:]

        @jax.jit
        def shard(q_hi, k, v):
            return flash_attention(q_hi, k, v, causal=True, q_offset=512,
                                   k_offset=0, interpret=False)

        o_shard = shard(q_hi, k, v)
        o_oracle = jax.jit(functools.partial(full_attention, causal=True,
                                             precision="highest"))(
            q, k, v)[:, 512:]
        err = float(jnp.max(jnp.abs(o_shard - o_oracle)))
        emit({"test": "offset_causal", "max_abs_err": err,
              "ok": bool(err < 1e-3), "wall_s": round(time.time() - t0, 1)})

    # ---- 4: ring/zigzag composition on a 1-device mesh ----------------- #
    if time.time() < deadline:
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        from chainermn_tpu.parallel.sequence import (
            ring_flash_attention, zigzag_flash_attention)

        mesh = Mesh(np.array(devs[:1]), ("sp",))
        b, t, h, d = 1, 1024, 2, 64
        q, k, v = mk(b, t, h, d, jnp.float32)
        oracle = jax.jit(functools.partial(full_attention, causal=True,
                                           precision="highest"))(
            q, k, v)
        for name, fn in (("ring_flash", ring_flash_attention),
                         ("zigzag_flash", zigzag_flash_attention)):
            t0 = time.time()
            try:
                def step(q, k, v):
                    def inner(q, k, v):
                        return fn(q, k, v, "sp", causal=True)
                    return shard_map(
                        inner, mesh=mesh,
                        in_specs=(P(None, "sp"),) * 3,
                        out_specs=P(None, "sp"))(q, k, v)

                def loss(q, k, v):
                    return jnp.sum(step(q, k, v) ** 2)

                with mesh:
                    o = jax.jit(step)(q, k, v)
                    g = jax.jit(jax.grad(loss))(q, k, v)
                err = float(jnp.max(jnp.abs(o - oracle)))
                emit({"test": f"{name}_world1", "max_abs_err_vs_full": err,
                      "grad_finite": bool(jnp.all(jnp.isfinite(g))),
                      "ok": bool(err < 1e-3),
                      "wall_s": round(time.time() - t0, 1)})
            except Exception as e:
                emit({"test": f"{name}_world1",
                      "error": f"{type(e).__name__}: {e}"[:400],
                      "wall_s": round(time.time() - t0, 1)})

    # ---- 5: flash vs full wall-clock (fwd+bwd), bf16 ------------------- #
    for t_len in (2048, 4096, 8192):
        if time.time() > deadline:
            emit({"test": "timing", "seq_len": t_len, "skipped": "budget"})
            continue
        b, h, d = 1, 8, 64
        q, k, v = mk(b, t_len, h, d, jnp.bfloat16)
        rec = {"test": "timing", "seq_len": t_len, "shape": [b, t_len, h, d]}
        for name, fn in (
            ("flash", functools.partial(flash_attention, causal=True,
                                        interpret=False)),
            ("full", functools.partial(full_attention, causal=True)),
        ):
            try:
                rec[f"{name}_ms"] = time_grad_step(fn, q, k, v, n=20)
            except Exception as e:
                rec[f"{name}_error"] = f"{type(e).__name__}: {e}"[:300]
        if "flash_ms" in rec and "full_ms" in rec:
            rec["full_over_flash"] = round(rec["full_ms"] / rec["flash_ms"], 3)
        emit(rec)

    # ---- 6: flash-only long-context (post-restructure capability) ------ #
    for t_len in (16384, 32768):
        if time.time() > deadline:
            emit({"test": "timing_long", "seq_len": t_len,
                  "skipped": "budget"})
            continue
        b, h, d = 1, 8, 64
        q, k, v = mk(b, t_len, h, d, jnp.bfloat16)
        rec = {"test": "timing_long", "seq_len": t_len,
               "shape": [b, t_len, h, d]}
        try:
            rec["flash_ms"] = time_grad_step(
                functools.partial(flash_attention, causal=True,
                                  interpret=False), q, k, v, n=10)
            # causal fwd+bwd FLOPs per (b,h): fwd = 2 matmuls x (T^2/2
            # visible pairs) x d x 2 FLOP/MAC = 2*T^2*d; bwd ~ 2.5x fwd
            # (5 matmuls) -> total ~ 7*T^2*d. Same FLOP (not MAC)
            # convention as bench.py / PERF.md vs the 197 TFLOP/s peak.
            flops = 7.0 * b * h * t_len * t_len * d
            rec["achieved_tflops"] = round(
                flops / (rec["flash_ms"] / 1e3) / 1e12, 2)
        except Exception as e:
            rec["error"] = f"{type(e).__name__}: {e}"[:300]
        emit(rec)

    emit({"test": "done"})


if __name__ == "__main__":
    main()
