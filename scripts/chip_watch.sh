#!/bin/bash
# Chip watcher (round 5): probe the TPU on a timer; the FIRST time it responds,
# run the full measurement battery in that window, in priority order:
#   1. bench.py            -> scripts/bench_stdout.txt (headline MFU record)
#   2. onchip_flash.py     -> scripts/onchip_flash.jsonl (Pallas compiled parity)
#   3. mfu_sweep.py        -> scripts/mfu_sweep.jsonl (batch/strategy sweep)
# Wedge protocol (PERF.md): TERM-capped probes, never KILL first; keep probing
# all round. Timeout budgets are consistent top-down: each wrapper timeout
# exceeds its child's internal budget so the child always winds down first
# and releases the single-tenant device lease (mfu_sweep.py forwards TERM to
# its running bench cell for the same reason). Writes status lines to
# scripts/chip_watch.log.
set -u
cd /root/repo
LOG=scripts/chip_watch.log
echo "$(date +%FT%T) chip_watch start" >> "$LOG"
while true; do
  timeout -s TERM 90 python -c "import jax; d=jax.devices(); assert d[0].platform=='tpu', d" >/dev/null 2>&1
  rc=$?
  if [ $rc -eq 0 ]; then
    echo "$(date +%FT%T) CHIP ALIVE — running battery" >> "$LOG"
    touch scripts/.chip_alive
    # bench.py: internal total budget 1500s (its own parent enforces it);
    # wrapper adds headroom so the internal deadline always fires first.
    ( timeout -s TERM 1700 python bench.py > scripts/bench_stdout.txt 2> scripts/bench_stderr.txt; \
      echo "$(date +%FT%T) bench rc=$?" >> "$LOG" )
    # onchip flash battery BEFORE the sweep: it is the round-5 evidence
    # the verdict asked for and fits a short window
    ( ONCHIP_FLASH_BUDGET=780 timeout -s TERM 900 python scripts/onchip_flash.py >> "$LOG" 2>&1; \
      echo "$(date +%FT%T) onchip_flash rc=$?" >> "$LOG" )
    # sweep: capped to the 3 highest-value cells (512/256/space_to_depth)
    # so a late-opening chip window cannot leave a sweep running into the
    # driver's own round-end bench on the single-tenant tunnel. 1500s/cell
    # (a contended conv7 compile has exceeded 1200s — PERF.md); wrapper =
    # 3*(1500 + ~180 teardown) + slack.
    ( MFU_SWEEP_CELL_TIMEOUT=1500 MFU_SWEEP_MAX_CELLS=3 \
      timeout -s TERM 5400 python scripts/mfu_sweep.py >> "$LOG" 2>&1; \
      echo "$(date +%FT%T) sweep rc=$?" >> "$LOG" )
    echo "$(date +%FT%T) battery done" >> "$LOG"
    exit 0
  fi
  echo "$(date +%FT%T) probe rc=$rc (wedged)" >> "$LOG"
  sleep 420
done
