#!/bin/bash
# Chip watcher (round 5): probe the TPU on a timer; the FIRST time it responds,
# run the full measurement battery in that window, in priority order:
#   1. bench.py            -> scripts/bench_stdout.txt (headline MFU record)
#   2. mfu_sweep.py        -> scripts/mfu_sweep.jsonl (batch/strategy sweep)
#   3. onchip_flash.py     -> scripts/onchip_flash.jsonl (Pallas compiled parity)
# Wedge protocol (PERF.md): TERM-capped probes, never KILL first; keep probing
# all round. Timeout budgets are consistent top-down: each wrapper timeout
# exceeds its child's internal budget so the child always winds down first
# and releases the single-tenant device lease (mfu_sweep.py forwards TERM to
# its running bench cell for the same reason). Writes status lines to
# scripts/chip_watch.log.
set -u
cd /root/repo
LOG=scripts/chip_watch.log
echo "$(date +%FT%T) chip_watch start" >> "$LOG"
while true; do
  timeout -s TERM 90 python -c "import jax; d=jax.devices(); assert d[0].platform=='tpu', d" >/dev/null 2>&1
  rc=$?
  if [ $rc -eq 0 ]; then
    echo "$(date +%FT%T) CHIP ALIVE — running battery" >> "$LOG"
    touch scripts/.chip_alive
    # bench.py: internal total budget 1500s (its own parent enforces it);
    # wrapper adds headroom so the internal deadline always fires first.
    ( timeout -s TERM 1700 python bench.py > scripts/bench_stdout.txt 2> scripts/bench_stderr.txt; \
      echo "$(date +%FT%T) bench rc=$?" >> "$LOG" )
    # sweep: 5 cells x 1500s/cell max; results append per-cell so a timeout
    # loses only remaining cells. Wrapper = 5*(1500 + ~180 teardown: bench's
    # TERM wait + KILL wait + interpreter startup) + slack, so even five
    # wedged cells exit on their own before this TERM lands.
    ( MFU_SWEEP_CELL_TIMEOUT=1500 timeout -s TERM 8700 python scripts/mfu_sweep.py >> "$LOG" 2>&1; \
      echo "$(date +%FT%T) sweep rc=$?" >> "$LOG" )
    ( ONCHIP_FLASH_BUDGET=780 timeout -s TERM 900 python scripts/onchip_flash.py >> "$LOG" 2>&1; \
      echo "$(date +%FT%T) onchip_flash rc=$?" >> "$LOG" )
    echo "$(date +%FT%T) battery done" >> "$LOG"
    exit 0
  fi
  echo "$(date +%FT%T) probe rc=$rc (wedged)" >> "$LOG"
  sleep 420
done
