#!/bin/bash
# Chip watcher (round 5, rev 2): probe the TPU on a timer; the FIRST time it
# responds, run the measurement battery in that window, in priority order:
#   1. bench.py            -> scripts/bench_stdout.txt (headline MFU record)
#   2. onchip_flash.py     -> scripts/onchip_flash.jsonl (Pallas compiled parity)
#   3. onchip_lm.py        -> scripts/onchip_lm.jsonl (LM train MFU, flash vs full)
#   4. mfu_sweep.py        -> scripts/mfu_sweep.jsonl (batch/strategy sweep)
#
# Rev-2 budget lesson (2026-07-31, the first live chip window in 3 rounds):
# a cold conv7 ResNet-50 compile through the axon tunnel takes >11 min —
# longer than the 720s/attempt the rev-1 battery allowed. Both attempts were
# TERMed mid-compile, ignored TERM (main thread blocked in the remote-compile
# C call, so the SystemExit handler never ran), got SIGKILLed, and the orphaned
# lease wedged the tunnel for the NEXT stage — the exact hazard-#2 spiral the
# budgets were meant to avoid. Rev 2 therefore gives bench ONE attempt with a
# 2400s window (compile ~12 min + 50 measured steps fits several times over),
# relies on the persistent compilation cache (bench.py) to make any LATER run
# nearly compile-free, probes the chip between stages so a stage never
# inherits a wedged tunnel from its predecessor, and gives the WHOLE battery
# a deadline (default 6h) so a long wedge cannot leave a stage running into
# the driver's own round-end bench on the single-tenant tunnel.
set -u
cd /root/repo
LOG=scripts/chip_watch.log
START=$(date +%s)
BATTERY_DEADLINE=${CHIP_WATCH_DEADLINE:-21600}   # seconds from start
echo "$(date +%FT%T) chip_watch(rev2) start (deadline ${BATTERY_DEADLINE}s)" >> "$LOG"

probe() {
  timeout -s TERM 90 python -c "import jax; d=jax.devices(); assert d[0].platform=='tpu', d" >/dev/null 2>&1
}

can_fit() {
  # A stage starts only if its ENTIRE default window fits before the battery
  # deadline: a clamped/partial window would TERM a child mid-remote-compile
  # (un-preemptable; the follow-up KILL orphans the lease), and a stage
  # running past the deadline collides with the driver's round-end bench.
  [ $(( BATTERY_DEADLINE - ( $(date +%s) - START ) )) -ge "$1" ]
}

wait_alive() {
  # Probe until the chip responds; single-tenant leases clear in minutes.
  # Returns 1 (skip remaining stages) once the battery deadline passes.
  while true; do
    if [ $(( $(date +%s) - START )) -gt "$BATTERY_DEADLINE" ]; then
      echo "$(date +%FT%T) battery deadline passed; skipping remaining stages" >> "$LOG"
      return 1
    fi
    if probe; then return 0; fi
    echo "$(date +%FT%T) probe wedged" >> "$LOG"
    sleep 240
  done
}

if wait_alive && can_fit 2700; then
  echo "$(date +%FT%T) CHIP ALIVE — bench (one 2400s attempt)" >> "$LOG"
  touch scripts/.chip_alive
  ( CHAINERMN_TPU_BENCH_ATTEMPTS=1 \
    CHAINERMN_TPU_BENCH_TIMEOUT=2400 \
    CHAINERMN_TPU_BENCH_TOTAL_BUDGET=2500 \
    timeout -k 120 -s TERM 2700 python bench.py > scripts/bench_stdout.txt 2> scripts/bench_stderr.txt; \
    echo "$(date +%FT%T) bench rc=$?" >> "$LOG" )
fi

if wait_alive && can_fit 1300; then
  echo "$(date +%FT%T) CHIP ALIVE — onchip_flash" >> "$LOG"
  ( ONCHIP_FLASH_BUDGET=1100 timeout -k 120 -s TERM 1300 python scripts/onchip_flash.py >> "$LOG" 2>&1; \
    echo "$(date +%FT%T) onchip_flash rc=$?" >> "$LOG" )
fi

if wait_alive && can_fit 1700; then
  echo "$(date +%FT%T) CHIP ALIVE — onchip_lm" >> "$LOG"
  ( ONCHIP_LM_BUDGET=1500 timeout -k 120 -s TERM 1700 python scripts/onchip_lm.py >> "$LOG" 2>&1; \
    echo "$(date +%FT%T) onchip_lm rc=$?" >> "$LOG" )
fi

if wait_alive && can_fit 8100; then
  echo "$(date +%FT%T) CHIP ALIVE — sweep" >> "$LOG"
  # 3 highest-value cells (conv7/512, conv7/256, space_to_depth/256); each cell
  # is one bench attempt whose compile either hits the cache (same graph as the
  # headline) or pays its own cold compile — 2400s covers both.
  ( MFU_SWEEP_CELL_TIMEOUT=2500 MFU_SWEEP_MAX_CELLS=3 \
    timeout -k 180 -s TERM 8100 python scripts/mfu_sweep.py >> "$LOG" 2>&1; \
    echo "$(date +%FT%T) sweep rc=$?" >> "$LOG" )
fi
echo "$(date +%FT%T) battery done" >> "$LOG"
