#!/usr/bin/env python
"""AOT where-the-time-goes analysis for the ResNet-50 MFU target — no chip
needed.

The axon tunnel can wedge for whole rounds (PERF.md hazard #2; rounds 3-4
both lost chip time to it), which blocked every on-device MFU measurement.
This script gets the analysis anyway: `jax.experimental.topologies` builds
an abstract **TPU v5e** device, the real XLA TPU compiler AOT-compiles the
actual training step against it, and the compiled module's cost analysis
(FLOPs + HBM bytes accessed) feeds a roofline model:

    t_compute = flops / peak_bf16        (v5e: 197 TFLOP/s)
    t_memory  = bytes / hbm_bw           (v5e: 819 GB/s)
    mfu_ceiling = t_compute / max(t_compute, t_memory)

per (stem, batch) config. This is the COMPILER's own accounting of the
exact program the bench runs — far stronger evidence than a CPU-backend
proxy — though still a ceiling: it assumes perfect overlap inside the
fused program and no host/runtime gaps (the r2 on-chip record, 25.9% MFU
at a ~52% roofline ceiling, shows those gaps are the other half of the
story).

Prints one JSON line per config and a summary table; run result lands in
``scripts/mfu_aot.jsonl``.
"""

import json
import os
import sys
import time

V5E_PEAK_BF16 = 197e12
V5E_HBM_BW = 819e9

CONFIGS = [
    {"stem": "conv7", "batch": 128},
    {"stem": "conv7", "batch": 192},
    {"stem": "conv7", "batch": 256},
    {"stem": "conv7", "batch": 512},  # the config whose ceiling crosses 35%
    {"stem": "space_to_depth", "batch": 128},
    {"stem": "space_to_depth", "batch": 192},
    {"stem": "space_to_depth", "batch": 256},
]


def main() -> None:
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import jax

    # nothing here may touch a real backend (the axon tunnel may be wedged
    # — that is the whole point of this script); any accidental eager op
    # goes to CPU, and the AOT path below names its TPU target explicitly
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from chainermn_tpu.models import ResNet50

    topo = topologies.get_topology_desc("v5e:2x2", "tpu")
    dev = np.array(topo.devices[:1])
    mesh = Mesh(dev, ("x",))
    repl = NamedSharding(mesh, P())
    print(f"# AOT target: {topo.devices[0].device_kind} (abstract, 1 chip)",
          file=sys.stderr)

    out_path = os.path.join(os.path.dirname(__file__), "mfu_aot.jsonl")
    results = []
    for cfg in CONFIGS:
        model = ResNet50(num_classes=1000, stem=cfg["stem"])
        opt = optax.sgd(0.1, momentum=0.9)

        def step(variables, opt_state, images, labels):
            def loss_fn(p):
                logits, updated = model.apply(
                    {"params": p, **{k: v for k, v in variables.items()
                                     if k != "params"}},
                    images, mutable=["batch_stats"], train=True)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, labels).mean(), updated

            (loss, updated), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(variables["params"])
            updates, opt_state = opt.update(grads, opt_state,
                                            variables["params"])
            params = optax.apply_updates(variables["params"], updates)
            return {"params": params, **updated}, opt_state, loss

        # abstract avals with shardings on the AOT mesh (no real arrays)
        img = jax.ShapeDtypeStruct((cfg["batch"], 224, 224, 3),
                                   jnp.bfloat16, sharding=repl)
        lbl = jax.ShapeDtypeStruct((cfg["batch"],), jnp.int32, sharding=repl)
        # abstract rng too — a concrete PRNGKey would eagerly initialize
        # the default backend
        var_shapes = jax.eval_shape(
            lambda k: model.init(k, jnp.zeros((2, 224, 224, 3), jnp.bfloat16),
                                 train=True),
            jax.ShapeDtypeStruct((2,), jnp.uint32))
        to_aval = lambda t: jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=repl), t)
        variables = to_aval(var_shapes)
        opt_state = to_aval(jax.eval_shape(
            opt.init, var_shapes["params"]))

        t0 = time.time()
        compiled = jax.jit(step).lower(variables, opt_state, img, lbl).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0))
        byts = float(ca.get("bytes accessed", 0.0))
        t_c = flops / V5E_PEAK_BF16
        t_m = byts / V5E_HBM_BW
        rec = {
            "stem": cfg["stem"],
            "batch": cfg["batch"],
            "step_flops": flops,
            "hbm_bytes": byts,
            "arithmetic_intensity": round(flops / byts, 1) if byts else None,
            "t_compute_ms": round(t_c * 1e3, 2),
            "t_memory_ms": round(t_m * 1e3, 2),
            "bound": "compute" if t_c >= t_m else "memory",
            "mfu_ceiling": round(t_c / max(t_c, t_m), 4),
            "roofline_step_ms": round(max(t_c, t_m) * 1e3, 2),
            "img_per_sec_ceiling": round(cfg["batch"] / max(t_c, t_m), 0),
            "compile_s": round(time.time() - t0, 1),
        }
        results.append(rec)
        print(json.dumps(rec), flush=True)

    with open(out_path, "w") as f:
        for r in results:
            f.write(json.dumps(r) + "\n")
    print(f"\n# {'stem':>16} {'batch':>5} {'AI':>6} {'bound':>8} "
          f"{'ceil ms':>8} {'MFU ceil':>8}", file=sys.stderr)
    for r in results:
        print(f"# {r['stem']:>16} {r['batch']:>5} "
              f"{r['arithmetic_intensity']:>6} {r['bound']:>8} "
              f"{r['roofline_step_ms']:>8} {r['mfu_ceiling']:>8}",
              file=sys.stderr)


if __name__ == "__main__":
    main()
