#!/usr/bin/env python
"""Chip-free re-validation of the flash long-context ceiling after kernel
changes (round-5: storage-dtype MXU inputs, ce1ad92).

AOT-compiles single-call flash fwd+bwd against the real TPU compiler for an
abstract v5e target at T in {32768, 131072} (the PERF.md ceiling claim), at
the default and the sweep-candidate block sizes. A claim like "compiles to
T = 131072" must be re-proven whenever the kernels change — scoped-VMEM
accounting is exactly what the dtype changes could move.

Emits one JSON record per cell to scripts/aot_flash_ceiling.jsonl.
"""

import json
import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
OUT = os.path.join(_HERE, "aot_flash_ceiling.jsonl")


def emit(rec):
    rec["t"] = round(time.time(), 1)
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")  # host only; target is abstract

    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import importlib

    # import_module, not `import ... as`: ops/__init__ re-exports the
    # flash_attention FUNCTION, which shadows the submodule in attribute
    # lookup (the same trap aot_ring_overlap.py sidesteps)
    fa = importlib.import_module("chainermn_tpu.ops.flash_attention")
    fa._interpret_default = lambda: False  # Mosaic lowering during AOT trace

    # smallest valid v5e topology is 2x2 (chips_per_host_bounds); the
    # ceiling is still a single-device property — the kernel call is
    # wrapped in a fully-replicated shard_map, so every chip runs the
    # complete single-chip program (Mosaic calls cannot be auto-partitioned
    # outside shard_map)
    topo = topologies.get_topology_desc("v5e:2x2", "tpu")
    mesh = Mesh(np.array(topo.devices).reshape(4), ("replica",))
    repl = NamedSharding(mesh, P())

    # Opt-in skip of already-recorded cells (AOT_CEILING_SKIP_RECORDED=1):
    # a battery stage with a tight window spends it on the NEW cells (the
    # block-1024 runs backing the new default) instead of re-proving
    # 128/256/512. OFF by default on purpose — this script's job is
    # re-proving the ceiling after kernel changes, and a recorded-ok cell
    # from an OLDER kernel must not masquerade as re-validation (records
    # carry no kernel fingerprint).
    done = set()
    if os.environ.get("AOT_CEILING_SKIP_RECORDED"):
        try:
            with open(OUT) as f:
                for line in f:
                    try:
                        r = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if r.get("ok"):
                        done.add((r.get("seq_len"), r.get("block")))
        except OSError:
            pass

    B, H, D = 1, 8, 64
    for t_len in (32768, 131072):
        for blk in (1024, 512, 256, 128):
            if (t_len, blk) in done:
                emit({"seq_len": t_len, "block": blk, "skipped": "recorded"})
                continue
            aval = jax.ShapeDtypeStruct((B, t_len, H, D), jnp.bfloat16,
                                        sharding=repl)

            def loss(q, k, v):
                def body(q, k, v):
                    o = fa.flash_attention(q, k, v, causal=True,
                                           interpret=False, block_q=blk,
                                           block_k=blk)
                    return jnp.sum(o.astype(jnp.float32) ** 2)

                return jax.shard_map(body, mesh=mesh, in_specs=(P(),) * 3,
                                     out_specs=P(), check_vma=False)(q, k, v)

            rec = {"seq_len": t_len, "block": blk}
            t0 = time.time()
            try:
                c = jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(
                    aval, aval, aval).compile()
                rec["ok"] = True
                try:
                    mem = c.memory_analysis()
                    rec["peak_hbm_gb"] = round(
                        (mem.temp_size_in_bytes + mem.argument_size_in_bytes
                         + mem.output_size_in_bytes) / 2**30, 2)
                except Exception:
                    pass
            except Exception as e:
                rec["ok"] = False
                rec["error"] = f"{type(e).__name__}: {e}"[:300]
            rec["compile_s"] = round(time.time() - t0, 1)
            emit(rec)
    emit({"done": True})


if __name__ == "__main__":
    main()
