#!/usr/bin/env python
"""MFU sweep driver: run bench.py once per (stem, batch) cell, sequentially.

The axon TPU tunnel is single-tenant and wedges if a lease-holding process is
SIGKILLed (PERF.md hazard #2 — one mid-compile SIGKILL cost hours of chip
time this round). So: cells run one at a time, each gets ONE attempt with a
budget generous enough for a contended compile (batch-192 ResNet-50 compile
exceeded 1200s while the CPU test suite ran beside it), and timeouts go
through bench.py's parent, which since round 3 TERMinates (letting PJRT
release the device grant) and only escalates to SIGKILL after 60s of ignored
TERM. Results append to scripts/mfu_sweep.jsonl as they land, so an
interrupted sweep loses only the remaining cells.

Usage: python scripts/mfu_sweep.py [out.jsonl]
"""

import json
import os
import signal
import subprocess
import sys
import time

CELLS = [
    # (stem, batch) ordered by the round-4 AOT roofline (PERF.md): the
    # workload is HBM-bound and batch is the MFU lever — ceiling 35.2% at
    # conv7/512, 31.2% at 256, 27% at 128. space_to_depth is byte-identical
    # to conv7 (NOT a bandwidth lever); one cell kept as the measured
    # cross-check of that prediction. 512 first: it is the only config
    # whose ceiling clears the 35% bar (fits in ~15.3 of 16 GB HBM per the
    # AOT memory analysis). bench.py does NOT halve an explicitly-set
    # batch, so an OOM here fails this cell and the sweep moves on to the
    # next (conv7/256 is measured on purpose, once, under its own label).
    # First three are the MFU_SWEEP_MAX_CELLS=3 priority set.
    ("conv7", 512),
    ("conv7", 256),
    ("space_to_depth", 256),
    ("conv7", 384),
    ("conv7", 192),
]

BENCH = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "bench.py")


def main():
    out_path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "mfu_sweep.jsonl")
    # Per-cell budget is overridable so a wrapper (scripts/chip_watch.sh) can
    # keep its own timeout ABOVE n_cells * cell_timeout — a wrapper TERM that
    # lands mid-cell would otherwise orphan a lease-holding bench child.
    cell_timeout = int(os.environ.get("MFU_SWEEP_CELL_TIMEOUT", "2700"))
    # cap the cell count (wrappers budget wall-clock; the chip window may
    # open late in a round and the driver's own round-end bench must not
    # contend with a still-running sweep on the single-tenant tunnel)
    max_cells = int(os.environ.get("MFU_SWEEP_MAX_CELLS", str(len(CELLS))))

    # Forward TERM to the running bench cell: `timeout` signals only THIS
    # process; without forwarding, the bench parent (and its lease-holding
    # grandchild) would outlive us and contend with whatever runs next on
    # the single-tenant tunnel (PERF.md hazard #2). The in-flight cell gets
    # a rc=143 record so "killed mid-measurement" is distinguishable from
    # "never ran" (the bench child additionally salvages to its scratch
    # file; we don't touch its stdout pipe here — the interrupted
    # communicate() in the main frame owns it).
    current = [None]       # running Popen
    current_cell = [None]  # (stem, batch, t0)

    def _on_term(signum, frame):
        proc = current[0]
        if proc is not None and proc.poll() is None:
            proc.terminate()  # bench's parent handles TERM: salvages + unwinds
            try:
                proc.wait(timeout=90)
            except subprocess.TimeoutExpired:
                pass
        if current_cell[0] is not None:
            stem, batch, t0 = current_cell[0]
            with open(out_path, "a") as f:
                f.write(json.dumps({
                    "stem": stem, "batch": batch, "rc": 143,
                    "terminated_by": f"signal {signum}",
                    "wall_s": round(time.time() - t0, 1)}) + "\n")
        sys.exit(143)

    signal.signal(signal.SIGTERM, _on_term)

    for stem, batch in CELLS[:max_cells]:
        env = dict(os.environ,
                   CHAINERMN_TPU_BENCH_STEM=stem,
                   CHAINERMN_TPU_BENCH_BATCH=str(batch),
                   CHAINERMN_TPU_BENCH_SWEEP="0",
                   CHAINERMN_TPU_BENCH_STEPS="50",
                   CHAINERMN_TPU_BENCH_ATTEMPTS="1",
                   CHAINERMN_TPU_BENCH_TIMEOUT=str(cell_timeout),
                   CHAINERMN_TPU_BENCH_TOTAL_BUDGET=str(cell_timeout + 60))
        t0 = time.time()
        print(f"=== cell stem={stem} batch={batch}", file=sys.stderr, flush=True)
        current_cell[0] = (stem, batch, t0)
        proc = subprocess.Popen([sys.executable, BENCH], env=env,
                                stdout=subprocess.PIPE, text=True)
        current[0] = proc
        stdout_txt, _ = proc.communicate()
        current[0] = None
        current_cell[0] = None
        line = (stdout_txt or "").strip().splitlines()
        rec = {"stem": stem, "batch": batch, "rc": proc.returncode,
               "wall_s": round(time.time() - t0, 1)}
        if line:
            try:
                rec["result"] = json.loads(line[-1])
            except json.JSONDecodeError:
                rec["raw"] = line[-1][:500]
        with open(out_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(f"=== cell done rc={proc.returncode} "
              f"({rec['wall_s']}s)", file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
