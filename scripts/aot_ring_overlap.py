#!/usr/bin/env python
"""Ring comm/compute overlap evidence from AOT multi-chip HLO (VERDICT r4
weak #4 / next-step #6).

``parallel/sequence.py`` asserts "XLA pipelines the ppermute with the block
einsums" — this script checks that claim against the real TPU compiler's
SCHEDULE, no chip needed (the round-4 AOT method): compile each ring
variant for an abstract v5e:2x2 slice, then walk the scheduled while-body
and test whether each ``collective-permute-start``/``done`` pair brackets
the block compute (fusions / Mosaic custom-calls / conditionals) or
serializes around it.

The schedule in the optimized module IS the order the TPU executes — an
async start issued before the compute and resolved after it is overlap by
construction (the DMA rides the ICI while the MXU works).

Emits one JSON record per (case, computation) to
``scripts/ring_overlap_aot.jsonl`` and a human summary to stderr.
"""

import json
import os
import re
import sys

# ops that represent real block compute in the scheduled body
_HEAVY = ("fusion", "conditional", "custom-call", "dot", "convolution",
          "while")


def analyze_schedule(text: str):
    """For every computation containing collective-permutes, pair each
    start with its done (by HLO result-name suffix) and count heavy compute
    ops scheduled between them."""
    out = []
    lines = text.splitlines()
    # computation boundaries: "name (params) -> type {" ... "}"
    comp_start = None
    comp_name = None
    depth = 0
    for i, raw in enumerate(lines):
        stripped = raw.strip()
        if comp_start is None:
            if raw.rstrip().endswith("{"):
                comp_start = i
                comp_name = raw.strip().split()[0].lstrip("%")
                depth = 1
            continue
        if raw.rstrip().endswith("{"):
            depth += 1
        if stripped == "}" or stripped.startswith("} "):
            depth -= 1
            if depth == 0:
                body = lines[comp_start + 1:i]
                rec = _analyze_body(comp_name, body)
                if rec is not None:
                    out.append(rec)
                comp_start = None
        # (single-line computations never contain permutes; ignore)
    return out


def _analyze_body(comp_name, body):
    ops = []  # (index, result_name, opcode, raw_line)
    for idx, l in enumerate(body):
        m = re.match(r"\s*(?:ROOT\s+)?(\S+)\s*=\s*.*?\b([a-z][\w-]*)\(", l)
        if not m:
            continue
        ops.append((idx, m.group(1).lstrip("%"), m.group(2), l))
    # async collectives analyzed: ring permutes AND ulysses all-to-alls
    _START = ("collective-permute-start", "all-to-all-start")
    _DONE = ("collective-permute-done", "all-to-all-done")
    starts = {name: i for i, name, op, _ in ops if op in _START}
    if not starts:
        return None
    # pair each done with its start by OPERAND (the done's argument names
    # the start op) — name-suffix pairing breaks on .remat/.clone suffixes
    # and would silently drop pairs, letting an un-analyzed schedule read
    # as "all overlapped"
    done_for_start = {}
    for i, name, op, raw in ops:
        if op in _DONE:
            mo = re.search(op + r"\(\s*%?([\w.-]+)", raw)
            if mo:
                done_for_start[mo.group(1)] = i
    heavy = [(i, name, op) for i, name, op, _ in ops
             if any(op == h or op.startswith(h) for h in _HEAVY)
             and "collective-permute" not in op and "all-to-all" not in op]
    pairs = []
    for sname, si in starts.items():
        di = done_for_start.get(sname)
        if di is None:
            # unmatched start: loud failure, never a silent drop
            pairs.append({"start": sname, "start_pos": si,
                          "done_pos": None, "heavy_between": [],
                          "overlapped": False, "unmatched_done": True})
            continue
        between = [f"{op}:{name[:40]}" for i, name, op in heavy
                   if si < i < di]
        pairs.append({
            "start": sname, "start_pos": si, "done_pos": di,
            "heavy_between": between,
            "overlapped": bool(between),
        })
    return {
        "computation": comp_name,
        "n_instructions": len(body),
        "pairs": pairs,
        "all_overlapped": all(p["overlapped"] for p in pairs) if pairs
        else None,
    }


def main():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    shard_map = jax.shard_map
    import importlib

    # NOT `import chainermn_tpu.ops.flash_attention` — the ops package
    # re-exports the flash_attention FUNCTION under that name, shadowing
    # the submodule attribute
    fa = importlib.import_module("chainermn_tpu.ops.flash_attention")
    from chainermn_tpu.parallel.sequence import (
        ring_attention,
        ring_flash_attention,
        ulysses_attention,
        zigzag_flash_attention,
        zigzag_ring_attention,
    )

    # Force COMPILED pallas lowering during AOT tracing: default_backend()
    # is cpu here, but the target is the abstract TPU — interpret-mode
    # kernels would not produce Mosaic custom-calls to schedule.
    fa._interpret_default = lambda: False

    topo = topologies.get_topology_desc("v5e:2x2", "tpu")
    mesh = Mesh(np.array(topo.devices).reshape(4), ("sp",))
    B, T, H, D = 1, 8192, 8, 64
    sh = NamedSharding(mesh, P(None, "sp"))
    avals = [jax.ShapeDtypeStruct((B, T, H, D), jnp.bfloat16, sharding=sh)] * 3

    def ring_xla(q, k, v):
        return ring_attention(q, k, v, "sp", causal=True)

    def ring_flash(q, k, v):
        return ring_flash_attention(q, k, v, "sp", causal=True)

    def zigzag_flash(q, k, v):
        return zigzag_flash_attention(q, k, v, "sp")

    def zigzag_xla(q, k, v):
        return zigzag_ring_attention(q, k, v, "sp", causal=True)

    def ulysses(q, k, v):
        return ulysses_attention(q, k, v, "sp", causal=True)

    def ulysses_hc2(q, k, v):
        return ulysses_attention(q, k, v, "sp", causal=True, head_chunks=2)

    def fwd(inner):
        def f(q, k, v):
            return shard_map(inner, mesh=mesh, in_specs=(P(None, "sp"),) * 3,
                             out_specs=P(None, "sp"))(q, k, v)
        return f

    def fwdbwd(inner):
        def loss(q, k, v):
            def body(q, k, v):
                o = inner(q, k, v)
                # per-shard sum -> psum: replicated scalar loss
                return jax.lax.psum(
                    jnp.sum(o.astype(jnp.float32) ** 2), "sp")
            return shard_map(body, mesh=mesh, in_specs=(P(None, "sp"),) * 3,
                             out_specs=P())(q, k, v)
        return jax.grad(loss, argnums=(0, 1, 2))

    cases = [
        ("ring_xla_fwd", jax.jit(fwd(ring_xla))),
        ("ring_xla_fwdbwd", jax.jit(fwdbwd(ring_xla))),
        ("ring_flash_fwd", jax.jit(fwd(ring_flash))),
        ("ring_flash_fwdbwd", jax.jit(fwdbwd(ring_flash))),
        ("zigzag_flash_fwdbwd", jax.jit(fwdbwd(zigzag_flash))),
        ("zigzag_xla_fwdbwd", jax.jit(fwdbwd(zigzag_xla))),
        ("ulysses_fwdbwd", jax.jit(fwdbwd(ulysses))),
        ("ulysses_hc2_fwdbwd", jax.jit(fwdbwd(ulysses_hc2))),
    ]
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "ring_overlap_aot.jsonl")
    results = []
    for name, fn in cases:
        try:
            compiled = fn.lower(*avals).compile()
            text = compiled.as_text()
            comps = analyze_schedule(text)
            verdicts = [c["all_overlapped"] for c in comps
                        if c["all_overlapped"] is not None]
            # SYNCHRONOUS collectives (no -start/-done pair) are reported,
            # not treated as overlap failures: ulysses' all_to_alls are
            # sequentially data-dependent on the attention between them
            # (exchange -> attend -> exchange), so there is nothing of its
            # own to overlap them WITH — unlike a ring hop, which is
            # independent of the current block's compute.
            sync = len(re.findall(r"\ball-to-all\(", text))
            # no analyzed pairs at all -> None (inconclusive), never True
            rec = {"case": name, "computations": comps,
                   "sync_all_to_all": sync,
                   "all_overlapped": all(verdicts) if verdicts else None}
        except Exception as e:
            rec = {"case": name, "error": f"{type(e).__name__}: {e}"[:400]}
        results.append(rec)
        pairs = sum(len(c.get("pairs", [])) for c in rec.get("computations", []))
        sync_note = (f", {rec['sync_all_to_all']} sync all-to-alls"
                     if rec.get("sync_all_to_all") else "")
        print(f"# {name}: "
              f"{rec.get('all_overlapped', rec.get('error'))} "
              f"({pairs} permute pairs{sync_note})", file=sys.stderr)
        for c in rec.get("computations", []):
            for p in c["pairs"]:
                print(f"#   {c['computation'][:40]} {p['start'][:40]}: "
                      f"pos {p['start_pos']}->{p['done_pos']}, "
                      f"{len(p['heavy_between'])} heavy ops between "
                      f"({'OVERLAP' if p['overlapped'] else 'SERIAL'})",
                      file=sys.stderr)
    with open(out_path, "w") as f:
        for r in results:
            f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
