#!/bin/bash
# Round-5 battery 4: service-side AOT analyses, after battery3 completes.
#   1. aot_ring_overlap.py — re-verify the overlap schedule now that the
#      default flash block at typical shard sizes moved 512 -> 1024.
#   2. aot_lm_roofline.py — bytes/FLOPs breakdown of the onchip_lm cells
#      (the 13.9%-LM-MFU diagnosis) incl. the B=32 token-batch probe.
# These hold no device lease but ride the same axon remote-compile helper
# that wedges with it — probe-gated and TERM/KILL-capped like the others.
set -u
cd /root/repo
LOG=scripts/battery4.log
START=$(date +%s)
BATTERY_DEADLINE=${BATTERY4_DEADLINE:-21600}
echo "$(date +%FT%T) battery4 start (deadline ${BATTERY_DEADLINE}s)" >> "$LOG"

# Wait on the battery3 PROCESS, not its log marker: the append-only log
# keeps 'done' lines from earlier runs (stale-marker race), and battery3
# has exit paths that never write one (deadline while waiting on
# battery2, external kill). Process-gone covers every case. Launcher
# contract: start battery4 only while battery3 is already running — the
# first pgrep must see it or the gate opens immediately. The pattern
# matches any invocation spelling of the script name.
while pgrep -f "battery3.sh" >/dev/null 2>&1; do
  if [ $(( $(date +%s) - START )) -gt "$BATTERY_DEADLINE" ]; then
    echo "$(date +%FT%T) battery4 deadline passed waiting for battery3" >> "$LOG"
    exit 0
  fi
  sleep 120
done
echo "$(date +%FT%T) battery3 gone; proceeding" >> "$LOG"

probe() {
  timeout -k 30 -s TERM 90 python -c "import jax; d=jax.devices(); assert d[0].platform=='tpu', d" >/dev/null 2>&1
}

can_fit() {
  [ $(( BATTERY_DEADLINE - ( $(date +%s) - START ) )) -ge "$1" ]
}

wait_alive() {
  while true; do
    if [ $(( $(date +%s) - START )) -gt "$BATTERY_DEADLINE" ]; then
      echo "$(date +%FT%T) battery4 deadline passed" >> "$LOG"
      return 1
    fi
    if probe; then return 0; fi
    echo "$(date +%FT%T) probe wedged" >> "$LOG"
    sleep 240
  done
}

if wait_alive && can_fit 2400; then
  echo "$(date +%FT%T) SERVICE ALIVE — aot_ring_overlap (block-1024 defaults)" >> "$LOG"
  ( timeout -k 120 -s TERM 2400 python scripts/aot_ring_overlap.py >> "$LOG" 2>&1; \
    echo "$(date +%FT%T) ring_overlap rc=$?" >> "$LOG" )
fi

if wait_alive && can_fit 2400; then
  echo "$(date +%FT%T) SERVICE ALIVE — aot_lm_roofline" >> "$LOG"
  ( timeout -k 120 -s TERM 2400 python scripts/aot_lm_roofline.py >> "$LOG" 2>&1; \
    echo "$(date +%FT%T) lm_roofline rc=$?" >> "$LOG" )
fi
echo "$(date +%FT%T) battery4 done" >> "$LOG"
