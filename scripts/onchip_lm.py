#!/usr/bin/env python
"""On-chip transformer-LM training MFU — the second headline metric.

bench.py measures the reference's acceptance workload (ResNet-50 DP,
SURVEY.md S6). This measures the flagship LM path — ``jit_lm_train_step``
over :class:`TransformerLM` with the Pallas flash kernels — compiled and
executed on the real chip, at sizes where the MXU (not the input pipeline)
is the constraint:

  cells: (T=2048, B=8, flash) — throughput headline
         (T=2048, B=8, full)  — LM-level flash-vs-full ratio, short ctx
         (T=8192, B=2, flash) — long-context step
         (T=8192, B=2, full)  — the AOT table's 4.3x prediction, measured

FLOPs come from the compiled module's cost_analysis (post-optimization,
per-device — same convention as bench.py), with the analytic
``6 * params * tokens (+ attention term)`` estimate recorded beside it as a
cross-check. MFU is vs the chip's bf16 peak (197 TFLOP/s on v5e).

Appends one JSON record per cell to scripts/onchip_lm.jsonl the moment it
lands (wedge protocol: partial evidence survives teardown). Exits 0 with a
"skipped" record if no TPU is attached.
"""

import json
import os
import signal
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root (run from anywhere)
OUT = os.path.join(_HERE, "onchip_lm.jsonl")

# one peak-FLOPs table and one cache setup for the whole battery
from bench import _chip_peak, enable_compilation_cache


_PERSIST = [False]  # set true after the platform check confirms a real TPU


def emit(rec):
    """Real-chip records append to the evidence jsonl; CPU/tiny smoke runs
    print only (the file is committed TPU evidence — same policy as
    bench._persist_measured)."""
    rec["t"] = round(time.time(), 1)
    if _PERSIST[0]:
        with open(OUT, "a") as f:
            f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)


def main():
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    deadline = time.time() + float(os.environ.get("ONCHIP_LM_BUDGET", "1500"))

    import jax

    plat = os.environ.get("CHAINERMN_TPU_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    enable_compilation_cache(jax)

    import jax.numpy as jnp
    import optax

    tiny_env = bool(os.environ.get("ONCHIP_LM_TINY"))  # CI smoke: any platform
    devs = jax.devices()
    if devs[0].platform != "tpu" and not tiny_env:
        emit({"test": "platform", "skipped": f"no TPU ({devs[0].platform})"})
        return
    kind = devs[0].device_kind
    peak = _chip_peak(kind)
    _PERSIST[0] = devs[0].platform == "tpu" and not tiny_env
    emit({"test": "platform", "device_kind": kind, "peak_flops": peak})

    import chainermn_tpu
    from chainermn_tpu.models import TransformerLM
    from chainermn_tpu.training import jit_lm_train_step

    vocab = int(os.environ.get("ONCHIP_LM_VOCAB", "32768"))
    d_model = int(os.environ.get("ONCHIP_LM_DMODEL", "1024"))
    n_layers = int(os.environ.get("ONCHIP_LM_LAYERS", "12"))
    n_heads = d_model // 64
    tiny = tiny_env
    if tiny:
        vocab, d_model, n_layers, n_heads = 256, 64, 2, 2
    cells = [(2048, 8, "flash"), (2048, 8, "full"),
             (8192, 2, "flash"), (8192, 2, "full"),
             # token-batch lever: 4x the tokens amortize the weight/state
             # HBM traffic (the AOT LM roofline names bytes, not MXU
             # occupancy, as the MFU limiter at B=8; ceiling 52% -> 79%
             # at B=16+remat, lm_roofline_aot.jsonl). B=16 is the biggest
             # feasible cell: B=32 peaks at 18.8 GB even WITH remat (the
             # f32 logits pair alone is ~17 GB); B=16+remat fits at 12.7.
             (2048, 16, "flash+remat"),
             # chunked fused head+loss (ops/losses.py) removes the f32
             # logits pair entirely: B=32 drops 18.8 -> 10.65 GB and the
             # ceiling rises to 87.9% (the best feasible single-chip cell;
             # B=64 is 17.9 GB = OOM)
             (2048, 32, "flash+remat+fused")]
    if tiny:
        cells = [(128, 2, "full")]

    comm = chainermn_tpu.create_communicator("tpu")
    opt = chainermn_tpu.create_multi_node_optimizer(optax.adamw(3e-4), comm)
    rng = jax.random.PRNGKey(0)

    this_run = []  # records from THIS process only (ratio pairing below)
    # Starting a cell means starting a compile, and a remote compile cannot
    # be preempted (SIGTERM defers while blocked in the C call; the
    # follow-up SIGKILL orphans the single-tenant lease). So gate each
    # cell on a pessimistic cost estimate, like bench.py's ladder: a warm
    # previous compile predicts warm neighbors (same earlier process, same
    # cell list); cold needs the full floor.
    cell_floor = float(os.environ.get("ONCHIP_LM_CELL_FLOOR", "700"))
    prev_wall = prev_compile = None
    for t_len, batch, attn in cells:
        remaining = deadline - time.time()
        if prev_wall is None:
            # first cell: the budget is the operator's statement that one
            # cell fits; no history to gate on — but a startup that already
            # drained the deadline (wedged-tunnel attach) must still skip,
            # or the un-preemptable compile starts with no window left and
            # the outer TERM/KILL orphans the lease.
            need = 60.0
        elif prev_compile is not None and prev_compile < 60:
            need = max(3 * prev_wall, 120.0)
        else:
            need = cell_floor
        if remaining < need:
            emit({"cell": [t_len, batch, attn], "skipped": "budget",
                  "remaining_s": round(remaining, 1), "need_s": need})
            continue
        flags = attn.split("+")
        attn_kind, use_remat, use_fused = (
            flags[0], "remat" in flags[1:], "fused" in flags[1:])
        rec = {"cell": [t_len, batch, attn], "seq_len": t_len,
               "batch": batch, "attention": attn_kind, "remat": use_remat,
               "fused_ce": use_fused,
               "d_model": d_model, "n_layers": n_layers, "vocab": vocab}
        t_start = time.time()
        try:
            model = TransformerLM(
                vocab_size=vocab, d_model=d_model, n_heads=n_heads,
                n_layers=n_layers, max_len=max(t_len, 2048),
                attention=attn_kind, compute_dtype=jnp.bfloat16,
                remat=use_remat)
            tokens = jax.random.randint(rng, (batch, t_len), 0, vocab)
            # real next-token objective (same key would make targets ==
            # tokens: a trivial copy task whose loss collapses)
            targets = jnp.roll(tokens, -1, axis=1)
            params = comm.bcast_data(model.init(rng, tokens))
            opt_state = jax.jit(opt.init)(params)
            n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
            rec["n_params"] = n_params

            step_fn = jit_lm_train_step(model, opt, comm,
                                        fused_ce=use_fused)
            t0 = time.time()
            # first call compiles (jit_lm_train_step caches per-shape)
            params, opt_state, loss, _ = step_fn(
                params, opt_state, tokens, targets)
            float(loss)
            rec["compile_plus_first_step_s"] = round(time.time() - t0, 1)

            n_steps = 3 if tiny else int(os.environ.get(
                "ONCHIP_LM_STEPS", "20"))
            # warm, enqueue n, close with a device->host fetch (the
            # tunnel-safe timing idiom — see bench.py's note on
            # block_until_ready through the relay)
            params, opt_state, loss, _ = step_fn(
                params, opt_state, tokens, targets)
            float(loss)
            t0 = time.time()
            for _ in range(n_steps):
                params, opt_state, loss, _ = step_fn(
                    params, opt_state, tokens, targets)
            rec["loss"] = float(loss)
            dt = time.time() - t0
            step_s = dt / n_steps
            rec["step_time_ms"] = round(step_s * 1e3, 2)
            rec["tokens_per_sec"] = round(batch * t_len / step_s, 1)

            # Analytic fwd+bwd FLOPs: 6 * non-embedding-params * tokens for
            # the matmul tower + 12 * B * H * T^2 * d_head / 2 (causal) for
            # attention scores/values, fwd+bwd. Recorded as the cross-check;
            # cost_analysis is unavailable here because jit_lm_train_step
            # manages its own jit cache (no AOT handle) — the bench keeps
            # both conventions side by side where it can.
            embed_params = vocab * d_model + model.max_len * d_model
            d_head = d_model // n_heads
            flops = (6.0 * (n_params - embed_params) * batch * t_len
                     + 12.0 * batch * n_heads * t_len * t_len * d_head / 2)
            rec["analytic_tflops"] = round(flops / step_s / 1e12, 2)
            if peak:
                rec["mfu_analytic"] = round(flops / step_s / peak, 4)
        except Exception as e:
            rec["error"] = f"{type(e).__name__}: {e}"[:400]
        rec["wall_s"] = round(time.time() - t_start, 1)
        prev_wall = rec["wall_s"]
        prev_compile = rec.get("compile_plus_first_step_s")  # None => cold
        this_run.append(rec)
        emit(rec)

    # LM-level flash-vs-full ratios, paired within THIS run only (an
    # append-only OUT can hold records from earlier runs / other configs)
    by = {tuple(r["cell"]): r for r in this_run if "step_time_ms" in r}
    for t_len in (2048, 8192):
        b = {2048: 8, 8192: 2}[t_len]
        fl, fu = by.get((t_len, b, "flash")), by.get((t_len, b, "full"))
        if fl and fu:
            emit({"test": "full_over_flash", "seq_len": t_len,
                  "ratio": round(fu["step_time_ms"]
                                 / fl["step_time_ms"], 3)})
    emit({"test": "done"})


if __name__ == "__main__":
    main()
