#!/usr/bin/env python
"""Bench-trajectory diff: compare bench records across rounds.

The repo accumulates one ``BENCH_r*.json`` per bench round (the driver's
``{n, cmd, rc, tail, parsed}`` wrapper around ``bench.py``'s single JSON
line). Each round is a point on the project's performance trajectory;
this tool turns the set into one consolidated, diffable artifact and
gates new numbers against it:

- ``--build`` flattens every round's ``parsed`` record into dotted
  numeric paths (``prefix_serving.ttft_p50_ms``), groups them by
  ``device_kind`` (a CPU-mesh harness number must never band against a
  real-chip number), and writes ``BENCH_TRAJECTORY.json`` with per-metric
  tolerance bands anchored on the most recent value.
- ``--record FILE`` compares one fresh bench record (a raw ``bench.py``
  output line or a round wrapper) against the committed bands and prints
  ONE parseable verdict line: ``{"bench_compare": {"ok": ..., "checked":
  N, "regressed": [...], ...}}``. A metric is *regressed* when it moved
  past its band in the bad direction — direction is inferred from the
  metric name (``*_ms``/``wall_*``/``ttft*`` lower-better;
  ``tokens_per_sec``/``*speedup``/``hit_rate`` higher-better; unknown
  names are informational only).
- ``--check`` (the ``scripts/lint.sh`` hook, mirroring the
  ``SANITIZER.json`` runtime-report cross-check) re-derives the
  trajectory from the committed rounds and fails when
  ``BENCH_TRAJECTORY.json`` is stale, then verdicts the newest
  successful round against the bands of the rounds before it.

Stdlib-only on purpose: it must run anywhere the repo checks out,
including inside the tier-1 suite (``tests/test_bench_compare.py``).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

TRAJECTORY = "BENCH_TRAJECTORY.json"
DEFAULT_TOLERANCE = 0.25

# direction inference, checked on the LAST dotted segment, higher-better
# patterns first (so "ttft_p50_speedup" reads as a speedup, not a TTFT)
_HIGHER = ("tokens_per_sec", "throughput", "speedup", "hit_rate",
           "accept_rate", "gain", "gbps", "mfu", "tflops", "value",
           "max_concurrent", "parity", "bandwidth", "goodput")
_LOWER = ("_ms", "wall", "ttft", "tpot", "mttr", "lag", "overhead",
          "dip", "seconds", "preemption", "recompile", "eviction",
          "read_amplification", "conservation")
# flattened subtrees that are snapshots/config, not trajectory metrics
_SKIP_KEYS = ("monitor", "tail", "cmd", "model", "trie", "kv_stats",
              "compile_counts", "critical_path", "health", "outcomes",
              "replica_states", "weight_versions", "detail")


def direction(path: str) -> str | None:
    """'higher' / 'lower' / None (informational) for a dotted path."""
    leaf = path.rsplit(".", 1)[-1]
    for pat in _HIGHER:
        if pat in leaf:
            return "higher"
    for pat in _LOWER:
        if pat in leaf:
            return "lower"
    if leaf.endswith("_s"):
        return "lower"
    return None


def flatten(node, prefix: str = "", out: dict | None = None) -> dict:
    """Numeric leaves of a nested record as ``{dotted.path: value}``
    (bools, strings, lists, and the ``_SKIP_KEYS`` subtrees are
    dropped — bands only make sense over scalars)."""
    if out is None:
        out = {}
    if isinstance(node, dict):
        for k, v in node.items():
            if k in _SKIP_KEYS:
                continue
            flatten(v, f"{prefix}.{k}" if prefix else str(k), out)
    elif isinstance(node, (int, float)) and not isinstance(node, bool):
        out[prefix] = float(node)
    return out


def load_rounds(repo: str) -> list[dict]:
    """Every ``BENCH_r*.json`` in round order, normalized to
    ``{n, file, rc, device_kind, metrics}`` (metrics None for rounds
    whose bench run produced no parseable record)."""
    rounds = []
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        if os.path.basename(path) == TRAJECTORY:
            continue
        with open(path) as f:
            raw = json.load(f)
        parsed = raw.get("parsed")
        ok = isinstance(parsed, dict) and parsed.get("value") is not None
        rounds.append({
            "n": raw.get("n"),
            "file": os.path.basename(path),
            "rc": raw.get("rc"),
            "device_kind": (parsed or {}).get("device_kind"),
            "metrics": flatten(parsed) if ok else None,
        })
    return rounds


def build_trajectory(repo: str,
                     tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """The consolidated artifact: per-device-kind bands over every
    successful round, anchored on the most recent value (``last``) with
    the observed min/max alongside — so the band carries both the
    current expectation and the historical envelope."""
    rounds = load_rounds(repo)
    bands: dict[str, dict] = {}
    for r in rounds:
        if r["metrics"] is None:
            continue
        kind = str(r["device_kind"])
        group = bands.setdefault(kind, {})
        for path, v in r["metrics"].items():
            entry = group.setdefault(
                path, {"last": v, "min": v, "max": v, "n": 0,
                       "direction": direction(path)})
            entry["last"] = v
            entry["min"] = min(entry["min"], v)
            entry["max"] = max(entry["max"], v)
            entry["n"] += 1
    return {
        "tolerance": tolerance,
        "rounds": [{k: r[k] for k in ("n", "file", "rc", "device_kind")}
                   for r in rounds],
        "bands": bands,
    }


def compare(metrics: dict, device_kind, trajectory: dict,
            tolerance: float | None = None) -> dict:
    """One record's flattened metrics vs the trajectory's bands for its
    device kind. Regression = worse than ``last * (1 +/- tolerance)``
    in the metric's bad direction; unknown-direction metrics are
    informational. Returns the verdict dict (``ok`` is False only on
    regressions)."""
    tol = (trajectory.get("tolerance", DEFAULT_TOLERANCE)
           if tolerance is None else tolerance)
    group = trajectory.get("bands", {}).get(str(device_kind), {})
    regressed, improved, new, info = [], [], [], 0
    checked = 0
    for path, v in sorted(metrics.items()):
        band = group.get(path)
        if band is None:
            new.append(path)
            continue
        d = band.get("direction")
        if d is None:
            info += 1
            continue
        checked += 1
        base = band["last"]
        scale = max(abs(base), 1e-9)
        if d == "higher" and v < base - tol * scale:
            regressed.append({"metric": path, "value": v, "baseline": base})
        elif d == "lower" and v > base + tol * scale:
            regressed.append({"metric": path, "value": v, "baseline": base})
        elif ((d == "higher" and v > base + tol * scale)
              or (d == "lower" and v < base - tol * scale)):
            improved.append({"metric": path, "value": v, "baseline": base})
    missing = sorted(set(group) - set(metrics))
    return {
        "ok": not regressed,
        "device_kind": device_kind,
        "tolerance": tol,
        "checked": checked,
        "informational": info,
        "regressed": regressed,
        "improved": improved,
        "new": sorted(new),
        "missing": missing,
    }


def _load_record(path: str) -> dict:
    """A fresh record: either bench.py's own JSON line or a round
    wrapper holding it under ``parsed``."""
    with open(path) as f:
        raw = json.load(f)
    return raw.get("parsed") if isinstance(raw.get("parsed"), dict) \
        else raw


def check_repo(repo: str) -> tuple[bool, str]:
    """The lint-hook pass: committed trajectory must match a rebuild
    from the committed rounds, and the newest successful round must sit
    inside the bands derived from the rounds BEFORE it."""
    tpath = os.path.join(repo, TRAJECTORY)
    if not os.path.exists(tpath):
        return False, f"{TRAJECTORY} missing: run bench_compare.py --build"
    with open(tpath) as f:
        committed = json.load(f)
    rebuilt = build_trajectory(repo, committed.get("tolerance",
                                                   DEFAULT_TOLERANCE))
    if rebuilt != committed:
        return False, (f"{TRAJECTORY} is stale vs BENCH_r*.json: re-run "
                       "bench_compare.py --build and commit the result")
    successes = [r for r in load_rounds(repo) if r["metrics"] is not None]
    if len(successes) < 2:
        return True, ("trajectory consistent; "
                      f"{len(successes)} successful round(s) — nothing "
                      "to band against")
    latest = successes[-1]
    prior = build_trajectory_from(successes[:-1],
                                  committed.get("tolerance",
                                                DEFAULT_TOLERANCE))
    verdict = compare(latest["metrics"], latest["device_kind"], prior)
    print(json.dumps({"bench_compare": verdict}))
    if not verdict["ok"]:
        return False, (f"round {latest['file']} regressed "
                       f"{len(verdict['regressed'])} metric(s)")
    return True, (f"round {latest['file']}: {verdict['checked']} metrics "
                  "inside tolerance bands")


def build_trajectory_from(rounds: list[dict], tolerance: float) -> dict:
    """Bands over an explicit round list (the --check prior-rounds
    view)."""
    bands: dict[str, dict] = {}
    for r in rounds:
        if r["metrics"] is None:
            continue
        group = bands.setdefault(str(r["device_kind"]), {})
        for path, v in r["metrics"].items():
            entry = group.setdefault(
                path, {"last": v, "min": v, "max": v, "n": 0,
                       "direction": direction(path)})
            entry["last"] = v
            entry["min"] = min(entry["min"], v)
            entry["max"] = max(entry["max"], v)
            entry["n"] += 1
    return {"tolerance": tolerance, "rounds": [], "bands": bands}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--repo", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="repo root holding BENCH_r*.json (default: this script's)")
    ap.add_argument("--build", action="store_true",
                    help=f"rebuild {TRAJECTORY} from BENCH_r*.json")
    ap.add_argument("--check", action="store_true",
                    help="verify the committed trajectory is current and "
                         "the newest round sits in the prior bands "
                         "(the scripts/lint.sh hook)")
    ap.add_argument("--record", metavar="FILE",
                    help="compare one fresh bench record JSON against "
                         "the committed bands; prints a verdict line")
    ap.add_argument("--tolerance", type=float, default=None,
                    help="relative band width (default: the "
                         f"trajectory's, else {DEFAULT_TOLERANCE})")
    args = ap.parse_args(argv)
    if not (args.build or args.check or args.record):
        ap.error("pick one of --build / --check / --record FILE")
    if args.build:
        traj = build_trajectory(args.repo,
                                args.tolerance or DEFAULT_TOLERANCE)
        out = os.path.join(args.repo, TRAJECTORY)
        with open(out, "w") as f:
            json.dump(traj, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {out}: {len(traj['rounds'])} rounds, "
              f"{sum(len(g) for g in traj['bands'].values())} banded "
              f"metrics over {len(traj['bands'])} device kind(s)")
    if args.record:
        tpath = os.path.join(args.repo, TRAJECTORY)
        with open(tpath) as f:
            trajectory = json.load(f)
        rec = _load_record(args.record)
        verdict = compare(flatten(rec), rec.get("device_kind"),
                          trajectory, tolerance=args.tolerance)
        print(json.dumps({"bench_compare": verdict}))
        return 0 if verdict["ok"] else 1
    if args.check:
        ok, msg = check_repo(args.repo)
        print(f"bench_compare --check: {msg}")
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
