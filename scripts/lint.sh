#!/usr/bin/env bash
# graftlint wrapper: human output to the terminal, machine-readable
# findings recorded to LINT.json (counts per rule + every finding with
# its fingerprint). Exit code is graftlint's: 0 clean, 1 errors.
#
#   scripts/lint.sh                # analyze the package
#   scripts/lint.sh path/to.py     # analyze specific files/dirs
#   LINT_OUT=/tmp/l.json scripts/lint.sh
set -u
cd "$(dirname "$0")/.."

targets=("$@")
default_scope=0
if [ ${#targets[@]} -eq 0 ]; then
    targets=(chainermn_tpu/)
    default_scope=1
fi
out="${LINT_OUT:-LINT.json}"

python -m chainermn_tpu.analysis --json "${targets[@]}" > "$out"
status=$?

python -m chainermn_tpu.analysis "${targets[@]}"
echo "findings record: $out"

# cross-check the runtime sanitizer's observed lock-order graph against
# the static one (observed must be a subset). SANITIZER.json is dumped
# by the serving/fleet/dataflow tier-1 suites; only meaningful against
# the default full-package scope.
if [ "$default_scope" -eq 1 ] && [ -f SANITIZER.json ]; then
    python -m chainermn_tpu.analysis chainermn_tpu/ \
        --runtime-report SANITIZER.json || status=1
fi

# cross-check the committed bench trajectory against the per-round
# artifacts (BENCH_TRAJECTORY.json must be a faithful rebuild, and the
# newest successful round must sit inside the prior rounds' tolerance
# bands) — same stance as the sanitizer runtime report above.
if [ "$default_scope" -eq 1 ] && [ -f BENCH_TRAJECTORY.json ]; then
    python scripts/bench_compare.py --check || status=1
fi
exit $status
