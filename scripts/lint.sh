#!/usr/bin/env bash
# graftlint wrapper: human output to the terminal, machine-readable
# findings recorded to LINT.json (counts per rule + every finding with
# its fingerprint). Exit code is graftlint's: 0 clean, 1 errors.
#
#   scripts/lint.sh                # analyze the package
#   scripts/lint.sh path/to.py     # analyze specific files/dirs
#   LINT_OUT=/tmp/l.json scripts/lint.sh
set -u
cd "$(dirname "$0")/.."

targets=("$@")
if [ ${#targets[@]} -eq 0 ]; then
    targets=(chainermn_tpu/)
fi
out="${LINT_OUT:-LINT.json}"

python -m chainermn_tpu.analysis --json "${targets[@]}" > "$out"
status=$?

python -m chainermn_tpu.analysis "${targets[@]}"
echo "findings record: $out"
exit $status
