#!/usr/bin/env python
"""On-chip block-size sweep for the flash kernels (round 5 tuning).

The round-5 battery measured the kernels at ~6.5 TFLOP/s with the original
f32-precast MXU inputs and 128x128 blocks. After the storage-dtype MXU fix
(ops/flash_attention.py), this sweeps (block_q, block_k) on the real chip at
the onchip_flash timing shapes so the default can be set from data rather
than guessed: fwd+bwd ms/step and achieved TFLOP/s per cell, flash-vs-full
ratio recomputed at the winning block size.

Appends one JSON record per cell to scripts/flash_tune.jsonl as it lands
(wedge protocol). Exits 0 with a "skipped" record if no TPU is attached.
"""

import functools
import json
import os
import signal
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
OUT = os.path.join(_HERE, "flash_tune.jsonl")

from bench import enable_compilation_cache
from onchip_flash import time_grad_step  # the one shared timing idiom


def emit(rec):
    rec["t"] = round(time.time(), 1)
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)


def main():
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    deadline = time.time() + float(os.environ.get("FLASH_TUNE_BUDGET", "900"))

    import jax

    plat = os.environ.get("CHAINERMN_TPU_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    enable_compilation_cache(jax)

    import jax.numpy as jnp

    devs = jax.devices()
    if devs[0].platform != "tpu":
        emit({"test": "platform", "skipped": f"no TPU ({devs[0].platform})"})
        return
    emit({"test": "platform", "device_kind": devs[0].device_kind})

    from chainermn_tpu.ops.flash_attention import flash_attention
    from chainermn_tpu.parallel.sequence import full_attention

    rng = jax.random.PRNGKey(0)

    def mk(b, t, h, d):
        ks = jax.random.split(rng, 3)
        return tuple(
            jax.random.normal(k, (b, t, h, d), jnp.bfloat16) for k in ks
        )

    b, h, d = 1, 8, 64
    for t_len in (4096, 8192):
        q, k, v = mk(b, t_len, h, d)
        # full-attention reference under the same harness/process
        if time.time() < deadline:
            try:
                full_ms = time_grad_step(
                    functools.partial(full_attention, causal=True), q, k, v, 10)
                emit({"test": "full_ref", "seq_len": t_len, "full_ms": full_ms})
            except Exception as e:
                emit({"test": "full_ref", "seq_len": t_len,
                      "error": f"{type(e).__name__}: {e}"[:200]})
        for blk in (128, 256, 512, 1024, 2048):
            if time.time() > deadline:
                emit({"test": "tune", "seq_len": t_len, "block": blk,
                      "skipped": "budget"})
                continue
            rec = {"test": "tune", "seq_len": t_len, "block": blk}
            try:
                fn = functools.partial(flash_attention, causal=True,
                                       interpret=False, block_q=blk,
                                       block_k=blk)
                rec["flash_ms"] = time_grad_step(fn, q, k, v, 10)
                flops = 7.0 * b * h * t_len * t_len * d  # causal fwd+bwd
                rec["achieved_tflops"] = round(
                    flops / (rec["flash_ms"] / 1e3) / 1e12, 2)
            except Exception as e:
                rec["error"] = f"{type(e).__name__}: {e}"[:200]
            emit(rec)
    emit({"test": "done"})


if __name__ == "__main__":
    main()
