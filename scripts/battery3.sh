#!/bin/bash
# Round-5 battery 3: runs AFTER battery2 completes (waits on its log
# marker), in the next responsive chip window. Priority order:
#   1. bench.py headline (conv7/256, sweep off) — restores
#      scripts/last_measured.json to the flagship config after battery2's
#      space_to_depth retry overwrote it (newest-success-wins semantics).
#   2. flash_tune.py — block sweep now including 2048 cells.
#   3. onchip_flash.py — flash timing at the new block-1024 default.
#   4. aot_flash_ceiling.py — T=131072 compile check at block 1024
#      (service-side only, no device lease; still probe-gated because the
#      axon remote-compile helper wedges together with the lease).
#   5. onchip_lm.py — LAST: the one stage that hung (and wedged the
#      tunnel) in battery2; if it wedges again nothing else is lost.
# Same wedge protocol as chip_watch.sh rev2: probe between stages,
# whole-window stage gates, one attempt per stage, battery deadline.
set -u
cd /root/repo
LOG=scripts/battery3.log
START=$(date +%s)
BATTERY_DEADLINE=${BATTERY3_DEADLINE:-21600}
echo "$(date +%FT%T) battery3 start (deadline ${BATTERY_DEADLINE}s)" >> "$LOG"

# Wait for battery2 to finish so two children never share the tunnel.
while ! grep -q "battery2 done" scripts/battery2.log 2>/dev/null; do
  if [ $(( $(date +%s) - START )) -gt "$BATTERY_DEADLINE" ]; then
    echo "$(date +%FT%T) battery3 deadline passed waiting for battery2" >> "$LOG"
    exit 0
  fi
  sleep 120
done
echo "$(date +%FT%T) battery2 done observed" >> "$LOG"

probe() {
  # -k 30: a wedged probe can defer TERM inside the remote C call
  # (PERF.md window 2) — without the KILL escalation the probe, and with
  # it the whole battery, would hang past its deadline.
  timeout -k 30 -s TERM 90 python -c "import jax; d=jax.devices(); assert d[0].platform=='tpu', d" >/dev/null 2>&1
}

can_fit() {
  [ $(( BATTERY_DEADLINE - ( $(date +%s) - START ) )) -ge "$1" ]
}

wait_alive() {
  while true; do
    if [ $(( $(date +%s) - START )) -gt "$BATTERY_DEADLINE" ]; then
      echo "$(date +%FT%T) battery3 deadline passed" >> "$LOG"
      return 1
    fi
    if probe; then return 0; fi
    echo "$(date +%FT%T) probe wedged" >> "$LOG"
    sleep 240
  done
}

if wait_alive && can_fit 2700; then
  echo "$(date +%FT%T) CHIP ALIVE — bench headline conv7/256" >> "$LOG"
  ( CHAINERMN_TPU_BENCH_SWEEP=0 CHAINERMN_TPU_BENCH_STEPS=50 \
    CHAINERMN_TPU_BENCH_ATTEMPTS=1 CHAINERMN_TPU_BENCH_TIMEOUT=2400 \
    CHAINERMN_TPU_BENCH_TOTAL_BUDGET=2500 \
    timeout -k 120 -s TERM 2700 python bench.py > scripts/bench3.json 2>> "$LOG"; \
    echo "$(date +%FT%T) bench rc=$?" >> "$LOG" )
fi

if wait_alive && can_fit 1500; then
  echo "$(date +%FT%T) CHIP ALIVE — flash_tune (incl. 2048)" >> "$LOG"
  ( FLASH_TUNE_BUDGET=1300 timeout -k 120 -s TERM 1500 python scripts/flash_tune.py >> "$LOG" 2>&1; \
    echo "$(date +%FT%T) flash_tune rc=$?" >> "$LOG" )
fi

if wait_alive && can_fit 1700; then
  echo "$(date +%FT%T) CHIP ALIVE — onchip_flash (block-1024 default)" >> "$LOG"
  ( ONCHIP_FLASH_BUDGET=1500 timeout -k 120 -s TERM 1700 python scripts/onchip_flash.py >> "$LOG" 2>&1; \
    echo "$(date +%FT%T) onchip_flash rc=$?" >> "$LOG" )
fi

if wait_alive && can_fit 2000; then
  echo "$(date +%FT%T) CHIP ALIVE — aot_flash_ceiling (block 1024)" >> "$LOG"
  ( AOT_CEILING_SKIP_RECORDED=1 timeout -k 120 -s TERM 2000 python scripts/aot_flash_ceiling.py >> "$LOG" 2>&1; \
    echo "$(date +%FT%T) aot_ceiling rc=$?" >> "$LOG" )
fi

if wait_alive && can_fit 1700; then
  echo "$(date +%FT%T) CHIP ALIVE — onchip_lm (wedge suspect, last)" >> "$LOG"
  ( ONCHIP_LM_BUDGET=1500 timeout -k 120 -s TERM 1700 python scripts/onchip_lm.py >> "$LOG" 2>&1; \
    echo "$(date +%FT%T) onchip_lm rc=$?" >> "$LOG" )
fi
echo "$(date +%FT%T) battery3 done" >> "$LOG"
