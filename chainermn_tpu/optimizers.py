"""Multi-node optimizer wrappers — the data-parallel hot path.

Re-design of ``[U] chainermn/optimizers.py`` (SURVEY.md S2.12 — unverified
cite). The reference wraps any Chainer optimizer so that ``update()`` runs
forward/backward, then ``comm.allreduce_grad(model)``, then the inner
optimizer; its double-buffering variant overlaps the allreduce of step t-1's
gradients with step t's backward on a side thread + CUDA stream.

The TPU mapping: the optimizer protocol here is **optax** (pure functional
GradientTransformations), and the wrapper is itself a GradientTransformation
that inserts the cross-rank gradient mean before the inner update. Because
the whole train step — backward, mean, update — is ONE jitted program, XLA's
scheduler overlaps the gradient collective with independent compute
automatically; the double-buffering option additionally gives the scheduler a
full step of slack by applying one-step-stale means, the same staleness
semantics as the reference (without threads: the stale mean is carried in the
optimizer state, so the current step's psum has no consumer inside its own
step and can run entirely behind the backward).

Usage (the canonical shard_map data-parallel step; see examples/mnist):

    opt = create_multi_node_optimizer(optax.sgd(0.1), comm)
    state = opt.init(params)
    def train_step(params, state, batch):          # traced under comm.shard_map
        grads = jax.grad(loss_fn)(params, batch)   # local microbatch grads
        updates, state = opt.update(grads, state, params)  # mean + inner opt
        return optax.apply_updates(params, updates), state
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import optax

from chainermn_tpu.communicators.communicator_base import CommunicatorBase


class _DoubleBufferState(NamedTuple):
    inner: Any
    stale_mean: Any  # step t-1's averaged gradients (zeros before step 1)


def create_multi_node_optimizer(
    actual_optimizer: optax.GradientTransformation,
    communicator: CommunicatorBase,
    double_buffering: bool = False,
    zero_fill: bool = False,
) -> optax.GradientTransformation:
    """Wrap an optax optimizer with cross-rank gradient averaging.

    Args mirror the reference's ``create_multi_node_optimizer(actual_optimizer,
    communicator, double_buffering)``; ``zero_fill`` is accepted for signature
    parity (jax.grad never yields missing gradient entries).

    The returned transformation must be used inside a step traced over the
    communicator's mesh (``comm.shard_map``), where the gradient mean lowers
    to the strategy's ICI collective and fuses into the program.
    """
    if double_buffering:
        return _double_buffering_optimizer(actual_optimizer, communicator, zero_fill)

    def init(params):
        return actual_optimizer.init(params)

    def update(grads, state, params=None):
        mean = communicator.multi_node_mean_grad(grads, zero_fill)
        return actual_optimizer.update(mean, state, params)

    return optax.GradientTransformation(init, update)


def _double_buffering_optimizer(
    actual_optimizer: optax.GradientTransformation,
    communicator: CommunicatorBase,
    zero_fill: bool,
) -> optax.GradientTransformation:
    """One-step-stale gradient averaging (reference ``_DoubleBufferingOptimizer``,
    pure_nccl-only; here strategy-agnostic).

    Step t applies the mean of step t-1's gradients while step t's mean is
    being produced — inside one XLA program the current psum has no in-step
    consumer, so the scheduler runs it concurrently with the update math and
    the next step's forward/backward dispatch. Semantics match the reference:
    updates lag one step; the first step applies zero updates.
    """

    def init(params):
        zeros = jax.tree_util.tree_map(jax.numpy.zeros_like, params)
        return _DoubleBufferState(
            inner=actual_optimizer.init(params), stale_mean=zeros,
        )

    def update(grads, state, params=None):
        fresh_mean = communicator.multi_node_mean_grad(grads, zero_fill)
        # Apply the stale mean; it is zeros before step 1, so the first
        # update is a no-op by construction.
        updates, inner = actual_optimizer.update(state.stale_mean, state.inner, params)
        return updates, _DoubleBufferState(inner=inner, stale_mean=fresh_mean)

    return optax.GradientTransformation(init, update)


def create_component_wise_optimizer(
    actual_optimizer: optax.GradientTransformation,
) -> optax.GradientTransformation:
    """Apply an optimizer independently per component of a
    ``MultiNodeChainList`` params list.

    Needed because each component's params are *committed* to its rank's
    device; a single optax update over the whole list would jit mixed-device
    arguments and fail. Per-component application keeps every update on its
    owner device — the reference has the same structure implicitly (each
    process's optimizer only sees its local sub-model, SURVEY.md S2.11/S2.12).
    """

    def init(params_list):
        return [actual_optimizer.init(p) for p in params_list]

    def update(grads_list, state_list, params_list=None):
        if params_list is None:
            params_list = [None] * len(grads_list)
        updates, new_states = [], []
        for g, s, p in zip(grads_list, state_list, params_list):
            u, ns = actual_optimizer.update(g, s, p)
            updates.append(u)
            new_states.append(ns)
        return updates, new_states

    return optax.GradientTransformation(init, update)


def wait_double_buffering(state: _DoubleBufferState) -> Any:
    """Flush helper: the stale mean still pending in ``state`` (apply it
    manually after the last step if you need exact parity with non-buffered
    training; the reference similarly waits out the background allreduce at
    the end of training)."""
    return state.stale_mean
