"""Multi-node optimizer wrappers — the data-parallel hot path.

Re-design of ``[U] chainermn/optimizers.py`` (SURVEY.md S2.12 — unverified
cite). The reference wraps any Chainer optimizer so that ``update()`` runs
forward/backward, then ``comm.allreduce_grad(model)``, then the inner
optimizer; its double-buffering variant overlaps the allreduce of step t-1's
gradients with step t's backward on a side thread + CUDA stream.

The TPU mapping: the optimizer protocol here is **optax** (pure functional
GradientTransformations), and the wrapper is itself a GradientTransformation
that inserts the cross-rank gradient mean before the inner update. Because
the whole train step — backward, mean, update — is ONE jitted program, XLA's
scheduler overlaps the gradient collective with independent compute
automatically; the double-buffering option additionally gives the scheduler a
full step of slack by applying one-step-stale means, the same staleness
semantics as the reference (without threads: the stale mean is carried in the
optimizer state, so the current step's psum has no consumer inside its own
step and can run entirely behind the backward).

Usage (the canonical shard_map data-parallel step; see examples/mnist):

    opt = create_multi_node_optimizer(optax.sgd(0.1), comm)
    state = opt.init(params)
    def train_step(params, state, batch):          # traced under comm.shard_map
        def loss_fn(p):
            # define the GLOBAL objective: shard_map auto-psums the backward
            # wrt the replicated params, so grads arrive as the exact global
            # gradient and the wrapper passes them through
            return comm.allreduce(local_loss(p, batch), "mean")
        grads = jax.grad(loss_fn)(params)
        updates, state = opt.update(grads, state, params)  # mean + inner opt
        return optax.apply_updates(params, updates), state

(Alternatively compute only the LOCAL loss and differentiate wrt a varying
view — ``jax.lax.pcast(params, comm.axis_name, to="varying")`` — so the
wrapper's strategy collective performs the one cross-rank mean; that is what
``chainermn_tpu.training.jit_train_step`` does, and it is the path that
honors ``allreduce_grad_dtype``/packing. Do NOT mix the two: a local-mean
loss with invariant params computes the gradient of the SUM of local losses,
an effective lr scale of ``comm.size``.)
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import optax

from chainermn_tpu.communicators.communicator_base import CommunicatorBase


class _DoubleBufferState(NamedTuple):
    inner: Any
    stale_mean: Any  # step t-1's averaged gradients (zeros before step 1)


def create_multi_node_optimizer(
    actual_optimizer: optax.GradientTransformation,
    communicator: CommunicatorBase,
    double_buffering: bool = False,
    zero_fill: bool = False,
) -> optax.GradientTransformation:
    """Wrap an optax optimizer with cross-rank gradient averaging.

    Args mirror the reference's ``create_multi_node_optimizer(actual_optimizer,
    communicator, double_buffering)``; ``zero_fill`` is accepted for signature
    parity (jax.grad never yields missing gradient entries).

    The returned transformation must be used inside a step traced over the
    communicator's mesh (``comm.shard_map``), where the gradient mean lowers
    to the strategy's ICI collective and fuses into the program.
    """
    if double_buffering:
        return _double_buffering_optimizer(actual_optimizer, communicator, zero_fill)

    def init(params):
        return actual_optimizer.init(params)

    def update(grads, state, params=None):
        mean = communicator.multi_node_mean_grad(grads, zero_fill)
        return actual_optimizer.update(mean, state, params)

    return optax.GradientTransformation(init, update)


def _double_buffering_optimizer(
    actual_optimizer: optax.GradientTransformation,
    communicator: CommunicatorBase,
    zero_fill: bool,
) -> optax.GradientTransformation:
    """One-step-stale gradient averaging (reference ``_DoubleBufferingOptimizer``,
    pure_nccl-only; here strategy-agnostic).

    Step t applies the mean of step t-1's gradients while step t's mean is
    being produced — inside one XLA program the current psum has no in-step
    consumer, so the scheduler runs it concurrently with the update math and
    the next step's forward/backward dispatch. Semantics match the reference:
    updates lag one step; the first step applies zero updates.
    """

    def init(params):
        zeros = jax.tree_util.tree_map(jax.numpy.zeros_like, params)
        return _DoubleBufferState(
            inner=actual_optimizer.init(params), stale_mean=zeros,
        )

    def update(grads, state, params=None):
        fresh_mean = communicator.multi_node_mean_grad(grads, zero_fill)
        # Apply the stale mean; it is zeros before step 1, so the first
        # update is a no-op by construction.
        updates, inner = actual_optimizer.update(state.stale_mean, state.inner, params)
        return updates, _DoubleBufferState(inner=inner, stale_mean=fresh_mean)

    return optax.GradientTransformation(init, update)


def create_component_wise_optimizer(
    actual_optimizer: optax.GradientTransformation,
) -> optax.GradientTransformation:
    """Apply an optimizer independently per component of a
    ``MultiNodeChainList`` params list.

    Needed because each component's params are *committed* to its rank's
    device; a single optax update over the whole list would jit mixed-device
    arguments and fail. Per-component application keeps every update on its
    owner device — the reference has the same structure implicitly (each
    process's optimizer only sees its local sub-model, SURVEY.md S2.11/S2.12).
    """

    def init(params_list):
        return [actual_optimizer.init(p) for p in params_list]

    def update(grads_list, state_list, params_list=None):
        if params_list is None:
            params_list = [None] * len(grads_list)
        updates, new_states = [], []
        for g, s, p in zip(grads_list, state_list, params_list):
            u, ns = actual_optimizer.update(g, s, p)
            updates.append(u)
            new_states.append(ns)
        return updates, new_states

    return optax.GradientTransformation(init, update)


class ZeroOptimizer(NamedTuple):
    """``optax.GradientTransformation``-shaped tuple with the extra
    ``state_spec`` the train-step builders use to shard the optimizer state
    over the mesh (duck-types as a GradientTransformation)."""

    init: Any
    update: Any
    state_spec: Any  # PartitionSpec for every state leaf (rank-major)
    # The update gather is a true all_gather (wire-optimal: 1x param bytes
    # vs 2x for a psum of zero-placed shards), whose output JAX's static
    # replication (VMA) system conservatively marks 'varying' even though
    # every rank provably holds the same values. Step builders read this
    # flag and build the shard_map with check_vma=False; semantics are
    # unchanged, only the static replication check is off.
    check_vma: bool = False


def create_zero_optimizer(
    actual_optimizer: optax.GradientTransformation,
    communicator: CommunicatorBase,
    wire_dtype: Optional[Any] = None,
) -> ZeroOptimizer:
    """ZeRO-1: shard optimizer state over the data-parallel axis.

    TPU-idiomatic extension BEYOND the reference (SURVEY.md S2.16 marks
    sharded optimizer states absent upstream: grads and moments are
    replicated there). Per step, inside the traced program:

    1. local gradients are flattened — in the **wire dtype** — and
       ``psum_scatter``'d: each rank receives the cross-rank MEAN of its own
       1/n slice of the parameter vector (same wire bytes as one allreduce's
       reduce half);
    2. the inner optimizer updates only that slice **in f32** (moments are
       always f32 regardless of wire dtype), stored rank-major ``[n, shard]``
       and sharded over the mesh — per-device optimizer memory is ``full/n``
       (the ZeRO-1 saving);
    3. the update shards are cast back to the wire dtype and ``all_gather``'d
       so parameters stay replicated.

    The wire dtype — the dtype both collectives move — resolves as:
    explicit ``wire_dtype`` arg > the communicator's ``allreduce_grad_dtype``
    (the reference's compressed-allreduce knob) > the common dtype of the
    gradient leaves (``jnp.result_type``), so all-bf16 gradients ride the
    wire in bf16 (half the bytes) without any flag.

    Constraints: the inner optimizer must be *elementwise* (sgd, momentum,
    adam(w), rmsprop... — anything whose update for parameter i depends only
    on grad/param/moment i). Plain global-statistic transforms (e.g.
    ``optax.clip_by_global_norm``) would compute shard-local statistics —
    use :func:`clip_by_global_norm_sharded` (psums the squared norm across
    shards) for gradient clipping, or compose other global transforms
    outside. Requires a flat (single-axis, unsplit) communicator.

    Use with ``jit_train_step(model, opt, comm)`` (it reads ``state_spec``)
    and place the initial state with
    ``jax.device_put(opt.init(params), comm.named_sharding(*opt.state_spec))``.
    """
    import jax.numpy as jnp
    from jax import lax

    axis = communicator.axis_name
    if not isinstance(axis, str):
        raise ValueError(
            "create_zero_optimizer needs a flat single-axis communicator "
            f"(got axes {axis!r}); hierarchical meshes would scatter over "
            "a tuple axis — flatten first"
        )
    if getattr(communicator, "_groups", None) is not None:
        raise ValueError("create_zero_optimizer does not support split() "
                         "sub-communicators")
    n = communicator.size
    if wire_dtype is None:
        wire_dtype = getattr(communicator, "allreduce_grad_dtype", None)
    if wire_dtype is not None:
        wire_dtype = jnp.dtype(wire_dtype)

    def _wire(tree):
        """The dtype the collectives move for this gradient tree."""
        if wire_dtype is not None:
            return wire_dtype
        return jnp.result_type(*jax.tree_util.tree_leaves(tree))

    def _flatten(tree, dtype):
        leaves = jax.tree_util.tree_leaves(tree)
        flat = jnp.concatenate([l.ravel().astype(dtype) for l in leaves])
        pad = (-flat.size) % n
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        return flat  # [n * shard_len]

    def _unflatten(flat, tree):
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        out, off = [], 0
        for l in leaves:
            out.append(flat[off:off + l.size].reshape(l.shape).astype(l.dtype))
            off += l.size
        return jax.tree_util.tree_unflatten(treedef, out)

    def init(params):
        """Host-side: inner state over the rank-major [n, shard] gradient
        layout (f32 — moments stay full precision whatever the wire dtype);
        every leaf is given a leading rank axis so ONE spec shards the whole
        state."""
        flat = _flatten(params, jnp.float32)
        shards = flat.reshape(n, flat.size // n)
        inner = actual_optimizer.init(shards)
        return jax.tree_util.tree_map(
            lambda l: (l if l.ndim >= 1 and l.shape[0] == n
                       else jnp.broadcast_to(l, (n,) + jnp.shape(l))),
            inner,
        )

    def update(grads, state, params=None):
        wire = _wire(grads)
        flat_g = _flatten(grads, wire)
        shard_len = flat_g.size // n
        # cross-rank mean of MY slice only (reduce half of an allreduce),
        # moved in the wire dtype — bf16 grads pay bf16 bytes
        g_shard = lax.psum_scatter(flat_g, axis, scatter_dimension=0,
                                   tiled=True) / n
        idx = communicator.axis_index()
        p_shard = None
        if params is not None:
            p_shard = lax.dynamic_slice(
                _flatten(params, jnp.float32), (idx * shard_len,), (shard_len,)
            )
        # local view of the sharded state: [1, ...] -> drop the rank axis;
        # the inner optimizer runs in f32 whatever the wire dtype
        local = jax.tree_util.tree_map(lambda l: l[0], state)
        upd_shard, new_local = actual_optimizer.update(
            g_shard.astype(jnp.float32), local, p_shard
        )
        new_state = jax.tree_util.tree_map(lambda l: l[None], new_local)
        # gather the disjoint update shards back so params stay replicated —
        # a true all_gather in the wire dtype (1x wire-dtype param bytes; see
        # check_vma note on ZeroOptimizer for why the step runs with the
        # static replication check off)
        flat_u = lax.all_gather(upd_shard.astype(wire), axis, tiled=True)
        return _unflatten(flat_u, grads), new_state

    from jax.sharding import PartitionSpec as P

    return ZeroOptimizer(init=init, update=update, state_spec=P(axis))


def clip_by_global_norm_sharded(
    max_norm: float,
    communicator: CommunicatorBase,
) -> optax.GradientTransformation:
    """``optax.clip_by_global_norm`` whose norm is the TRUE global norm when
    the gradients it sees are 1/n shards.

    Inside :func:`create_zero_optimizer`, the inner chain runs on each
    rank's parameter-vector shard — plain ``optax.clip_by_global_norm``
    there would clip by the *shard-local* norm (the documented ZeRO
    constraint against global-statistic transforms). This transform psums
    the squared norm over the communicator's axis first, so::

        create_zero_optimizer(
            optax.chain(clip_by_global_norm_sharded(1.0, comm),
                        optax.adam(lr)),
            comm)

    clips identically to replicated ``optax.chain(clip_by_global_norm(1.0),
    adam(lr))`` — pinned in tests. Outside a traced mesh context it is an
    error (the psum needs the axis); use plain optax clipping for
    replicated gradients.

    Composed against REPLICATED gradients inside a traced step (e.g. under
    ``create_multi_node_optimizer`` instead of ``create_zero_optimizer``):
    with replication tracking on (``check_vma=True``, the default) the
    transform detects invariant leaves via their varying-manner set and
    divides their contribution by the axis size, so the norm stays exact.
    With ``check_vma=False`` that information does not exist — the psum
    then sums n identical replicas and clips by a sqrt(n)-inflated norm
    with no error; keep this transform inside the ZeRO chain there.
    """
    import jax.numpy as jnp

    def init(params):
        del params
        return optax.EmptyState()

    def update(updates, state, params=None):
        del params
        leaves = jax.tree_util.tree_leaves(updates)
        vmas = [frozenset(getattr(jax.typeof(g), "vma", frozenset()) or ())
                for g in leaves]
        # vma-aware over-count correction: a leaf NOT varying over a reduce
        # axis is replicated there — the psum would sum n identical copies
        # and inflate the norm by sqrt(n) (silent over-clip when this
        # transform is composed outside its ZeRO home, e.g. under
        # create_multi_node_optimizer). With replication tracking active
        # (any leaf carries vma), divide each leaf's contribution by the
        # sizes of the axes it is invariant over; with tracking off
        # (check_vma=False — vma sets all empty) the correction cannot be
        # inferred and the caller owns the contract (docstring).
        axes = communicator.axis_name
        axes = (axes,) if isinstance(axes, str) else tuple(axes)
        # detect whether replication tracking is live: a pcast probe gets a
        # varying-manner set iff check_vma is on (any(vmas) alone misses
        # the ALL-replicated case, which is exactly the over-clip hazard)
        try:
            probe = jax.lax.pcast(jnp.zeros(()), axes, to="varying")
            tracking = bool(frozenset(getattr(jax.typeof(probe), "vma",
                                              frozenset()) or ()))
        except Exception:
            tracking = any(vmas)

        # split() sub-communicators reduce over their GROUP, not the full
        # mesh axis — the replica count for an invariant leaf is the group
        # size there (comm.size), the axis extent otherwise
        group = (communicator.size
                 if getattr(communicator, "_groups", None) is not None
                 else None)

        def leaf_sq(g, vma):
            s = jnp.sum(jnp.square(g.astype(jnp.float32)))
            if tracking:
                for ax in axes:
                    if ax not in vma:
                        s = s / (group if group is not None
                                 else communicator.mesh.shape[ax])
            return s

        local_sq = sum(leaf_sq(g, v) for g, v in zip(leaves, vmas))
        # through the communicator, not a raw lax.psum: split()
        # sub-communicators then reduce over THEIR group only, and
        # multi-axis meshes reduce over all their axes
        gn = jnp.sqrt(communicator.allreduce(local_sq, "sum"))
        # optax semantics: scale by max_norm/gn only when gn > max_norm
        scale = jnp.where(gn > max_norm, max_norm / gn, 1.0)
        return (
            jax.tree_util.tree_map(lambda g: (g * scale).astype(g.dtype), updates),
            state,
        )

    return optax.GradientTransformation(init, update)


def wait_double_buffering(state: _DoubleBufferState) -> Any:
    """Flush helper: the stale mean still pending in ``state`` (apply it
    manually after the last step if you need exact parity with non-buffered
    training; the reference similarly waits out the background allreduce at
    the end of training)."""
    return state.stale_mean
