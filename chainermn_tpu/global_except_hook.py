"""Fail-fast global exception hook.

Re-design of ``[U] chainermn/global_except_hook.py`` (SURVEY.md S2.14/S3.5 —
unverified cite). Reference behavior: a ``sys.excepthook`` that prints the
traceback and calls ``MPI_Abort`` on COMM_WORLD so one rank's Python
exception kills the whole job instead of leaving the other ranks deadlocked
inside a collective.

TPU mapping: XLA collectives hang across processes exactly the way
NCCL/MPI ones do. The abort primitive here is a hard process exit
(``os._exit``) after flushing the traceback — in a multi-process
``jax.distributed`` job the coordination service notices the death and the
job scheduler tears down the remaining workers (the barrier-timeout path),
which is the strongest abort available without an MPI runtime. Install is
idempotent and chainable (the previous hook still runs first).
"""

from __future__ import annotations

import os
import sys
import traceback

_installed = False


def _make_hook(prev_hook, exit_code: int):
    def _global_except_hook(exctype, value, tb):
        try:
            rank = os.environ.get("JAX_PROCESS_INDEX", os.environ.get("RANK", "?"))
            sys.stderr.write(
                f"chainermn_tpu: uncaught exception on process {rank} — "
                "aborting the job to avoid deadlocked collectives\n"
            )
            if prev_hook not in (None, sys.__excepthook__):
                prev_hook(exctype, value, tb)  # prior hook owns the printing
            else:
                traceback.print_exception(exctype, value, tb)
            # Flight recorder: if the monitor subsystem was in use, append
            # the last events + device memory so the crash record says what
            # the process was doing, not just where it raised. Only when
            # already imported — a bare crash must not drag telemetry in.
            # once="failure": a Watchdog fire or resilient-trainer boundary
            # that already dumped this episode suppresses this layer's dump.
            mon = sys.modules.get("chainermn_tpu.monitor")
            if mon is not None:
                try:
                    log = mon.get_event_log()
                    if len(log):
                        log.dump(file=sys.stderr, once="failure")
                except Exception:
                    pass
            sys.stderr.flush()
            sys.stdout.flush()
        finally:
            # the MPI_Abort analog: die hard, never hang in atexit/teardown
            os._exit(exit_code)

    return _global_except_hook


def add_hook(exit_code: int = 1) -> None:
    """Install the hook (reference ``add_hook``). Idempotent.

    Enabled automatically at import when ``CHAINERMN_TPU_GLOBAL_EXCEPT_HOOK=1``
    (the reference gates on an env var likewise). Only meaningful in
    multi-process jobs; in single-process runs a normal traceback+exit
    happens anyway, so the hook is harmless.
    """
    global _installed
    if _installed:
        return
    sys.excepthook = _make_hook(sys.excepthook, exit_code)
    _installed = True


if os.environ.get("CHAINERMN_TPU_GLOBAL_EXCEPT_HOOK", "0") == "1":
    add_hook()
