"""Elastic resharded restore: snapshot on mesh (d1, m1), resume on (d2, m2).

The communicator thesis says the worker set is a deployment detail; this
module makes checkpoints honor it. A :class:`~chainermn_tpu.extensions
.sharded_checkpoint.ShardedCheckpointer` snapshot already restores onto
whatever shardings the restore *template* declares (orbax gathers or
slices each leaf onto the target layout), so a pure mesh-shape change —
8-way DP to 4-way DP, flat to dp×tp — needs no manual shard surgery at
all. What orbax cannot know about are the two pieces of save-time
*semantics*:

- **TP-degree layout**: the fused qkv kernel's column order bakes the
  tensor-axis size into the stored weights (see
  :func:`~chainermn_tpu.parallel.reshard_tp_qkv`). A degree change must
  permute through the canonical head order — and because optax moments
  mirror the params tree structure, the SAME permutation applies to the
  whole train state (Adam's m/v for a qkv kernel live on identically
  shaped, identically scrambled leaves).
- **DP optimizer wrapping**: :func:`~chainermn_tpu.optimizers
  .create_multi_node_optimizer`'s plain-mode state is the inner optax
  state (mesh-agnostic) — re-wrapping for the new world is rebuilding
  the wrapper around the NEW communicator and using its ``init(params)``
  as the restore template; :func:`restore_train_state` packages exactly
  that. (ZeRO state is rank-major ``[n, shard]`` and is NOT elastically
  reshardable across world sizes — restore it at the same size, or
  checkpoint the gathered inner state instead.)

:func:`elastic_restore` reads the save-time TP degree from the
checkpoint's manifest sidecar, routes degree changes through a
replicated gather → permute → re-slice, and degrades to the plain
(bit-exact when the mesh is unchanged) path otherwise. The
``deploy.reshard`` fault cut-point covers the whole decision.

Import hygiene: jax / extensions / parallel load lazily inside functions
— pinned by ``test_import_hygiene.py``.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple


def snapshot_meta(*, comm=None, model=None, **extra) -> dict:
    """Build the manifest dict a resharding restore needs, for
    ``ShardedCheckpointer.save(step, state, meta=...)``: mesh shape and
    axis names (from ``comm``), TP degree and head geometry (from
    ``model`` + ``comm``). Extra keys pass through."""
    meta = dict(extra)
    mesh = getattr(comm, "mesh", None) if comm is not None else None
    if mesh is not None:
        meta["mesh_shape"] = tuple(int(s) for s in mesh.devices.shape)
        meta["mesh_axes"] = tuple(str(a) for a in mesh.axis_names)
    if model is not None:
        meta["n_heads"] = int(model.n_heads)
        meta["d_head"] = int(model.d_model) // int(model.n_heads)
        meta["tp_degree"] = _tp_degree(model, mesh)
    return meta


def _tp_degree(model, mesh) -> int:
    axis = getattr(model, "tensor_axis", None)
    if axis is None or mesh is None:
        return 1
    return int(mesh.shape[axis])


def _template_mesh(template):
    """The mesh of the restore target, read off the first NamedSharding
    leaf — elastic restore re-slices onto THIS mesh."""
    import jax

    for leaf in jax.tree_util.tree_leaves(template):
        sh = getattr(leaf, "sharding", None)
        if sh is not None and getattr(sh, "mesh", None) is not None:
            return sh.mesh
    return None


def elastic_restore(
    checkpointer, template: Any, *, comm=None, model=None,
    step: Optional[int] = None, tp_degree: Optional[int] = None,
    n_heads: Optional[int] = None, d_head: Optional[int] = None,
) -> Tuple[Optional[Any], Optional[int]]:
    """Restore the newest (or ``step``-pinned) snapshot onto ``template``'s
    mesh/shardings, which may differ from the save-time world.

    Returns ``(state, step)`` or ``(None, None)`` when no snapshot
    exists. The target TP degree comes from ``model`` + ``comm`` (or an
    explicit ``tp_degree``); the save-time degree and head geometry come
    from the snapshot's manifest (saved via :func:`snapshot_meta`) with
    the explicit ``n_heads``/``d_head`` arguments as fallback. When the
    degrees agree — including manifest-less legacy snapshots — this is
    exactly ``maybe_restore`` (bit-exact on an unchanged mesh); when
    they differ, every leaf is gathered replicated, the qkv column
    permutation is applied to the WHOLE tree (optimizer moments mirror
    the params structure), and the result is re-sliced onto the
    template's shardings.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from chainermn_tpu.resilience.cutpoints import DEPLOY_RESHARD
    from chainermn_tpu.resilience.faults import inject

    mesh = getattr(comm, "mesh", None) if comm is not None else None
    if mesh is None:
        mesh = _template_mesh(template)
    new_tp = (int(tp_degree) if tp_degree is not None
              else _tp_degree(model, mesh))

    manifest = checkpointer.manifest(step) or {}
    old_tp = int(manifest.get("tp_degree", new_tp))
    heads = n_heads if n_heads is not None else manifest.get("n_heads")
    dh = d_head if d_head is not None else manifest.get("d_head")
    if heads is None and model is not None:
        heads = int(model.n_heads)
        dh = int(model.d_model) // int(model.n_heads)

    inject(DEPLOY_RESHARD, old_tp=old_tp, new_tp=new_tp)

    if old_tp == new_tp:
        return checkpointer.maybe_restore(template, step=step)

    if heads is None or dh is None:
        raise ValueError(
            f"elastic restore across TP degrees ({old_tp} -> {new_tp}) "
            "needs the head geometry — save with meta=snapshot_meta(...) "
            "or pass n_heads/d_head explicitly")
    if mesh is None:
        raise ValueError(
            "elastic restore needs a target mesh — pass comm= or a "
            "template whose leaves carry NamedShardings")

    from chainermn_tpu.parallel import reshard_tp_qkv

    # 1. gather: restore every leaf replicated on the TARGET mesh (the
    # permutation needs whole rows, and a replicated gather is what
    # SNIPPETS' shard/gather-fn pair does leaf-by-leaf)
    replicated = NamedSharding(mesh, P())
    state, got_step = checkpointer.maybe_restore(
        template, shardings=replicated, step=step)
    if state is None:
        return None, None
    # 2. permute: old degree's (rank, 3, lh, dh) column order -> new
    # degree's, through the canonical head order
    state = reshard_tp_qkv(state, int(heads), int(dh), old_tp, new_tp)
    # 3. re-slice: commit each leaf onto the template's target sharding.
    # Only NamedSharding leaves (mesh-placed) are re-sliced — template
    # leaves that came out of a plain jit (e.g. optax's count scalar,
    # single-device and uncommitted) stay replicated on the target mesh,
    # which is compatible with the mesh-committed params; committing
    # them to the template's single device would wedge the train step.
    def _reslice(leaf, tmpl):
        sh = getattr(tmpl, "sharding", None)
        if sh is not None and getattr(sh, "mesh", None) is not None:
            return jax.device_put(leaf, sh)
        return leaf

    state = jax.tree_util.tree_map(_reslice, state, template)
    return state, got_step


def restore_train_state(
    checkpointer, *, params_template, optimizer, comm=None, model=None,
    step: Optional[int] = None, extra: Optional[dict] = None,
) -> Tuple[Optional[dict], Optional[int]]:
    """Elastic restore of the standard ``{"params", "opt"}`` train state,
    with the DP optimizer re-wrap folded in: ``optimizer`` is the NEW
    world's wrapper (``create_multi_node_optimizer(inner, new_comm)``)
    and its ``init(params_template)`` supplies the opt-state template —
    plain-mode multi-node state IS the inner optax state, so the saved
    moments restore directly onto the new wrapper. ``extra`` adds more
    like-sharded template entries (e.g. ``{"it": ...}``)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    opt_template = optimizer.init(params_template)
    # optimizer.init runs on the host: its fresh leaves (Adam's count/mu/nu)
    # land on the default device, and restoring onto single-device
    # shardings would commit them there — incompatible with the
    # mesh-committed params in one jitted step. Plain-mode multi-node
    # state is replicated, so re-lay the opt template on the target mesh.
    mesh = getattr(comm, "mesh", None) if comm is not None else None
    if mesh is None:
        mesh = _template_mesh(params_template)
    if mesh is not None:
        opt_template = jax.device_put(
            opt_template, NamedSharding(mesh, P()))
    template = {"params": params_template, "opt": opt_template}
    if extra:
        template.update(extra)
    return elastic_restore(checkpointer, template, comm=comm, model=model,
                           step=step)


__all__ = ["elastic_restore", "restore_train_state", "snapshot_meta"]
