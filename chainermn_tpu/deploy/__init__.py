"""``chainermn_tpu.deploy`` — the weight lifecycle subsystem.

Checkpoints as the deployment substrate (ROADMAP items 4 and 5), two
halves over one versioned-weights abstraction
(:mod:`~chainermn_tpu.deploy.versions`):

- **Elastic restore** (:mod:`~chainermn_tpu.deploy.reshard`): resume a
  snapshot saved on mesh (d1, m1) onto mesh (d2, m2) — orbax re-lays
  each leaf onto the target shardings, the TP qkv permutation and the
  DP optimizer re-wrap handle the save-time semantics orbax can't see.
- **Hot-swap** (:mod:`~chainermn_tpu.deploy.publish`): commit new
  weights into a live :class:`~chainermn_tpu.serving.engine
  .ServingEngine` with zero recompiles and zero dropped requests,
  behind the scheduler's version fence;
  :meth:`~chainermn_tpu.fleet.router.FleetRouter.publish` rolls the
  same swap replica-by-replica across a fleet.

Import hygiene: like :mod:`~chainermn_tpu.fleet`, every module here
imports jax / serving / extensions lazily inside functions — importing
``chainermn_tpu.deploy`` is a pure host-logic import.
"""

from chainermn_tpu.deploy.publish import (
    PublishError,
    SwapHandle,
    WeightPublisher,
)
from chainermn_tpu.deploy.reshard import (
    elastic_restore,
    restore_train_state,
    snapshot_meta,
)
from chainermn_tpu.deploy.versions import VersionLog, WeightVersion

__all__ = [
    "PublishError",
    "SwapHandle",
    "VersionLog",
    "WeightPublisher",
    "WeightVersion",
    "elastic_restore",
    "restore_train_state",
    "snapshot_meta",
]
