"""Versioned-weights bookkeeping shared by both halves of the deploy layer.

A weight *version* is a monotonically increasing integer scoped to one
``ServingEngine``: version 0 is whatever the engine was constructed with,
and every successful :class:`~chainermn_tpu.deploy.publish.WeightPublisher`
commit (or elastic restore into a spawned replica) bumps it by one. The
number is deliberately engine-local — a fleet rolling through a publish has
replicas briefly on different versions, and the router's report exposes
exactly that skew rather than pretending to a global counter.

The :class:`VersionLog` is the host-side audit trail: who published which
version, from where (``init`` / ``publish`` / ``restore``), at which train
step. It is plain host state (no jax import) so the fleet/router layer can
read it without touching the device stack.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass(frozen=True)
class WeightVersion:
    """One committed weight set, as seen by one engine."""

    version: int
    # "init" | "publish" | "restore" | "canary" | "rollback"
    source: str = "init"
    step: Optional[int] = None    # producer's train step, when known
    wall_time: float = field(default_factory=time.time)


class VersionLog:
    """Thread-safe append-only log of :class:`WeightVersion` records.

    ``record`` is called from whichever thread executes the swap (the
    scheduler's driving thread, usually a replica loop); ``history`` and
    ``current`` are called from publisher/router threads — hence the lock.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: List[WeightVersion] = [WeightVersion(0, "init")]

    def record(self, version: int, *, source: str,
               step: Optional[int] = None) -> WeightVersion:
        entry = WeightVersion(version, source, step)
        with self._lock:
            self._entries.append(entry)
        return entry

    @property
    def current(self) -> WeightVersion:
        with self._lock:
            return self._entries[-1]

    def rollback_target(self) -> Optional[WeightVersion]:
        """The newest entry whose version differs from the current one —
        what an auto-rollback should land on. Scans backwards so a
        re-record of the same version (a retried publish) never makes the
        deployment its own rollback target. ``None`` when the log has
        only ever seen one version."""
        with self._lock:
            cur = self._entries[-1]
            for entry in reversed(self._entries[:-1]):
                if entry.version != cur.version:
                    return entry
        return None

    def history(self) -> List[WeightVersion]:
        with self._lock:
            return list(self._entries)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


__all__ = ["VersionLog", "WeightVersion"]
