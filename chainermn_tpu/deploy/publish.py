"""Online weight hot-swap into a live serving engine.

:class:`WeightPublisher` commits a new param pytree into a running
:class:`~chainermn_tpu.serving.engine.ServingEngine` without stopping
traffic and without a single recompile. The mechanism is split so the
expensive part happens OUTSIDE the fence:

1. **Commit** (publisher thread): every leaf of the incoming tree is
   ``device_put`` against the sharding of the engine's current leaf —
   the exact shardings warmup compiled against (sharding is part of the
   jit cache key) — and blocked until resident. After this, the swap
   itself is a pointer exchange.
2. **Fence** (scheduler thread): :meth:`FCFSScheduler.request_swap`
   pauses admissions; in-flight requests drain on the weights they
   started with (each response carries its ``weight_version``); between
   two decode steps the drained scheduler executes the swap on the one
   thread that owns the engine. ``swap_params`` validates structure,
   shapes, dtypes, and shardings BEFORE assigning, so a failed swap
   rolls back to the prior version by never having left it.

``publish`` blocks for the whole cycle and must be called from a thread
that is NOT driving ``scheduler.step()`` (the in-process
:class:`~chainermn_tpu.serving.client.ServingClient` owns such a driving
thread); single-threaded drivers (benchmarks, tests that call ``step()``
by hand) use :meth:`publish_async` and keep stepping until the returned
handle completes — a blocking wait on the driving thread would deadlock
against the fence it is supposed to drain.

Import hygiene: jax and the serving stack (which pulls extensions) load
lazily inside methods — pinned by ``test_import_hygiene.py``.
"""

from __future__ import annotations

import time
from typing import Optional

from chainermn_tpu.deploy.versions import VersionLog
from chainermn_tpu.monitor._state import get_event_log, get_registry


class PublishError(RuntimeError):
    """A weight publish failed; the engine kept its prior weights."""


class SwapHandle:
    """Progress/result handle for one publish cycle."""

    def __init__(self, ticket, t_start: float, commit_s: float) -> None:
        self._ticket = ticket
        self._t_start = t_start
        self.commit_s = commit_s          # device_put + block_until_ready
        self.version: Optional[int] = None

    @property
    def done(self) -> bool:
        return self._ticket.done

    @property
    def error(self) -> Optional[BaseException]:
        return self._ticket.error

    @property
    def fence_s(self) -> Optional[float]:
        return self._ticket.fence_s

    @property
    def total_s(self) -> Optional[float]:
        if self._ticket.t_executed is None:
            return None
        return self._ticket.t_executed - self._t_start

    def wait(self, timeout: Optional[float] = None) -> int:
        """Block until the swap executed; returns the new weight version.
        Raises :class:`PublishError` if the swap failed (engine still on
        its prior weights) or the wait timed out."""
        try:
            ok = self._ticket.wait(timeout)
        except BaseException as e:
            raise PublishError(f"weight publish failed: {e}") from e
        if not ok:
            raise PublishError(
                f"weight publish still fenced after {timeout}s — is the "
                "scheduler being stepped? (publish from a non-driving "
                "thread, or use publish_async with a manual step loop)")
        self.version = self._ticket.result
        return self.version


class WeightPublisher:
    """Publishes versioned weight sets into one live engine.

    ``scheduler=None`` is the offline mode: the swap applies immediately
    on the calling thread and requires the engine to be idle (no slots
    decoding) — the caller owns that guarantee.
    """

    def __init__(self, engine, scheduler=None, *,
                 log: Optional[VersionLog] = None) -> None:
        self.engine = engine
        self.scheduler = scheduler
        self.log = log if log is not None else VersionLog()
        reg = get_registry()
        labels = {"engine": "serving"}
        self._c_swaps = reg.counter("deploy_swaps_total", labels)
        self._c_failed = reg.counter("deploy_swap_failures_total", labels)
        self._h_swap = reg.histogram("deploy_swap_seconds", labels, unit="s")
        self._events = get_event_log()

    # ------------------------------------------------------------------ #

    def _commit(self, params):
        """Move the incoming tree onto the engine's exact shardings and
        wait for residency — the transfer happens on the publisher's
        thread, BEFORE the fence, so fence time is drain-only."""
        import jax

        from chainermn_tpu.resilience.cutpoints import DEPLOY_PUBLISH
        from chainermn_tpu.resilience.faults import inject

        inject(DEPLOY_PUBLISH, version=self.engine.weight_version + 1)
        old_leaves = jax.tree_util.tree_leaves(self.engine.params)
        new_leaves, treedef = jax.tree_util.tree_flatten(params)
        if len(old_leaves) != len(new_leaves):
            # full structural validation happens in swap_params; this
            # early check just keeps the zip below honest
            raise PublishError(
                f"publish: {len(new_leaves)} leaves for an engine with "
                f"{len(old_leaves)}")
        committed = []
        for old, new in zip(old_leaves, new_leaves):
            sh = getattr(old, "sharding", None)
            if sh is None:
                committed.append(new)
            elif getattr(old, "_committed", True):
                committed.append(jax.device_put(new, sh))
            else:
                # the engine's leaf is UNcommitted (plain single-device
                # init) — committed-ness is part of the jit cache key, so
                # an explicitly-placed replacement would recompile; a
                # bare device_put keeps the new leaf uncommitted on the
                # default device, matching the warmup key exactly
                committed.append(jax.device_put(new))
        committed = jax.block_until_ready(
            jax.tree_util.tree_unflatten(treedef, committed))
        return committed

    def _swap_fn(self, committed, step: Optional[int]):
        def run():
            version = self.engine.swap_params(committed)
            self.log.record(version, source="publish", step=step)
            return version
        return run

    def publish_async(self, params, *, step: Optional[int] = None
                      ) -> SwapHandle:
        """Commit ``params`` device-side, then fence the swap through the
        scheduler; returns a :class:`SwapHandle` immediately. The caller
        must keep the scheduler stepping (or be running a client/replica
        loop that does) for the handle to complete."""
        t0 = time.perf_counter()
        try:
            committed = self._commit(params)
        except Exception as e:
            self._c_failed.inc()
            self._events.emit("publish_failed", phase="commit",
                              error=type(e).__name__)
            raise PublishError(
                f"weight publish failed during commit: {e}") from e
        commit_s = time.perf_counter() - t0
        if self.scheduler is not None:
            ticket = self.scheduler.request_swap(
                self._swap_fn(committed, step))
        else:
            # offline: no fence needed, the engine must be idle
            from chainermn_tpu.serving.scheduler import SwapTicket

            ticket = SwapTicket(self._swap_fn(committed, step))
            if getattr(self.engine, "active_slots", 0):
                ticket.error = PublishError(
                    "publish without a scheduler requires an idle engine")
            else:
                try:
                    ticket.result = ticket.fn()
                except Exception as e:  # noqa: BLE001 — on the ticket
                    ticket.error = e
            ticket.t_executed = time.perf_counter()
            ticket._done.set()
        handle = SwapHandle(ticket, t0, commit_s)
        self._watch(handle)
        return handle

    def publish(self, params, *, step: Optional[int] = None,
                timeout: Optional[float] = 60.0) -> int:
        """Blocking publish cycle; returns the new weight version. Must
        NOT be called from the thread driving ``scheduler.step()`` (see
        module docstring)."""
        return self.publish_async(params, step=step).wait(timeout)

    # ------------------------------------------------------------------ #

    def _watch(self, handle: SwapHandle) -> None:
        """Record metrics when the handle resolves — inline if it already
        did (offline mode), else from the ticket's completion via a
        cheap poll at wait() time is not enough (async callers may never
        wait), so we piggyback on the ticket event in a tiny daemon
        thread only when still pending."""
        if handle.done:
            self._record(handle)
            return
        import threading

        def run():
            handle._ticket._done.wait()
            self._record(handle)

        threading.Thread(target=run, daemon=True,
                         name="deploy-swap-watch").start()

    def _record(self, handle: SwapHandle) -> None:
        if handle.error is None:
            self._c_swaps.inc()
            if handle.total_s is not None:
                self._h_swap.observe(handle.total_s)
            self._events.emit(
                "publish", version=handle._ticket.result,
                commit_s=round(handle.commit_s, 6),
                fence_s=round(handle.fence_s or 0.0, 6))
        else:
            self._c_failed.inc()
            self._events.emit("publish_failed", phase="swap",
                              error=type(handle.error).__name__)


__all__ = ["PublishError", "SwapHandle", "WeightPublisher"]
