"""Multi-node evaluation.

Re-design of ``[U] chainermn/evaluators/__init__.py``'s
``create_multi_node_evaluator`` (SURVEY.md S2.14 — unverified cite): each
rank evaluates its dataset shard, per-metric results are averaged across
ranks, and only root's report is authoritative.

Protocol: an *evaluator* is anything with an ``evaluate() -> dict`` method or
a plain callable returning a metrics dict (the reference requires a Chainer
``Evaluator``; we only need the result-dict contract). Metric values may be
scalars or jax/numpy arrays.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping

import numpy as np

from chainermn_tpu.communicators.communicator_base import CommunicatorBase


def _mean_dicts(dicts: list[Mapping[str, Any]]) -> dict[str, Any]:
    keys = sorted(dicts[0].keys())
    for d in dicts[1:]:
        if sorted(d.keys()) != keys:  # order-insensitive; sets must match
            raise ValueError(
                f"evaluators returned mismatched metric keys: {keys} vs {sorted(d.keys())}"
            )
    out: dict[str, Any] = {}
    for k in keys:
        mean = np.mean([np.asarray(d[k]) for d in dicts], axis=0)
        out[k] = float(mean) if mean.ndim == 0 else mean  # elementwise for arrays
    return out


class _MultiNodeEvaluator:
    """Wrapper produced by :func:`create_multi_node_evaluator`."""

    def __init__(self, actual_evaluator, communicator: CommunicatorBase) -> None:
        self._evaluator = actual_evaluator
        self._comm = communicator

    def evaluate(self) -> dict[str, Any]:
        inner = self._evaluator
        local = inner.evaluate() if hasattr(inner, "evaluate") else inner()
        if not isinstance(local, Mapping):
            raise TypeError(
                f"evaluator must return a metrics dict, got {type(local).__name__}"
            )
        gathered = self._comm.allgather_obj(dict(local))
        return _mean_dicts(gathered)

    __call__ = evaluate

    def __getattr__(self, name):  # delegate everything else to the wrapped one
        return getattr(self._evaluator, name)


def create_multi_node_evaluator(actual_evaluator, communicator: CommunicatorBase):
    """Wrap an evaluator so results are cross-rank means (reference name).

    The wrapped evaluator's ``evaluate()`` is called on every process with its
    local shard; the returned dict's values are averaged elementwise across
    processes. All processes receive the averaged dict (root-only reporting is
    the caller's choice, as in the reference examples)."""
    return _MultiNodeEvaluator(actual_evaluator, communicator)


__all__ = ["create_multi_node_evaluator"]
