"""Multi-node (cross-replica) batch normalization.

Re-design of ``[U] chainermn/links/batch_normalization.py`` and the
underlying ``[U] chainermn/functions/batch_normalization.py`` (SURVEY.md
S2.10-2.11 — unverified cites). The reference allreduces the batch mean and
squared-mean before normalizing, and allreduces the two stat-gradients in
backward.

TPU mapping: inside a ``shard_map``-traced step the stats reduction is a
``psum`` over the communicator axis, and the backward reductions fall out of
autodiff (psum's transpose). Two entry points:

- :func:`multi_node_batch_normalization` — the functional form (parity with
  the reference's FunctionNode).
- :class:`MultiNodeBatchNormalization` — flax module, drop-in for
  ``nn.BatchNorm`` (parity with the reference's drop-in link). Implemented
  directly on the functional form (not nn.BatchNorm) so running-stat updates
  also see the *global* batch.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


def multi_node_batch_normalization(
    x, gamma, beta, communicator, eps: float = 2e-5,
):
    """Normalize ``x`` with batch statistics pooled across the communicator.

    ``x``: [batch, ..., features] per-rank local batch (traced under
    shard_map), or rank-major eagerly. Returns (y, global_mean, global_var)
    so callers can maintain running statistics.
    """
    axes = tuple(range(x.ndim - 1))
    # local moments -> cross-rank mean (the reference allreduces mean and
    # sq-mean; mathematically identical, and one fused pair of psums here)
    mean = jnp.mean(x, axis=axes)
    sqmean = jnp.mean(jnp.square(x), axis=axes)
    mean = communicator.allreduce(mean, "mean")
    sqmean = communicator.allreduce(sqmean, "mean")
    var = sqmean - jnp.square(mean)
    y = (x - mean) * jax.lax.rsqrt(var + eps) * gamma + beta
    return y, mean, var


class MultiNodeBatchNormalization(nn.Module):
    """Drop-in ``nn.BatchNorm`` replacement with cross-replica statistics.

    Matches flax BatchNorm's interface subset the examples need:
    ``use_running_average`` selects stored vs batch stats; running stats are
    updated with the *global* batch moments, so evaluation is consistent
    across replicas without an extra AllreducePersistent pass (which is still
    provided for parity in extensions/).
    """

    communicator: Any
    use_running_average: Optional[bool] = None
    momentum: float = 0.9
    epsilon: float = 2e-5
    dtype: Optional[jnp.dtype] = None
    use_scale: bool = True
    use_bias: bool = True
    scale_init: Any = nn.initializers.ones
    bias_init: Any = nn.initializers.zeros

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        # call-time value wins; constructor value is the default; absent both,
        # train-mode batch statistics (False)
        if use_running_average is None:
            use_running_average = self.use_running_average
        use_ra = bool(use_running_average) if use_running_average is not None else False
        features = x.shape[-1]
        gamma = (
            self.param("scale", self.scale_init, (features,))
            if self.use_scale else jnp.ones((features,))
        )
        beta = (
            self.param("bias", self.bias_init, (features,))
            if self.use_bias else jnp.zeros((features,))
        )
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((features,))
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((features,))
        )
        if use_ra:
            y = (x - ra_mean.value) * jax.lax.rsqrt(
                ra_var.value + self.epsilon
            ) * gamma + beta
            return y.astype(self.dtype or x.dtype)
        if self.is_initializing():
            # shape-only pass, possibly outside any mesh trace: local stats
            # (values are discarded; avoids requiring init under shard_map)
            axes = tuple(range(x.ndim - 1))
            mean = jnp.mean(x, axis=axes)
            var = jnp.mean(jnp.square(x), axis=axes) - jnp.square(mean)
            y = (x - mean) * jax.lax.rsqrt(var + self.epsilon) * gamma + beta
            return y.astype(self.dtype or x.dtype)
        y, mean, var = multi_node_batch_normalization(
            x, gamma, beta, self.communicator, eps=self.epsilon
        )
        m = self.momentum
        ra_mean.value = m * ra_mean.value + (1 - m) * mean
        ra_var.value = m * ra_var.value + (1 - m) * var
        return y.astype(self.dtype or x.dtype)
