"""Recursive BatchNorm -> MultiNodeBatchNormalization replacement.

Re-design of ``[U] chainermn/links/create_mnbn_model.py`` (SURVEY.md S2.11 —
unverified cite): the reference walks a Chain/Sequential, replacing every
``L.BatchNormalization`` with the multi-node link, copying hyperparameters.

Flax modules are frozen dataclasses, so the walk is a reconstruct: every
dataclass field (including inside lists/tuples/dicts) holding an
``nn.BatchNorm`` is swapped for a hyperparameter-matched
``MultiNodeBatchNormalization``, recursively through submodules.

Limitation (documented, structural): ``@nn.compact`` modules that *construct*
``nn.BatchNorm`` inline in ``__call__`` cannot be rewritten by walking — the
submodule does not exist until trace time. Declare BN as a field (setup-style
or a module attribute), as all in-repo models do, or use
``MultiNodeBatchNormalization`` directly.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn

from chainermn_tpu.links.batch_normalization import MultiNodeBatchNormalization


def _convert_bn(bn: nn.BatchNorm, communicator) -> MultiNodeBatchNormalization:
    # refuse configs MNBN cannot represent, rather than silently changing
    # the math or the parameter tree
    if bn.axis != -1:
        raise ValueError(
            f"create_mnbn_model: nn.BatchNorm(axis={bn.axis}) unsupported; "
            "MultiNodeBatchNormalization normalizes the trailing feature axis"
        )
    if getattr(bn, "axis_name", None) is not None:
        raise ValueError(
            "create_mnbn_model: nn.BatchNorm already has axis_name set "
            f"({bn.axis_name!r}) — it is cross-replica already; converting "
            "would double-reduce"
        )
    return MultiNodeBatchNormalization(
        communicator=communicator,
        use_running_average=bn.use_running_average,
        momentum=bn.momentum,
        epsilon=bn.epsilon,
        dtype=bn.dtype,
        use_scale=bn.use_scale,
        use_bias=bn.use_bias,
        scale_init=bn.scale_init,
        bias_init=bn.bias_init,
        name=bn.name,
    )


def _walk(obj: Any, communicator) -> Any:
    if isinstance(obj, nn.BatchNorm):
        return _convert_bn(obj, communicator)
    if isinstance(obj, nn.Module):
        changes = {}
        for f in dataclasses.fields(obj):
            if f.name in ("name", "parent"):
                continue
            val = getattr(obj, f.name)
            new = _walk(val, communicator)
            if new is not val:
                changes[f.name] = new
        if changes:
            return obj.clone(**changes)
        return obj
    if isinstance(obj, (list, tuple)):
        walked = [_walk(v, communicator) for v in obj]
        if any(w is not v for w, v in zip(walked, obj)):
            return type(obj)(walked)
        return obj
    if isinstance(obj, dict):
        walked = {k: _walk(v, communicator) for k, v in obj.items()}
        if any(walked[k] is not obj[k] for k in obj):
            return walked
        return obj
    return obj


def create_mnbn_model(model: nn.Module, communicator) -> nn.Module:
    """Return a copy of ``model`` with every field-declared ``nn.BatchNorm``
    replaced by :class:`MultiNodeBatchNormalization` (reference name)."""
    return _walk(model, communicator)
