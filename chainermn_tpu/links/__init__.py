"""Links: model-parallel composition & sync-BN (``[U] chainermn/links/``)."""

from chainermn_tpu.links.batch_normalization import (
    MultiNodeBatchNormalization,
    multi_node_batch_normalization,
)
from chainermn_tpu.links.create_mnbn_model import create_mnbn_model
from chainermn_tpu.links.multi_node_chain_list import MultiNodeChainList

__all__ = [
    "MultiNodeChainList",
    "MultiNodeBatchNormalization",
    "multi_node_batch_normalization",
    "create_mnbn_model",
]
