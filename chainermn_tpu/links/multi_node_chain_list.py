"""Model-parallel composition: MultiNodeChainList.

Re-design of ``[U] chainermn/links/multi_node_chain_list.py`` (SURVEY.md
S2.11 — unverified cite). In the reference, every process builds its own
chain of components; ``add_link(link, rank_in, rank_out)`` declares where each
component's inputs come from and outputs go, and ``__call__`` interleaves
compute with blocking MPI send/recv, relying on delegate variables to order
the backward graph (S3.3 — the trickiest semantic in the reference, where a
mis-ordered pair deadlocks the job).

Single-controller re-design: ONE object declares the WHOLE cross-rank model —
``add_link`` gains an explicit ``rank=`` (who owns the component), since there
is no ambient process identity. Execution is compute-follows-data MPMD:

- each component's parameters live on its rank's device (committed);
- "send/recv" is ``jax.device_put`` of boundary tensors onto the consumer's
  device — on TPU this is a direct ICI transfer, and its autodiff transpose
  is the reverse transfer, which is exactly the reference's transposed
  backward communication;
- each component's apply is jitted separately (compilation is per-stage;
  placement follows its committed parameters);
- ordering needs no delegate protocol: data dependence in one Python trace
  is total, so the reference's deadlock class is unrepresentable.

Like the reference, scheduling is sequential fill-drain per batch — NO
microbatch pipelining (upstream has none either, SURVEY.md S2.16). The
scan+ppermute microbatched pipeline lives separately in
``chainermn_tpu.ops.pipeline`` as a TPU extension.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Sequence

import jax


def _as_tuple(v) -> tuple:
    if v is None:
        return ()
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,)


@dataclasses.dataclass
class _Component:
    link: Any                      # flax.linen.Module (or any (init, apply) pair)
    rank: int                      # logical rank (mesh flat index) owning it
    rank_in: tuple[int, ...]       # () => consumes the model inputs
    rank_out: tuple[int, ...]      # () => contributes to the model outputs


class MultiNodeChainList:
    """Cross-rank model as an ordered component list (reference name).

    Usage (2-rank MLP, the reference's mnist model-parallel example shape)::

        model = MultiNodeChainList(comm)
        model.add_link(MLP0(), rank=0, rank_in=None, rank_out=1)
        model.add_link(MLP1(), rank=1, rank_in=0, rank_out=None)
        params = model.init(key, x)
        y = model.apply(params, x)          # differentiable end-to-end

    Components execute in insertion order. ``rank_in=None`` feeds the model
    inputs; an int/list receives the outputs previously sent toward this
    component's rank by those ranks. ``rank_out=None`` emits a model output;
    an int/list sends to later components on those ranks. Multi-input,
    multi-output, and non-adjacent topologies work exactly as upstream.
    """

    def __init__(self, comm) -> None:
        self._comm = comm
        self._components: list[_Component] = []
        self._apply_cache: dict[int, Any] = {}
        # Number of times the fused body was traced. Under jit (the only way
        # the fused path runs it), staying at 1 across repeated same-shape
        # calls means no retrace and hence no recompile — tests assert that.
        self.fused_trace_count = 0

    # ------------------------------------------------------------------ #

    def add_link(self, link, rank: int, rank_in=None, rank_out=None) -> None:
        if not 0 <= rank < self._comm.size:
            raise ValueError(f"rank {rank} out of range [0, {self._comm.size})")
        self._components.append(
            _Component(link, rank, _as_tuple(rank_in), _as_tuple(rank_out))
        )

    def _device(self, rank: int):
        return list(self._comm.mesh.devices.flat)[rank]

    # ------------------------------------------------------------------ #

    def init(self, key, *inputs):
        """Initialize every component's flax *variables* (params AND state
        collections like batch_stats) on its own device; returns a list of
        variables dicts, one per component, committed to its rank."""
        if not self._components:
            raise ValueError("MultiNodeChainList has no components; call add_link")
        keys = jax.random.split(key, len(self._components))
        variables: list[Any] = []

        def call(comp, idx, args):
            y, v = comp.link.init_with_output(keys[idx], *args)
            variables.append(jax.device_put(v, self._device(comp.rank)))
            return y

        self._run(inputs, call)
        return variables

    def apply(self, variables: Sequence[Any], *inputs, mutable=False,
              fused: bool = False):
        """Forward through all components with ICI transfers at boundaries.

        Differentiable: ``jax.grad`` of a loss of the output reaches every
        component's variables and the inputs (backward transfers reversed).
        ``mutable`` (e.g. ``["batch_stats"]``) is forwarded to each
        component's apply; when set, returns ``(output, updated_states)``
        with ``updated_states`` a per-component list ({} for stateless
        components) to merge back into ``variables``.

        ``fused=True`` builds ONE jitted program over the whole chain
        (forward AND, under ``jax.grad``, one backward program) instead of a
        jit per stage: no per-stage Python dispatch, XLA schedules across
        stage boundaries, numerics identical. The program runs replicated
        over the communicator's mesh, so pass variables replicated (see
        :meth:`replicate`) — the memory layout trades the default mode's
        per-rank parameter placement for single-program dispatch. For
        homogeneous chains that want true microbatch overlap, use
        ``chainermn_tpu.ops.pipeline``.
        """
        if len(variables) != len(self._components):
            raise ValueError(
                f"variables has {len(variables)} entries for "
                f"{len(self._components)} components"
            )
        mutable_key = tuple(mutable) if isinstance(mutable, (list, tuple)) else mutable
        if fused:
            return self._fused_apply(list(variables), inputs, mutable_key)
        updated: list[Any] = []

        def call(comp, idx, args):
            fn = self._apply_cache.get((idx, mutable_key))
            if fn is None:
                fn = jax.jit(
                    functools.partial(comp.link.apply, mutable=mutable_key)
                    if mutable_key
                    else comp.link.apply
                )
                self._apply_cache[(idx, mutable_key)] = fn
            if mutable_key:
                y, upd = fn(variables[idx], *args)
                updated.append(upd)
                return y
            return fn(variables[idx], *args)

        out = self._run(inputs, call)
        if mutable_key:
            return out, updated
        return out

    def replicate(self, variables: Sequence[Any]):
        """Re-place per-component variables replicated over the mesh — do
        this once before training with ``apply(..., fused=True)``."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(self._comm.mesh, P())
        return [jax.device_put(v, sharding) for v in variables]

    def _fused_apply(self, variables, inputs, mutable_key):
        """One jitted program over the whole chain (see ``apply``).

        Inside a single trace there are no device boundaries to cross — the
        mailbox walk is ordinary data flow, and placement is carried by the
        (replicated) input shardings.
        """
        cache_key = ("fused", mutable_key, len(variables))
        fn = self._apply_cache.get(cache_key)
        if fn is None:
            def body(variables, inputs):
                self.fused_trace_count += 1
                updated: list[Any] = []

                def call(comp, idx, args):
                    if mutable_key:
                        y, upd = comp.link.apply(
                            variables[idx], *args, mutable=mutable_key
                        )
                        updated.append(upd)
                        return y
                    return comp.link.apply(variables[idx], *args)

                out = self._run_traced(inputs, call)
                return (out, updated) if mutable_key else out

            fn = jax.jit(body)
            self._apply_cache[cache_key] = fn
        return fn(variables, inputs)

    def _run_traced(self, inputs, call):
        """The mailbox walk without device_put hops (single-trace variant of
        :meth:`_run` — used by the fused path where everything is one
        program and placement is carried by the input shardings)."""
        return self._run(inputs, call, place=lambda x, rank: x)

    def merge_updates(self, variables: Sequence[Any], updated: Sequence[Any]):
        """Merge ``apply(..., mutable=...)``'s updated state collections back
        into the per-component variables list."""
        return [
            {**v, **u} if u else v for v, u in zip(variables, updated)
        ]

    # ------------------------------------------------------------------ #

    def _run(self, inputs, call, place=None):
        """Forward walker. ``mailbox[(src_rank, dst_rank)]`` holds in-flight
        tensors — the single-controller descendant of the reference's
        delegate queue. ``place(x, rank)`` moves a boundary tensor onto the
        consumer rank; the default is a committed ``jax.device_put`` (an ICI
        hop between stage devices), while the fused single-trace path passes
        identity since there are no device boundaries inside one program."""
        if place is None:
            place = lambda x, rank: jax.device_put(x, self._device(rank))  # noqa: E731
        mailbox: dict[tuple[int, int], list[Any]] = {}
        outputs: list[Any] = []
        for idx, comp in enumerate(self._components):
            # gather inputs: model inputs, or queued sends from rank_in
            if not comp.rank_in:
                args = [place(x, comp.rank) for x in inputs]
            else:
                args = []
                for src in comp.rank_in:
                    q = mailbox.get((src, comp.rank))
                    if not q:
                        raise RuntimeError(
                            f"component #{idx} (rank {comp.rank}) expects an "
                            f"input from rank {src}, but nothing was sent — "
                            "check add_link order and rank_in/rank_out wiring"
                        )
                    args.append(place(q.pop(0), comp.rank))  # <- "recv"
            y = call(comp, idx, args)
            # route outputs
            if not comp.rank_out:
                outputs.append(y)
            else:
                for dst in comp.rank_out:
                    mailbox.setdefault((comp.rank, dst), []).append(y)  # <- "send"
        undelivered = {k: len(v) for k, v in mailbox.items() if v}
        if undelivered:
            raise RuntimeError(
                f"undelivered sends remain {undelivered}: a rank_out named a "
                "rank that no later component (rank_in) consumes"
            )
        if not outputs:
            raise RuntimeError("no component declared rank_out=None (model output)")
        return outputs[0] if len(outputs) == 1 else tuple(outputs)
