"""Canonical data-parallel training step.

The reference's per-step control flow lives in Chainer's Trainer/Updater
(SURVEY.md S1: ChainerMN only wraps the optimizer hook, S3.2). In the TPU
rebuild the equivalent "hot loop contract" is a single jitted SPMD program:
forward + backward + cross-rank gradient mean + optimizer update + BN-stat
sync, built here once and reused by bench.py, the examples, and
``__graft_entry__``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators.communicator_base import CommunicatorBase
from chainermn_tpu.monitor import annotate, instrument
from chainermn_tpu.utils import axis_size as _axis_size
from chainermn_tpu.utils import pcast_varying


def classification_loss_fn(
    model,
    rest: dict,
    mutable: list,
    images,
    labels,
    train_kwargs: dict,
    label_smoothing: float,
):
    """``loss_fn(params) -> (loss, updated_collections)`` shared by the
    shard_map step below and the FSDP step (``parallel/fsdp.py``), so the
    training math — loss options, mutable-collection handling — can never
    diverge between layouts."""

    def loss_fn(p):
        if mutable:
            logits, updated = model.apply(
                {"params": p, **rest}, images, mutable=mutable, **train_kwargs
            )
        else:
            logits = model.apply({"params": p}, images, **train_kwargs)
            updated = {}
        if label_smoothing:
            targets = optax.smooth_labels(
                jax.nn.one_hot(labels, logits.shape[-1]), label_smoothing
            )
            loss = optax.softmax_cross_entropy(logits, targets).mean()
        else:
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            ).mean()
        return loss, updated

    return loss_fn


def make_classification_train_step(
    model,
    optimizer: optax.GradientTransformation,
    comm: CommunicatorBase,
    train_kwargs: Optional[dict] = None,
    label_smoothing: float = 0.0,
) -> Callable:
    """Build the per-rank step body (to be wrapped by :func:`jit_train_step`).

    ``variables`` is a flax variables dict ({'params', 'batch_stats', ...});
    mutable collections (BN running stats) are updated from the local batch
    and then cross-rank averaged inside the step, so evaluation state is
    replica-consistent by construction (the reference needs a separate
    AllreducePersistent pass for this; we keep that extension for parity but
    the canonical step doesn't need it).
    """
    train_kwargs = dict(train_kwargs or {})

    def step(variables, opt_state, images, labels):
        # profiler scope: every op this body traces carries the name in its
        # HLO metadata, so XProf device rows read as "train_step/..."
        with annotate("chainermn.train_step"):
            return step_body(variables, opt_state, images, labels)

    def step_body(variables, opt_state, images, labels):
        params = variables["params"]
        rest = {k: v for k, v in variables.items() if k != "params"}
        mutable = list(rest.keys())
        # Differentiate wrt a VARYING view of the (replicated) params: under
        # shard_map's replication-tracking semantics, grad-of-varying-loss
        # wrt invariant params would insert an automatic cross-rank psum in
        # the backward — the grads arriving at the optimizer would already be
        # SUMMED (n x the mean, a silent lr scale) and the communicator
        # strategy's own collective (packed buffers, wire dtype, two-level
        # meshes) would be bypassed. pcast keeps the grads per-rank local so
        # the multi-node optimizer owns the one true reduction.
        params_v = jax.tree_util.tree_map(
            lambda a: pcast_varying(a, comm.axis_name), params
        )
        loss_fn = classification_loss_fn(
            model, rest, mutable, images, labels, train_kwargs, label_smoothing
        )
        (loss, updated), grads = jax.value_and_grad(loss_fn, has_aux=True)(params_v)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # replica-consistent mutable state (BN running stats are tiny; one
        # extra small collective per step)
        synced = {
            k: jax.tree_util.tree_map(lambda a: comm.allreduce(a, "mean"), v)
            for k, v in updated.items()
        }
        new_variables = {"params": params, **synced}
        return new_variables, opt_state, comm.allreduce(loss, "mean")

    return step


def jit_train_step(
    model,
    optimizer: optax.GradientTransformation,
    comm: CommunicatorBase,
    donate: bool = True,
    train_kwargs: Optional[dict] = None,
    label_smoothing: float = 0.0,
    monitored: bool = True,
) -> Callable:
    """The full jitted SPMD train step over the communicator's mesh.

    Call as ``step(variables, opt_state, images, labels)`` with ``variables``/
    ``opt_state`` replicated and the batch rank-major (leading axis = global
    batch, sharded over the mesh). Buffer donation keeps params/opt-state
    updates in-place on HBM (the reference's grow-only arenas play this role,
    SURVEY.md S2.9).

    ``monitored=True`` (default) returns the step wrapped in
    :func:`chainermn_tpu.monitor.instrument`: step start/end events, a
    step counter + step-time histogram in the process registry, recompile
    detection, and periodic device-memory gauges — call-transparent
    (``lower``/``_cache_size`` still delegate to the jitted function) and
    a few host dict ops per step.
    """
    body = make_classification_train_step(
        model, optimizer, comm, train_kwargs, label_smoothing
    )
    data = comm.data_spec
    # ZeRO-style optimizers shard their state over the mesh (rank-major)
    opt_spec = getattr(optimizer, "state_spec", P())
    sm = comm.shard_map(
        body,
        in_specs=(P(), opt_spec, data, data),
        out_specs=(P(), opt_spec, P()),
        # ZeRO's all_gather'd updates and the 2D strategy's all_gather leg
        # both defeat static replication inference
        check_vma=getattr(optimizer, "check_vma", True)
        and getattr(comm, "check_vma", True),
    )
    donate_argnums = (0, 1) if donate else ()
    jitted = jax.jit(sm, donate_argnums=donate_argnums)
    return instrument(jitted, "train_step") if monitored else jitted


def _shard_positions(model, seq_axis, t_local):
    """Per-shard global positions under sequence sharding: a scalar base for
    contiguous layouts, a position VECTOR for zigzag (each shard holds one
    early + one late chunk; feed data permuted by
    :func:`~chainermn_tpu.parallel.sequence.zigzag_permutation`)."""
    if seq_axis is None:
        return 0
    idx = jax.lax.axis_index(seq_axis)
    if getattr(model, "attention", None) in ("zigzag", "zigzag_flash"):
        from chainermn_tpu.parallel.sequence import zigzag_positions

        return zigzag_positions(idx, _axis_size(seq_axis), t_local)
    return idx * t_local


def _jit_tp_lm_train_step(
    model,
    optimizer: optax.GradientTransformation,
    comm: CommunicatorBase,
    tensor_axis: str,
    shard_sequence: bool,
    donate: bool,
    monitored: bool = True,
) -> Callable:
    """The tensor-parallel LM step (dispatched to by :func:`jit_lm_train_step`
    when the model was built with ``tensor_axis``).

    Uses the **global-objective** gradient pattern (parallel/tensor.py):
    params stay invariant, the loss is pmean'd over every mesh axis it varies
    on, and replication tracking assembles each leaf's exact global gradient
    — sliced TP leaves by psum of zero-padded slices, replicated leaves by
    averaging. Consequently ``optimizer`` must be a PLAIN optax transform:
    the grads arriving at it are already the global gradient, and a
    multi-node wrapper's extra mean would shrink them by the axis size.

    The batch shards over every communicator axis EXCEPT ``tensor_axis`` and
    the model's ``sequence_axis`` (pure TP on a flat comm = replicated
    batch; a hierarchical comm gives dp x tp). A model built with BOTH
    ``tensor_axis`` and a distinct ``sequence_axis`` (``attention='ring'|
    'ulysses'``) over a 3-axis mesh gives full **dp x sp x tp**: the
    sequence dimension shards over ``sequence_axis`` and each shard's
    ``pos_offset`` is threaded automatically.
    """
    from chainermn_tpu.parallel.tensor import global_objective

    axes = comm.axis_name
    axes = (axes,) if isinstance(axes, str) else tuple(axes)
    if tensor_axis not in axes:
        raise ValueError(
            f"model.tensor_axis={tensor_axis!r} is not one of the "
            f"communicator's mesh axes {axes}"
        )
    seq_axis = getattr(model, "sequence_axis", None)
    if shard_sequence and seq_axis is None:
        raise ValueError(
            "shard_sequence=True with a TP model needs the model built with "
            "sequence_axis (and attention='ring'|'zigzag'|'ulysses' or a "
            "_flash variant)"
        )
    if seq_axis is not None and (seq_axis == tensor_axis
                                 or seq_axis not in axes):
        raise ValueError(
            f"model.sequence_axis={seq_axis!r} must be a mesh axis distinct "
            f"from tensor_axis={tensor_axis!r} (mesh axes {axes})"
        )
    if seq_axis is not None and not shard_sequence:
        # mirror the dense path: a sequence_axis model under this step WILL
        # have its sequence sharded — a caller asking for shard_sequence=
        # False must not silently get sequence sharding anyway
        raise ValueError(
            f"model has sequence_axis={seq_axis!r}: the TP step shards the "
            "sequence over it — pass shard_sequence=True (or build the "
            "model without sequence_axis for batch-only sharding)"
        )
    if seq_axis is not None and getattr(model, "attention", None) not in (
            "ring", "ring_flash", "zigzag", "zigzag_flash", "ulysses",
            "ulysses_flash"):
        # 'full' under a sharded sequence silently computes block-diagonal
        # attention (each shard attends within its own chunk only)
        raise ValueError(
            f"sequence_axis={seq_axis!r} needs attention='ring'|'zigzag'|"
            f"'ulysses' (or _flash); got "
            f"{getattr(model, 'attention', None)!r} — plain "
            "'full' would attend within each sequence shard only"
        )
    if (getattr(model, "attention", None) in ("flash", "ring_flash",
                                              "zigzag_flash",
                                              "ulysses_flash")
            and jax.default_backend() != "tpu"):
        # The dense LM step works around interpret-mode Pallas by dropping
        # to check_vma=False; the TP step CANNOT (the global-objective
        # pattern is built on vma tracking — global_objective raises).
        raise ValueError(
            "tensor_axis + Pallas attention (flash/ring_flash) needs "
            "compiled TPU kernels; in interpret mode (non-TPU backends) the "
            "required check_vma=False would break the global-objective "
            "gradient pattern — use attention='full'/'ring' off-TPU"
        )
    dp_axes = tuple(a for a in axes if a != tensor_axis and a != seq_axis)

    vocab_parallel = getattr(model, "vocab_parallel_head", False)

    def body(params, opt_state, tokens, targets):
        with annotate("chainermn.lm_tp_train_step"):
            return body_inner(params, opt_state, tokens, targets)

    def body_inner(params, opt_state, tokens, targets):
        pos_offset = _shard_positions(model, seq_axis, tokens.shape[1])

        def loss_fn(p):
            logits = model.apply(p, tokens, pos_offset)
            if vocab_parallel:
                from chainermn_tpu.parallel.tensor import (
                    vocab_parallel_cross_entropy,
                )

                ce = vocab_parallel_cross_entropy(
                    logits, targets, tensor_axis
                ).mean()
            else:
                ce = optax.softmax_cross_entropy_with_integer_labels(
                    logits, targets
                ).mean()
            return global_objective(ce, axes)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, new_opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # uniform step arity with the dense/MoE path: stats is always there
        # (TP models are dense, so it is always empty here)
        return params, new_opt_state, loss, {}

    # batch dim over the dp axes, sequence dim over the model's seq axis
    data = P(dp_axes if dp_axes else None,
             seq_axis if seq_axis is not None else None)
    sm = comm.shard_map(
        body,
        in_specs=(P(), P(), data, data),
        out_specs=(P(), P(), P(), P()),
    )
    donate_argnums = (0, 1) if donate else ()
    jitted = jax.jit(sm, donate_argnums=donate_argnums)
    return instrument(jitted, "lm_tp_train_step") if monitored else jitted


def jit_lm_train_step(
    model,
    optimizer: optax.GradientTransformation,
    comm: CommunicatorBase,
    shard_sequence: bool = False,
    donate: bool = True,
    moe_aux_weight: float = 0.01,
    fused_ce: bool = False,
    monitored: bool = True,
) -> Callable:
    """Jitted next-token-prediction step for :class:`TransformerLM`-shaped
    models. Call as ``step(params, opt_state, tokens, targets)`` ->
    ``(params, opt_state, loss, stats)``. ``stats`` is a dict — ``{}`` for
    dense models; MoE models carry ``{'moe_drop_frac': ...}``: the
    globally-averaged fraction of expert assignments dropped to the
    capacity bound this step (silent drops were round 3's telemetry gap —
    log it; a persistently high value means the gate is unbalanced or
    capacity_factor is too small). The arity is uniform on purpose: it
    does not change under the model config (round-4 advisor finding).

    ``shard_sequence=False``: batch axis sharded over the mesh (pure DP).
    ``shard_sequence=True``: the SEQUENCE axis is sharded (context
    parallelism for long-context training) — build the model with
    ``attention='ring'``, ``'zigzag'`` (load-balanced causal; feed data
    permuted by :func:`~chainermn_tpu.parallel.sequence.zigzag_permutation`)
    or ``'ulysses'``, and ``sequence_axis=comm.axis_name``; each shard's
    global positions are threaded through ``pos_offset`` (a vector under
    zigzag). Gradients are averaged over the axis by the multi-node
    optimizer either way, so params stay replicated.

    ``monitored=True`` (default) wraps the jitted step in
    :func:`chainermn_tpu.monitor.instrument` (step events + metrics +
    recompile tracking), call-transparently — see :func:`jit_train_step`.
    """
    # Mismatched model/step configs run without error but compute the wrong
    # attention (the axis IS bound inside shard_map either way) — reject.
    attn = getattr(model, "attention", None)
    seq_axis = getattr(model, "sequence_axis", None)
    moe_experts = getattr(model, "moe_experts", 0)
    tensor_axis = getattr(model, "tensor_axis", None)
    if fused_ce and (tensor_axis is not None
                     or getattr(model, "vocab_parallel_head", False)):
        raise ValueError(
            "fused_ce applies the replicated lm_head itself; the TP/"
            "vocab-parallel paths shard the head and already avoid full "
            "logits (vocab_parallel_cross_entropy)"
        )
    if tensor_axis is not None:
        return _jit_tp_lm_train_step(
            model, optimizer, comm, tensor_axis,
            shard_sequence=shard_sequence, donate=donate,
            monitored=monitored,
        )
    if moe_experts and getattr(model, "moe_axis", None) != comm.axis_name:
        raise ValueError(
            f"MoE model must be built with moe_axis={comm.axis_name!r} "
            f"(got {getattr(model, 'moe_axis', None)!r}) so experts shard "
            "over the step's mesh axis"
        )
    if attn is not None:
        if shard_sequence:
            if (attn not in ("ring", "ring_flash", "zigzag", "zigzag_flash",
                             "ulysses", "ulysses_flash")
                    or seq_axis != comm.axis_name):
                raise ValueError(
                    f"shard_sequence=True needs the model built with "
                    f"attention='ring'|'ring_flash'|'zigzag'|'zigzag_flash'|"
                    f"'ulysses'(+_flash) and sequence_axis={comm.axis_name!r}; got "
                    f"attention={attn!r}, sequence_axis={seq_axis!r}"
                )
        elif seq_axis is not None:
            raise ValueError(
                f"model has sequence_axis={seq_axis!r} but shard_sequence="
                f"False shards the batch axis — the sequence-parallel "
                f"attention would mix different batch shards' K/V"
            )

    def body(params, opt_state, tokens, targets):
        with annotate("chainermn.lm_train_step"):
            return body_inner(params, opt_state, tokens, targets)

    def body_inner(params, opt_state, tokens, targets):
        pos_offset = _shard_positions(
            model, comm.axis_name if shard_sequence else None, tokens.shape[1]
        )
        # varying view for local grads — see make_classification_train_step
        params_v = jax.tree_util.tree_map(
            lambda a: pcast_varying(a, comm.axis_name), params
        )

        def loss_fn(p):
            # return_hidden is passed ONLY when fused_ce asks for it: the
            # step's contract covers any TransformerLM-SHAPED model, and a
            # user model without the kwarg must keep working un-fused
            extra = {"return_hidden": True} if fused_ce else {}
            if moe_experts:
                (out, aux), sown = model.apply(
                    p, tokens, pos_offset, return_aux=True,
                    mutable=["moe_stats"], **extra,
                )
            else:
                out, aux, sown = model.apply(
                    p, tokens, pos_offset, **extra), 0.0, {}
            if fused_ce:
                # fused head+loss: the [B, T, vocab] f32 logits pair is the
                # step's largest tensor (scripts/lm_roofline_aot.jsonl) —
                # the chunked CE never builds it (ops/losses.py)
                from chainermn_tpu.ops.losses import (
                    chunked_softmax_cross_entropy,
                )

                head = p["params"]["lm_head"]
                ce = chunked_softmax_cross_entropy(
                    out, head["kernel"], head.get("bias"), targets
                ).mean()
            else:
                ce = optax.softmax_cross_entropy_with_integer_labels(
                    out, targets
                ).mean()
            return ce + moe_aux_weight * aux, sown

        (loss, sown), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params_v)
        updates, new_opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = comm.allreduce(loss, "mean")
        if not moe_experts:
            return params, new_opt_state, loss, {}
        # routing telemetry: mean drop fraction over the MoE layers (each
        # leaf is already pmean'd over the expert axis inside the module)
        from chainermn_tpu.parallel.moe import drop_frac_from_sown

        return params, new_opt_state, loss, {
            "moe_drop_frac": drop_frac_from_sown(sown)}

    data = P(None, comm.axis_name) if shard_sequence else comm.data_spec
    opt_spec = getattr(optimizer, "state_spec", P())
    # 4th slot is the stats dict: {} for dense (P() applies to no leaves)
    out_specs = (P(), opt_spec, P(), P())
    sm = comm.shard_map(
        body,
        in_specs=(P(), opt_spec, data, data),
        out_specs=out_specs,
        # Pallas interpret mode can't thread varying-manner metadata through
        # kernel-internal literals (JAX suggests check_vma=False as the
        # workaround); semantics are unchanged, only the static check is off.
        # Compiled TPU kernels don't need the workaround — keep the check on.
        # ZeRO's all_gather'd updates likewise defeat the static check.
        check_vma=(attn not in ("flash", "ring_flash", "zigzag_flash",
                            "ulysses_flash")
                   or jax.default_backend() == "tpu")
        and getattr(optimizer, "check_vma", True)
        and getattr(comm, "check_vma", True),
    )
    donate_argnums = (0, 1) if donate else ()
    jitted = jax.jit(sm, donate_argnums=donate_argnums)
    return instrument(jitted, "lm_train_step") if monitored else jitted


def fit(
    step: Callable,
    variables,
    opt_state,
    data,
    n_steps: int,
    *,
    fetch_every: int = 8,
    prefetch_depth: int = 0,
    sharding=None,
    transform: Optional[Callable] = None,
    on_loss: Optional[Callable] = None,
    name: str = "fit",
) -> tuple:
    """The async hot loop: drive a jitted step ``n_steps`` times with
    dispatch-ahead loss handling and (optionally) device prefetch.

    The synchronous pattern — ``batch = next(data); ...; float(loss)``
    per step — pays host latencies on the critical path twice: the input
    side (assembly + H2D after the step instead of under it) and the
    output side (a device->host round trip per step; PERF.md measured
    ~80 ms of RTT per blocked step through the axon tunnel). This loop
    pays neither: batches arrive device-resident from a
    :class:`~chainermn_tpu.dataflow.DevicePrefetcher` producer thread,
    and losses stay ON DEVICE in a
    :class:`~chainermn_tpu.dataflow.LossWindow`, fetched batched every
    ``fetch_every`` steps — one round trip closes the whole window and
    bounds in-flight dispatch at ``fetch_every`` steps.

    Parameters
    ----------
    step : callable
        ``step(variables, opt_state, x, y)`` returning
        ``(variables, opt_state, loss)`` (:func:`jit_train_step`) or
        ``(params, opt_state, loss, stats)`` (:func:`jit_lm_train_step`;
        ``stats`` is dropped here — drive MoE telemetry loops manually).
    data : iterator or iterable
        Yields ``(x, y)`` batch pairs. With ``prefetch_depth > 0`` it is
        wrapped in a ``DevicePrefetcher(depth=prefetch_depth,
        sharding=sharding, transform=transform)``; otherwise batches are
        fed as yielded (pass an already-wrapped prefetcher here to keep
        its ``state_dict`` under your control).
    fetch_every : int
        Loss-fetch cadence AND the in-flight dispatch bound.
        ``fetch_every=1`` degenerates to the synchronous per-step fetch.
    on_loss : callable, optional
        ``on_loss(step_index, float_loss)`` per loss, at fetch time
        (i.e. up to ``fetch_every - 1`` steps late).

    Returns
    -------
    ``(variables, opt_state, losses)`` — ``losses`` is every step's loss
    as floats, in step order; the trailing drain doubles as the loop's
    completion barrier, so on return all ``n_steps`` steps have finished
    on device.

    Every step runs inside a ``train_step`` trace (the monitor's tracing
    layer): child spans attribute the wall time to ``prefetch_wait``
    (drawing the batch — a stall here means the input pipeline is the
    bottleneck), ``dispatch`` (enqueueing the device step — async, so
    normally microseconds), and ``loss_fetch`` (the batched host round
    trip the loss window pays once per ``fetch_every`` steps). Sampled
    per the default tracer's config; disabled tracing costs one no-op
    call per step.
    """
    from chainermn_tpu.dataflow import DevicePrefetcher, LossWindow
    from chainermn_tpu.monitor.trace import get_tracer

    prefetcher = None
    if prefetch_depth:
        data = prefetcher = DevicePrefetcher(
            data, depth=prefetch_depth, sharding=sharding,
            transform=transform, name=name)
    it = data if hasattr(data, "__next__") else iter(data)
    window = LossWindow(fetch_every, name=name, on_fetch=on_loss)
    tracer = get_tracer()
    try:
        for i in range(n_steps):
            with tracer.trace("train_step", kind="train", step=i,
                              loop=name):
                with tracer.span("prefetch_wait"):
                    x, y = next(it)
                with tracer.span("dispatch"):
                    out = step(variables, opt_state, x, y)
                variables, opt_state = out[0], out[1]
                # a fetch inside push lands as a loss_fetch child span
                window.push(i, out[2])
        losses = window.drain()
    finally:
        if prefetcher is not None:
            prefetcher.close()
    return variables, opt_state, losses
