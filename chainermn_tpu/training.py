"""Canonical data-parallel training step.

The reference's per-step control flow lives in Chainer's Trainer/Updater
(SURVEY.md S1: ChainerMN only wraps the optimizer hook, S3.2). In the TPU
rebuild the equivalent "hot loop contract" is a single jitted SPMD program:
forward + backward + cross-rank gradient mean + optimizer update + BN-stat
sync, built here once and reused by bench.py, the examples, and
``__graft_entry__``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators.communicator_base import CommunicatorBase


def make_classification_train_step(
    model,
    optimizer: optax.GradientTransformation,
    comm: CommunicatorBase,
    train_kwargs: Optional[dict] = None,
) -> Callable:
    """Build the per-rank step body (to be wrapped by :func:`jit_train_step`).

    ``variables`` is a flax variables dict ({'params', 'batch_stats', ...});
    mutable collections (BN running stats) are updated from the local batch
    and then cross-rank averaged inside the step, so evaluation state is
    replica-consistent by construction (the reference needs a separate
    AllreducePersistent pass for this; we keep that extension for parity but
    the canonical step doesn't need it).
    """
    train_kwargs = dict(train_kwargs or {})

    def step(variables, opt_state, images, labels):
        params = variables["params"]
        rest = {k: v for k, v in variables.items() if k != "params"}
        mutable = list(rest.keys())

        def loss_fn(p):
            if mutable:
                logits, updated = model.apply(
                    {"params": p, **rest}, images, mutable=mutable, **train_kwargs
                )
            else:
                logits = model.apply({"params": p}, images, **train_kwargs)
                updated = {}
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            ).mean()
            return loss, updated

        (loss, updated), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # replica-consistent mutable state (BN running stats are tiny; one
        # extra small collective per step)
        synced = {
            k: jax.tree_util.tree_map(lambda a: comm.allreduce(a, "mean"), v)
            for k, v in updated.items()
        }
        new_variables = {"params": params, **synced}
        return new_variables, opt_state, comm.allreduce(loss, "mean")

    return step


def jit_train_step(
    model,
    optimizer: optax.GradientTransformation,
    comm: CommunicatorBase,
    donate: bool = True,
    train_kwargs: Optional[dict] = None,
) -> Callable:
    """The full jitted SPMD train step over the communicator's mesh.

    Call as ``step(variables, opt_state, images, labels)`` with ``variables``/
    ``opt_state`` replicated and the batch rank-major (leading axis = global
    batch, sharded over the mesh). Buffer donation keeps params/opt-state
    updates in-place on HBM (the reference's grow-only arenas play this role,
    SURVEY.md S2.9).
    """
    body = make_classification_train_step(model, optimizer, comm, train_kwargs)
    data = comm.data_spec
    sm = comm.shard_map(
        body,
        in_specs=(P(), P(), data, data),
        out_specs=(P(), P(), P()),
    )
    donate_argnums = (0, 1) if donate else ()
    return jax.jit(sm, donate_argnums=donate_argnums)
