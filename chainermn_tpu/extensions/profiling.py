"""Observability: step timing, per-step collective-traffic stats, profiler
trace helper, and a hang watchdog.

The reference ships NO profiling of its own (SURVEY.md S5: users reach for
Chainer hooks + nvprof; the paper profiles externally) and no hang
detection (a lost collective blocks forever in NCCL/MPI). The TPU rebuild
owes both: XLA gives tracing nearly free (``jax.profiler``), compiled
programs make comm traffic *statically knowable* (read the collectives out
of the lowered HLO instead of instrumenting a byte-mover), and XLA
collectives hang exactly like NCCL ones, so a watchdog turns silent stalls
into actionable failures (the same fail-fast stance as
``global_except_hook``, SURVEY.md S3.5).
"""

from __future__ import annotations

import contextlib
import re
import sys
import threading
import time
from typing import Any, Callable, Optional

import numpy as np

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1,
}

_COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast",
)

# `%name = TYPE op-name(` — TYPE is `f32[8,128]{...}` or a (tuple, of,
# them). The type is captured LAZILY up to the first lowercase
# word-followed-by-"(" — the op name — because real TPU layouts embed
# parens inside the braces (`{1,0:T(8,128)(2,1)S(1)}`), which a greedy
# "(...)" alternation cannot survive (that bug silently dropped every
# collective-permute-start from round-3-era counts).
_INSTR_RE = re.compile(r"=\s*(.*?)\s*([a-z][a-z0-9-]*(?:\.[0-9]+)?)\(")
_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")


def _shape_list(type_str: str) -> list[tuple[int, bool]]:
    """[(bytes, is_control), ...] for every array shape in a type string
    (layout annotations are ignored). Control words — the u32[] scalars TPU
    async-starts append to their tuples — are flagged BY DTYPE AND RANK so
    they can be filtered from payload math; a genuinely scalar payload of
    any other dtype (an f32[] loss psum) stays a payload."""
    out = []
    for dtype, dims in _SHAPE_RE.findall(type_str):
        if dtype not in _DTYPE_BYTES:
            continue  # token types etc.
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out.append((n * _DTYPE_BYTES[dtype], dtype == "u32" and dims == ""))
    return out


def _type_bytes(type_str: str) -> int:
    return sum(b for b, _ in _shape_list(type_str))


def parse_hlo_collectives(hlo: str) -> dict[str, Any]:
    """Count collectives + their output bytes in HLO text.

    Post-optimization TPU/GPU HLO rewrites collectives into async
    ``<op>-start`` / ``<op>-done`` pairs: the ``-start`` carries the payload
    type and is counted under the base op name (TPU starts append u32[]
    control scalars to the tuple — filtered out of the payload math);
    ``-done`` is skipped so pairs aren't double-counted. Collectives inside
    a ``while`` body (e.g. a ring's per-step ppermute) count ONCE, not once
    per iteration — this reports the program's collective *structure*; wire
    volume per step multiplies by the trip count.
    """
    stats: dict[str, Any] = {}
    total = 0
    for m in _INSTR_RE.finditer(hlo):
        type_str, op = m.group(1), m.group(2)
        op = op.split(".")[0]  # strip .N instance suffixes
        if op.endswith("-done"):
            continue
        is_start = op.endswith("-start")
        base = op[: -len("-start")] if is_start else op
        if base not in _COLLECTIVES:
            continue
        if is_start and type_str.startswith("("):
            els = [b for b, control in _shape_list(type_str) if not control]
            if not els:
                els = [b for b, _ in _shape_list(type_str)]
            if base == "all-reduce":
                # all-reduce-start's tuple members are all RESULTS (XLA's
                # all-reduce combiner emits variadic ops): count every one.
                nbytes = sum(els)
            elif len(els) % 2 == 0:
                # other async starts return (operands..., results...) pairs —
                # count the result half, matching the op's sync form (sum
                # would double-count; max picks the operand for
                # reduce-scatter).
                nbytes = sum(els[len(els) // 2 :])
            else:
                nbytes = max(els, default=0)
        else:
            nbytes = _type_bytes(type_str)
        entry = stats.setdefault(base, {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += nbytes
        total += nbytes
    stats["total_bytes"] = total
    return stats


# Memoized lowered-HLO text per (jitted fn, abstract arg shapes): the AOT
# ``lower().compile()`` below does not share the jit executable cache, so
# without this every collective_stats call paid one full extra XLA compile
# of a function the jit cache had already built (bench.py measured it twice
# per sweep cell). Keyed by id() but guarded by a weakref identity check so
# a recycled id can never serve another function's HLO.
_HLO_MEMO_MAX = 64
_hlo_memo: "dict[tuple, tuple]" = {}
_hlo_memo_info = {"hits": 0, "misses": 0}


def _abstract_sig(args, kwargs):
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            sig.append((tuple(leaf.shape), str(leaf.dtype)))
        else:
            sig.append(repr(leaf))
    return (treedef, tuple(sig))


def _lowered_hlo(jitted, args, kwargs) -> str:
    import weakref

    try:
        ref = weakref.ref(jitted)
    except TypeError:
        return jitted.lower(*args, **kwargs).compile().as_text()
    key = (id(jitted), _abstract_sig(args, kwargs))
    hit = _hlo_memo.get(key)
    if hit is not None and hit[0]() is jitted:
        _hlo_memo_info["hits"] += 1
        return hit[1]
    _hlo_memo_info["misses"] += 1
    hlo = jitted.lower(*args, **kwargs).compile().as_text()
    if len(_hlo_memo) >= _HLO_MEMO_MAX:  # bounded: drop the oldest entry
        _hlo_memo.pop(next(iter(_hlo_memo)))
    _hlo_memo[key] = (ref, hlo)
    return hlo


def collective_stats(fn: Callable, *args, **kwargs) -> dict[str, Any]:
    """Statically analyze one step's collective traffic from compiled HLO.

    ``fn`` is a jitted (or jittable) function; ``args`` example inputs.
    Returns ``{op: {"count": n, "bytes": output_bytes}, ...,
    "total_bytes": N}`` — output-shape bytes per collective, the standard
    proxy for wire traffic (all-gather output == gathered bytes, all-reduce
    output ~= ring traffic x 2(n-1)/n).

    This replaces instrumenting a hand-written byte-mover (the reference
    would count what it memcpy'd): under XLA the program IS the ground
    truth. The AOT ``lower().compile()`` does not share the jit executable
    cache, so the lowered HLO text is memoized per (jitted fn, abstract
    shapes): repeated calls — bench sweep cells, the monitor — pay the
    extra XLA compile once, not every time.
    """
    import jax

    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    return parse_hlo_collectives(_lowered_hlo(jitted, args, kwargs))


def latency_report(samples, prefix: str) -> dict[str, float]:
    """``{prefix}_mean_s`` / ``{prefix}_p50_s`` / ``{prefix}_p99_s`` from a
    list of second-valued samples — the one percentile convention every
    latency surface (``StepTimer`` steps, serving TTFT/TPOT) reports in, so
    records from training and serving benchmarks stay field-compatible.
    Empty input returns ``{}`` (no samples is not 0 latency)."""
    if not len(samples):
        return {}
    t = np.asarray(samples, dtype=np.float64)
    return {
        f"{prefix}_mean_s": float(t.mean()),
        f"{prefix}_p50_s": float(np.percentile(t, 50)),
        f"{prefix}_p99_s": float(np.percentile(t, 99)),
    }


class StepTimer:
    """Wall-clock step statistics with warmup exclusion.

    Use as a context manager around each step (or call ``tick()`` once per
    step); ``report()`` returns mean/p50/p99 step time and items/sec. The
    per-step comm-bytes x step-time pairing (SURVEY.md S5) comes from
    combining this with :func:`collective_stats`.
    """

    def __init__(self, warmup: int = 2, items_per_step: int = 0) -> None:
        self._warmup = warmup
        self._items = items_per_step
        self._times: list[float] = []
        self._seen = 0
        self._t0: Optional[float] = None
        self._last: Optional[float] = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._record(time.perf_counter() - self._t0)

    def tick(self) -> None:
        """Alternative to the context manager: call once per completed step
        (the first call only arms the clock)."""
        now = time.perf_counter()
        if self._last is not None:
            self._record(now - self._last)
        self._last = now

    def _record(self, dt: float) -> None:
        self._seen += 1
        if self._seen > self._warmup:
            self._times.append(dt)

    @property
    def steps(self) -> int:
        return len(self._times)

    def report(self) -> dict[str, float]:
        if not self._times:
            return {"steps": 0}
        out = {"steps": len(self._times)}
        out.update(latency_report(self._times, "step_time"))
        if self._items:
            out["items_per_sec"] = self._items / out["step_time_mean_s"]
        return out


@contextlib.contextmanager
def trace(log_dir: str):
    """``jax.profiler`` trace around a code block; view in XProf/Perfetto.
    (The reference points users at nvprof; this is the TPU equivalent.)"""
    import jax

    with jax.profiler.trace(log_dir):
        yield


class Watchdog:
    """Deadlock watchdog: a hung step (lost collective peer, wedged host
    callback) dumps every thread's stack and — by default — aborts the
    process so the launcher can restart it, instead of hanging silently
    forever the way a lost NCCL/XLA collective does.

    Use around each step::

        dog = Watchdog(timeout=300)
        with dog.step():
            train_step(...)

    ``on_timeout='warn'`` only reports — re-armed each period, so a
    multi-period hang keeps reporting instead of going quiet after one.
    """

    def __init__(self, timeout: float, on_timeout: str = "abort",
                 _sink=None) -> None:
        if on_timeout not in ("abort", "warn"):
            raise ValueError(f"on_timeout must be abort|warn, got {on_timeout!r}")
        self._timeout = timeout
        self._mode = on_timeout
        self._sink = _sink or sys.stderr
        self._fired = threading.Event()
        self._timer: Optional[threading.Timer] = None
        # Generation counter guards the warn-mode re-arm against racing a
        # step() exit: each step entry/exit bumps the generation, and a timer
        # carrying a stale generation discards itself instead of re-arming a
        # watchdog for a step that already finished.
        self._lock = threading.Lock()
        self._gen = 0
        self._armed = False
        self._ctx: dict = {}

    def _fire(self, where: str, gen: int) -> None:
        with self._lock:
            if gen != self._gen or not self._armed:
                return  # the watched step finished; stale timer, stand down
            ctx = dict(self._ctx)
        self._fired.set()
        import faulthandler

        who = (" " + " ".join(f"{k}={v}" for k, v in ctx.items())
               if ctx else "")
        print(
            f"chainermn_tpu.Watchdog: step exceeded {self._timeout}s "
            f"({where}{who}) — a peer likely died inside a collective. "
            "Thread stacks follow.",
            file=self._sink, flush=True,
        )
        try:
            # faulthandler needs a real fd; test sinks (StringIO) don't have
            # one, so fall back to a pure-Python dump in faulthandler's
            # format ("Thread 0x... (most recent call first):").
            self._sink.fileno()
            faulthandler.dump_traceback(file=self._sink)
        except Exception:
            try:
                import traceback

                current = threading.get_ident()
                for tid, frame in sys._current_frames().items():
                    tag = "Current thread" if tid == current else "Thread"
                    print(f"{tag} {tid:#x} (most recent call first):",
                          file=self._sink)
                    for line in reversed(traceback.format_stack(frame)):
                        self._sink.write(line)
                self._sink.flush()
            except Exception:
                pass
        # Flight recorder: what the system was DOING when it wedged — the
        # last N structured events (slot admits/retires, steps, compiles)
        # plus per-device memory stats, not just where threads are parked.
        # once="failure": one dump per failure episode per sink — a warn-
        # mode re-fire or the excepthook that follows an abort re-prints
        # thread stacks but not a duplicate flight record.
        try:
            from chainermn_tpu.monitor import emit, get_event_log

            # ctx carries the caller's request/trace identity (the
            # serving scheduler labels every watched device call), so the
            # fire event joins against exported traces
            emit("watchdog_fire", where=where, timeout_s=self._timeout,
                 mode=self._mode, **ctx)
            get_event_log().dump(file=self._sink, once="failure")
        except Exception:
            pass
        if self._mode == "abort":
            import os

            os._exit(43)  # mirror global_except_hook: die loudly, not hang
        with self._lock:  # warn mode: re-arm so long hangs keep reporting
            if self._armed and gen == self._gen:
                self._start_timer_locked(where)

    def _start_timer_locked(self, label: str) -> None:
        self._timer = threading.Timer(
            self._timeout, self._fire, args=(label, self._gen)
        )
        self._timer.daemon = True
        self._timer.start()

    @property
    def fired(self) -> bool:
        """Whether any watched step has ever timed out (for tests/metrics)."""
        return self._fired.is_set()

    @contextlib.contextmanager
    def step(self, label: str = "train step", **context):
        """Watch one step. ``context`` (request ids, trace ids — whatever
        identifies the work) rides into the ``watchdog_arm``/
        ``watchdog_fire`` events and the fire banner, so a hang dump
        names the victims instead of just the call site."""
        with self._lock:
            self._gen += 1
            self._armed = True
            self._ctx = context
            self._start_timer_locked(label)
        try:  # arm event: correlates hangs with the surrounding activity
            from chainermn_tpu.monitor import emit

            emit("watchdog_arm", label=label, timeout_s=self._timeout,
                 **context)
        except Exception:
            pass
        try:
            yield
        finally:
            with self._lock:
                self._gen += 1
                self._armed = False
                self._ctx = {}
                if self._timer is not None:
                    self._timer.cancel()
