"""AllreducePersistent — sync non-parameter model state across replicas.

Re-design of ``[U] chainermn/extensions/allreduce_persistent.py``
(SURVEY.md S2.14 — unverified cite): the reference allreduce-means every
``namedpersistent()`` array (BN running mean/var) so evaluation sees
consistent statistics without MultiNodeBatchNormalization.

Flax mapping: "persistents" are the non-``params`` collections of a
variables dict (``batch_stats`` et al.). The canonical jitted train step
(``chainermn_tpu.training``) already keeps them replica-consistent inside
the step; this extension covers the reference workflow where per-replica
state drifts (custom loops, eager rank-major state) and is averaged
on demand before evaluation/checkpointing.
"""

from __future__ import annotations

import jax

from chainermn_tpu.communicators.communicator_base import CommunicatorBase


class AllreducePersistent:
    """Callable extension: average all non-params collections across ranks.

    Usage::

        sync = AllreducePersistent(comm)
        variables = sync(variables)          # eager, rank-major state
        # or inside a traced step: variables = sync(variables)

    Works in both calling contexts because the communicator's ``allreduce``
    is dual traced/eager.
    """

    # mirror of the reference extension's default trigger (every epoch);
    # carried as metadata for loops that honor it
    trigger = (1, "epoch")
    priority = -100  # run after optimizer updates, like the reference

    def __init__(self, communicator: CommunicatorBase) -> None:
        self._comm = communicator

    def __call__(self, variables):
        if not isinstance(variables, dict):
            raise TypeError(
                f"expected a flax variables dict, got {type(variables).__name__}"
            )
        out = {}
        for collection, tree in variables.items():
            if collection == "params":
                out[collection] = tree
            else:
                out[collection] = jax.tree_util.tree_map(
                    lambda a: self._comm.allreduce(a, "mean"), tree
                )
        return out
