"""Sharded (orbax-backed) checkpointing — TPU extension beyond the reference.

The reference's checkpointer (SURVEY.md S2.14; ``extensions/checkpoint.py``
here) writes one snapshot per process and agrees on the newest common
iteration — matching it needs no sharding awareness. This module is the
TPU-idiomatic upgrade SURVEY S5 calls out as *exceeding* upstream: it saves
``jax.Array`` pytrees **with their shardings** through orbax, so

- each process writes only its local shards (a ZeRO-sharded optimizer state
  costs 1/n of the bytes per process, not n copies of everything);
- restore places every leaf back onto its original sharding (replicated
  leaves stay replicated, rank-sharded moments stay rank-sharded) given a
  template of like-sharded arrays;
- snapshots are step-stamped and GC'd to ``keep`` newest, mirroring the
  round-robin GC of the reference checkpointer.

Single- and multi-process: orbax coordinates multi-host writes through
jax.distributed on its own.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


class ShardedCheckpointer:
    """Step-stamped sharded snapshots under ``path``.

    Usage::

        cp = ShardedCheckpointer("/ckpts/run1", keep=3)
        cp.save(step, {"params": params, "opt": opt_state})
        restored, step = cp.maybe_restore(
            {"params": params, "opt": opt_state})   # template: like-sharded
    """

    def __init__(self, path: str, keep: int = 3) -> None:
        import orbax.checkpoint as ocp

        self._path = os.path.abspath(path)
        self._keep = keep
        self._mgr = ocp.CheckpointManager(
            self._path,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True
            ),
        )

    def save(self, step: int, state: Any, *, wait: bool = True) -> None:
        """Write a snapshot of ``state`` (a pytree of jax.Arrays) at
        ``step``; each process persists only its addressable shards."""
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def maybe_restore(self, template: Any) -> tuple[Optional[Any], Optional[int]]:
        """Restore the newest snapshot onto ``template``'s shardings.

        Returns ``(state, step)`` or ``(None, None)`` when no snapshot
        exists. ``template`` supplies structure, dtypes, shapes AND
        shardings (pass the live state you would otherwise initialize)."""
        import orbax.checkpoint as ocp

        step = self._mgr.latest_step()
        if step is None:
            return None, None
        restored = self._mgr.restore(
            step,
            args=ocp.args.StandardRestore(jax.tree_util.tree_map(
                lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=l.sharding)
                if hasattr(l, "sharding") else l,
                template,
            )),
        )
        return restored, step

    def all_steps(self) -> list[int]:
        return sorted(self._mgr.all_steps())

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


__all__ = ["ShardedCheckpointer"]
