"""Sharded (orbax-backed) checkpointing — TPU extension beyond the reference.

The reference's checkpointer (SURVEY.md S2.14; ``extensions/checkpoint.py``
here) writes one snapshot per process and agrees on the newest common
iteration — matching it needs no sharding awareness. This module is the
TPU-idiomatic upgrade SURVEY S5 calls out as *exceeding* upstream: it saves
``jax.Array`` pytrees **with their shardings** through orbax, so

- each process writes only its local shards (a ZeRO-sharded optimizer state
  costs 1/n of the bytes per process, not n copies of everything);
- restore places every leaf back onto the **template's** shardings — which
  need not be the save-time ones: orbax gathers-or-slices each leaf onto
  whatever mesh/spec the template (or an explicit ``shardings=`` override)
  declares, which is what makes snapshots the elastic-restore substrate
  (``chainermn_tpu.deploy.reshard`` builds on exactly this, adding the
  TP-degree permutation orbax cannot know about);
- snapshots are step-stamped and GC'd to ``keep`` newest, mirroring the
  round-robin GC of the reference checkpointer.

Hardening (unified with ``MultiNodeCheckpointer``): every save also writes
a small **manifest** sidecar (save-time mesh shape / TP degree / caller
meta) carrying the same CRC32 checksum footer, written atomically
(tmp + rename); a corrupt manifest is reported as absent rather than
trusted, and legacy footerless/manifest-less checkpoints restore exactly
as before. An optional :class:`~chainermn_tpu.resilience.retry.RetryPolicy`
wraps the save/restore I/O (``sharded_checkpoint.save`` /
``sharded_checkpoint.load`` ops), and both paths carry the matching
fault-injection cut-points.

Single- and multi-process: orbax coordinates multi-host writes through
jax.distributed on its own. The manifest lives in a sibling ``<path>.meta``
directory so the orbax-managed tree stays exclusively orbax's.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional

import jax

from chainermn_tpu.extensions.checkpoint import _add_footer, _strip_footer
from chainermn_tpu.resilience.cutpoints import (
    SHARDED_CHECKPOINT_LOAD,
    SHARDED_CHECKPOINT_SAVE,
)
from chainermn_tpu.resilience.faults import inject


class ShardedCheckpointer:
    """Step-stamped sharded snapshots under ``path``.

    Usage::

        cp = ShardedCheckpointer("/ckpts/run1", keep=3)
        cp.save(step, {"params": params, "opt": opt_state})
        restored, step = cp.maybe_restore(
            {"params": params, "opt": opt_state})   # template: like-sharded
    """

    def __init__(self, path: str, keep: int = 3, *, retry=None) -> None:
        import orbax.checkpoint as ocp

        self._path = os.path.abspath(path)
        self._keep = keep
        self._retry = retry
        self._meta_dir = self._path + ".meta"
        self._mgr = ocp.CheckpointManager(
            self._path,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True
            ),
        )

    def _call(self, fn, *args, op: str):
        if self._retry is not None:
            return self._retry.call(fn, *args, op=op)
        return fn(*args)

    # ------------------------------------------------------------------ #
    # save                                                                #
    # ------------------------------------------------------------------ #

    def save(self, step: int, state: Any, *, wait: bool = True,
             meta: Optional[dict] = None) -> None:
        """Write a snapshot of ``state`` (a pytree of jax.Arrays) at
        ``step``; each process persists only its addressable shards.
        ``meta`` (mesh shape, TP degree, model dims — anything picklable)
        lands in the step's manifest sidecar for restore-time decisions."""
        import orbax.checkpoint as ocp

        def write():
            inject(SHARDED_CHECKPOINT_SAVE, step=step)
            self._mgr.save(step, args=ocp.args.StandardSave(state))

        self._call(write, op="sharded_checkpoint.save")
        self._write_manifest(step, meta or {})
        if wait:
            self._mgr.wait_until_finished()

    def _write_manifest(self, step: int, meta: dict) -> None:
        """CRC32-footered, atomically-renamed sidecar (the
        ``MultiNodeCheckpointer`` hardening idiom) holding save-time
        metadata; pruned alongside orbax's own GC."""
        os.makedirs(self._meta_dir, exist_ok=True)
        payload = pickle.dumps(dict(meta, step=int(step)))
        final = self._manifest_path(step)
        tmp = final + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_add_footer(payload))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
        # GC manifests for steps orbax no longer retains
        live = {int(s) for s in self._mgr.all_steps()} | {int(step)}
        for name in os.listdir(self._meta_dir):
            if not name.startswith("manifest_") or name.endswith(".tmp"):
                continue
            try:
                s = int(name[len("manifest_"):].split(".", 1)[0])
            except ValueError:
                continue
            if s not in live:
                try:
                    os.remove(os.path.join(self._meta_dir, name))
                except OSError:
                    pass

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self._meta_dir, f"manifest_{int(step)}.bin")

    def manifest(self, step: Optional[int] = None) -> Optional[dict]:
        """The manifest saved with ``step`` (newest when None), or None
        when this checkpoint predates manifests OR the sidecar is corrupt
        (a bad checksum is reported as absence, never trusted — restoring
        without metadata degrades to the legacy same-shape path)."""
        if step is None:
            step = self._mgr.latest_step()
            if step is None:
                return None
        path = self._manifest_path(step)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            payload, verified = _strip_footer(f.read())
        if verified is False:
            return None
        try:
            return pickle.loads(payload)
        except Exception:  # noqa: BLE001 — corrupt == absent
            return None

    # ------------------------------------------------------------------ #
    # restore                                                             #
    # ------------------------------------------------------------------ #

    def maybe_restore(self, template: Any, *, shardings: Any = None,
                      step: Optional[int] = None,
                      ) -> tuple[Optional[Any], Optional[int]]:
        """Restore a snapshot onto a **target** sharding layout.

        Returns ``(state, step)`` or ``(None, None)`` when no snapshot
        exists. ``template`` supplies structure, dtypes, shapes AND
        shardings (pass the live state you would otherwise initialize) —
        the target layout may differ from the save-time one: each leaf is
        gathered-or-sliced onto the template's sharding. ``shardings``
        overrides the template's layout — either ONE sharding applied to
        every leaf (e.g. replicated for a pre-reshard gather) or a
        like-structured pytree of shardings. ``step`` pins a specific
        snapshot (newest when None)."""
        import orbax.checkpoint as ocp

        if step is None:
            step = self._mgr.latest_step()
            if step is None:
                return None, None

        def struct(leaf, sh):
            if sh is not None:
                return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                            sharding=sh)
            if hasattr(leaf, "sharding"):
                return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                            sharding=leaf.sharding)
            return leaf

        if shardings is None:
            target = jax.tree_util.tree_map(
                lambda l: struct(l, None), template)
        elif isinstance(shardings, jax.sharding.Sharding):
            target = jax.tree_util.tree_map(
                lambda l: struct(l, shardings), template)
        else:
            target = jax.tree_util.tree_map(struct, template, shardings)

        def load():
            inject(SHARDED_CHECKPOINT_LOAD, step=step)
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(target))

        restored = self._call(load, op="sharded_checkpoint.load")
        return restored, step

    def all_steps(self) -> list[int]:
        return sorted(self._mgr.all_steps())

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


__all__ = ["ShardedCheckpointer"]
