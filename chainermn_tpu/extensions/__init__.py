"""Trainer-extension equivalents: persistence sync, checkpointing, metric
aggregation (SURVEY.md S2.14).

The reference plugs these into Chainer's Trainer extension protocol; the
rebuild has no trainer object, so each extension is a plain callable/class
the training loop invokes at its chosen interval — same contract, kwargs-
first, no framework coupling.
"""

from chainermn_tpu.extensions.allreduce_persistent import AllreducePersistent
from chainermn_tpu.extensions.checkpoint import (
    MultiNodeCheckpointer,
    create_multi_node_checkpointer,
)
from chainermn_tpu.extensions.observation_aggregator import ObservationAggregator
from chainermn_tpu.extensions.profiling import (
    StepTimer,
    Watchdog,
    collective_stats,
    latency_report,
    parse_hlo_collectives,
    trace,
)
from chainermn_tpu.extensions.sharded_checkpoint import ShardedCheckpointer

__all__ = [
    "AllreducePersistent",
    "MultiNodeCheckpointer",
    "create_multi_node_checkpointer",
    "ObservationAggregator",
    "ShardedCheckpointer",
    "StepTimer",
    "Watchdog",
    "collective_stats",
    "latency_report",
    "parse_hlo_collectives",
    "trace",
]
