"""ObservationAggregator — cross-rank averaging of reported metrics.

Re-design of the reference's ``ObservationAggregator`` extension
(SURVEY.md S5, metrics/observability — later-version addition, med
confidence): per-rank observation dicts (loss, accuracy, timings) are
averaged across ranks so root's log reflects the whole job, not one shard.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from chainermn_tpu.communicators.communicator_base import CommunicatorBase


class ObservationAggregator:
    """Callable: ``agg(observation_dict) -> cross-rank mean dict``.

    Non-numeric values pass through from root untouched. Keys must agree
    across ranks (they do in SPMD loops by construction).
    """

    def __init__(self, communicator: CommunicatorBase) -> None:
        self._comm = communicator

    def __call__(self, observation: Mapping[str, Any]) -> dict[str, Any]:
        gathered = self._comm.allgather_obj(dict(observation))
        keys = list(gathered[0].keys())
        for d in gathered[1:]:
            if list(d.keys()) != keys:
                raise ValueError(
                    f"observation keys diverged across ranks: {keys} vs {list(d.keys())}"
                )
        out: dict[str, Any] = {}
        for k in keys:
            vals = [d[k] for d in gathered]
            if all(isinstance(v, (int, float, np.number, np.ndarray)) or hasattr(v, "shape") for v in vals):
                mean = np.mean([np.asarray(v) for v in vals], axis=0)
                out[k] = float(mean) if mean.ndim == 0 else mean
            else:
                out[k] = vals[0]
        return out
