"""Distributed checkpoint/resume for fail-and-restart fault tolerance.

Re-design of ``[U] chainermn/extensions/checkpoint.py`` (SURVEY.md S2.14 —
unverified cite). Reference semantics, kept exactly:

- each rank writes **iteration-stamped, rank-local** snapshots
  (``snapshot_<name>_<iteration>.<rank>``) of its training state;
- old snapshots are garbage-collected, keeping the newest ``n_retains``;
- on startup ``maybe_load`` resumes every rank from the **newest commonly
  available** iteration — agreement runs over the host-side object channel
  (the reference uses MPI obj-comm), so ranks that lost local files force
  the whole job back to the last iteration everyone still has;
- resume requires the same world size (snapshots are per-rank local).

Torn-snapshot hardening (beyond the reference):

- every snapshot carries a **CRC32 checksum footer**; ``maybe_load``
  verifies it (and the unpickle) before trusting a file, so a torn write
  that survived the atomic rename (truncated flush, lost page) is
  *detected*, not resumed from;
- a corrupt newest-common iteration is **skipped back** collectively:
  every rank re-agrees without it and tries the next-newest, until an
  iteration loads intact on all ranks (footer-less legacy files are
  accepted — the unpickle is then the only integrity check);
- orphaned ``.tmp`` files from crashed saves are swept at startup;
- the save/load paths carry fault-injection cut-points
  (``checkpoint.save`` / ``checkpoint.write`` / ``checkpoint.load``) and
  an optional :class:`~chainermn_tpu.resilience.retry.RetryPolicy` for
  host-transient I/O, and publish save/load latency histograms plus a
  ``checkpoint_corrupt_total`` counter into the monitor registry.

Background checkpointing (the ``dataflow`` async hot loop):

- :meth:`MultiNodeCheckpointer.save_async` fixes the snapshot's content
  with a ``jax.device_get`` on the calling thread, then runs the exact
  sync-save I/O path (serialize + CRC footer + cut-points + retry +
  atomic rename + GC) on a single writer thread — the training loop
  resumes after the device fetch instead of after the disk write;
- write **and GC share one lock**, so a snapshot is never deleted while
  its successor is still ``.tmp`` and sync/async writes never interleave;
- :meth:`MultiNodeCheckpointer.wait_async` is the completion barrier
  (writer errors re-raise there and on the next ``save_async``);
  ``maybe_load`` and ``finalize`` join pending saves first, so a restore
  never races a pending write.

Serialization: state is any pytree of jax/numpy arrays plus picklable leaves
(e.g. ``{"variables": ..., "opt_state": ..., "iterator": it.state_dict()}``).
Arrays are fetched to host (``jax.device_get``) and pickled; writes are
atomic (tmp + rename) so a crash mid-save can't corrupt the newest common
iteration. Loaded leaves come back as numpy — callers ``device_put`` them
back onto their mesh (sharding is a property of the run, not the snapshot;
this is also what makes these snapshots host-count-portable *per rank*).
"""

from __future__ import annotations

import os
import pickle
import queue
import re
import struct
import threading
import time
import zlib
from typing import Any, Optional

import jax
import numpy as np

from chainermn_tpu.communicators.communicator_base import CommunicatorBase
from chainermn_tpu.monitor._state import get_event_log, get_registry
from chainermn_tpu.resilience.cutpoints import (
    CHECKPOINT_LOAD,
    CHECKPOINT_SAVE,
    CHECKPOINT_WRITE,
)
from chainermn_tpu.resilience.faults import inject, torn_fraction

# Footer: | payload ... | MAGIC (8B) | crc32 (4B, LE) | payload_len (8B, LE) |
_FOOTER_MAGIC = b"CMNTPUC1"
_FOOTER_TAIL = struct.Struct("<IQ")
_FOOTER_LEN = len(_FOOTER_MAGIC) + _FOOTER_TAIL.size


def _host_copy(leaf):
    """Fetch a leaf to host with OWNED bytes. ``jax.device_get`` copies
    device arrays but passes host numpy arrays through by reference — an
    aliased leaf would let the training loop mutate a snapshot that is
    still queued for the async writer."""
    out = jax.device_get(leaf)
    if out is leaf and isinstance(out, np.ndarray):
        out = out.copy()
    return out


def _add_footer(payload: bytes) -> bytes:
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return payload + _FOOTER_MAGIC + _FOOTER_TAIL.pack(crc, len(payload))


def _strip_footer(data: bytes) -> tuple[bytes, Optional[bool]]:
    """``(payload, verified)`` — ``True``: checksum matched; ``False``:
    footer present but corrupt; ``None``: legacy file without a footer
    (the unpickle is then the only check)."""
    if len(data) >= _FOOTER_LEN and data[-_FOOTER_LEN:-_FOOTER_TAIL.size] \
            == _FOOTER_MAGIC:
        crc, ln = _FOOTER_TAIL.unpack(data[-_FOOTER_TAIL.size:])
        payload = data[:-_FOOTER_LEN]
        ok = ln == len(payload) and (zlib.crc32(payload) & 0xFFFFFFFF) == crc
        return payload, ok
    return data, None


class MultiNodeCheckpointer:
    """See module docstring. Build via :func:`create_multi_node_checkpointer`."""

    def __init__(
        self,
        name: str,
        comm: CommunicatorBase,
        path: Optional[str] = None,
        n_retains: int = 5,
        *,
        rank: Optional[int] = None,
        retry=None,
    ) -> None:
        if not re.fullmatch(r"[A-Za-z0-9_.-]+", name):
            raise ValueError(f"checkpoint name must be filename-safe, got {name!r}")
        self.name = name
        self._comm = comm
        self._rank = comm.rank if rank is None else rank
        self.path = os.path.abspath(path or os.getcwd())
        os.makedirs(self.path, exist_ok=True)
        self._n_retains = int(n_retains)
        self._retry = retry
        self.stats: dict[str, list[float]] = {
            "save": [], "load": [], "save_async": []}
        reg = get_registry()
        labels = {"name": name}
        self._h_save = reg.histogram("checkpoint_save_seconds", labels,
                                     unit="s")
        self._h_load = reg.histogram("checkpoint_load_seconds", labels,
                                     unit="s")
        self._c_corrupt = reg.counter("checkpoint_corrupt_total", labels)
        self._h_async = reg.histogram("checkpoint_async_save_seconds",
                                      labels, unit="s")
        self._c_async_err = reg.counter("checkpoint_async_errors_total",
                                        labels)
        self._events = get_event_log()
        # One lock serializes every write+GC (sync save, async writer): a
        # snapshot must never be GC-deleted while its successor is still
        # `.tmp` — a crash in that window would leave NO intact newest
        # snapshot even though the save "mostly worked".
        self._io_lock = threading.Lock()
        self._async_q: Optional[queue.Queue] = None
        self._async_thread: Optional[threading.Thread] = None
        self._async_cv = threading.Condition()
        self._async_pending = 0
        self._async_errors: list[BaseException] = []
        self._sweep_tmp()

    def _sweep_tmp(self) -> None:
        """Remove this rank's orphaned ``.tmp`` files from crashed saves."""
        pat = re.compile(
            rf"snapshot_{re.escape(self.name)}_\d+\.{self._rank}\.tmp$"
        )
        for f in os.listdir(self.path):
            if pat.fullmatch(f):
                try:
                    os.remove(os.path.join(self.path, f))
                except OSError:
                    pass

    def _world_size(self) -> int:
        """Per-rank snapshots exist per PROCESS; world agreement is over the
        process count (== inter_size except on declared multi-process-per-
        host launches)."""
        return max(
            1, getattr(self._comm, "process_size", None) or self._comm.inter_size
        )

    # -- naming ---------------------------------------------------------- #

    def filename(self, iteration: int, rank: Optional[int] = None) -> str:
        r = self._rank if rank is None else rank
        return os.path.join(
            self.path, f"snapshot_{self.name}_{int(iteration)}.{r}"
        )

    def _local_iterations(self) -> list[int]:
        pat = re.compile(
            rf"snapshot_{re.escape(self.name)}_(\d+)\.{self._rank}$"
        )
        its = []
        for f in os.listdir(self.path):
            m = pat.fullmatch(f)
            if m:
                its.append(int(m.group(1)))
        return sorted(its)

    # -- save ------------------------------------------------------------ #

    def save(self, state: Any, iteration: int) -> str:
        """Snapshot this rank's ``state`` at ``iteration``; GC old ones."""
        t0 = time.time()
        inject(CHECKPOINT_SAVE, iteration=int(iteration))
        target = self._write_snapshot(jax.device_get(state), iteration)
        dt = time.time() - t0
        self.stats["save"].append(dt)
        self._h_save.observe(dt)
        return target

    def _write_snapshot(self, host_state: Any, iteration: int) -> str:
        """Serialize + CRC footer + atomic rename + GC — the I/O half of a
        save, shared by the sync path and the async writer thread. Write
        AND GC run under one lock so a snapshot is never deleted while its
        successor is still ``.tmp`` (and sync/async writes never
        interleave)."""
        target = self.filename(iteration)
        tmp = target + ".tmp"
        payload = {"world_size": self._world_size(), "state": host_state}
        blob = _add_footer(pickle.dumps(payload, protocol=4))
        # torn-write cut-point: a fired fault silently truncates the bytes
        # that reach disk — the data-loss case only the checksum catches
        frac = torn_fraction(CHECKPOINT_WRITE, iteration=int(iteration))
        data = blob if frac is None else blob[: int(len(blob) * frac)]

        def write() -> None:
            # _io_lock IS the I/O serializer: sync and async savers must
            # not interleave writes, so disk work under it is the
            # invariant, not a bug (PR 4 design)
            # graftlint: blocking-ok
            with open(tmp, "wb") as f:
                f.write(data[: len(data) // 2])
                # mid-write cut-point: a raise here leaves a torn .tmp —
                # the crash the atomic rename + startup sweep absorb
                inject(CHECKPOINT_WRITE, iteration=int(iteration))
                f.write(data[len(data) // 2:])
            # atomic publish belongs inside the same _io_lock hold as
            # the bytes it publishes  # graftlint: blocking-ok
            os.replace(tmp, target)

        with self._io_lock:
            if self._retry is not None:
                self._retry.call(write, op="checkpoint.save")
            else:
                write()
            self._gc()
        self._events.emit("checkpoint_save", iteration=int(iteration),
                          bytes=len(data))
        return target

    # -- async save ------------------------------------------------------ #

    def save_async(self, state: Any, iteration: int) -> str:
        """Snapshot without blocking the caller on serialization or disk.

        The calling thread does only ``jax.device_get`` — the consistency
        point: the snapshot's content is fixed here, so the training loop
        is free to keep mutating device buffers (donation included) the
        moment this returns. A single writer thread then runs the exact
        sync-save I/O path (:meth:`_write_snapshot`): same CRC footer,
        same ``checkpoint.write`` / torn-write cut-points, same retry
        policy, same atomic rename, and GC under the same lock.

        Failure surfacing: a writer-thread error is counted
        (``checkpoint_async_errors_total``), event-logged, and re-raised
        from the NEXT ``save_async`` or from :meth:`wait_async`;
        :meth:`maybe_load` and :meth:`finalize` join pending saves first,
        so a restore can never race (or trust) a half-written snapshot.
        """
        self.wait_async(raise_errors=True, join=False)
        inject(CHECKPOINT_SAVE, iteration=int(iteration))
        host_state = jax.tree_util.tree_map(_host_copy, state)
        self._ensure_writer()
        with self._async_cv:
            self._async_pending += 1
        self._async_q.put((host_state, int(iteration), time.time()))
        self._events.emit("checkpoint_save_async_enqueued",
                          iteration=int(iteration))
        return self.filename(iteration)

    def _ensure_writer(self) -> None:
        if self._async_q is None:
            self._async_q = queue.Queue()
        if self._async_thread is None or not self._async_thread.is_alive():
            self._async_thread = threading.Thread(
                target=self._writer_loop, name=f"ckpt-writer-{self.name}",
                daemon=True)
            self._async_thread.start()

    def _writer_loop(self) -> None:
        while True:
            job = self._async_q.get()
            if job is None:
                return
            host_state, iteration, t_enq = job
            try:
                self._write_snapshot(host_state, iteration)
                dt = time.time() - t_enq
                self.stats["save_async"].append(dt)
                self._h_async.observe(dt)
            except BaseException as e:  # noqa: BLE001 — surfaced at join
                self._c_async_err.inc()
                self._events.emit(
                    "checkpoint_async_error", iteration=int(iteration),
                    error=f"{type(e).__name__}: {e}"[:200])
                with self._async_cv:
                    self._async_errors.append(e)
            finally:
                with self._async_cv:
                    self._async_pending -= 1
                    self._async_cv.notify_all()

    def wait_async(self, raise_errors: bool = True, join: bool = True
                   ) -> bool:
        """Join every pending async save (the pre-restore / end-of-run
        barrier). Returns True when all saves since the last wait landed
        intact. ``raise_errors=False`` is the restore path's posture —
        failures stay counted/evented only, because a missing snapshot is
        already handled by the newest-common-iteration agreement."""
        with self._async_cv:
            if join:
                while self._async_pending:
                    self._async_cv.wait(timeout=0.5)
            errs = list(self._async_errors)
            self._async_errors.clear()
        if errs and raise_errors:
            raise errs[0]
        return not errs

    def _shutdown_writer(self) -> None:
        if self._async_thread is not None and self._async_thread.is_alive():
            self._async_q.put(None)
            self._async_thread.join(timeout=5.0)
        self._async_thread = None

    def _gc(self) -> None:
        its = self._local_iterations()
        for it in its[: max(0, len(its) - self._n_retains)]:
            try:
                # GC-under-write-lock is deliberate (PR 4): a snapshot
                # must never be deleted while its successor is still a
                # torn .tmp  # graftlint: blocking-ok
                os.remove(self.filename(it))
            except OSError:
                pass  # already gone; never fail training over GC

    # -- load ------------------------------------------------------------ #

    def _try_load(self, iteration: int) -> Optional[dict]:
        """Read + verify + unpickle one local snapshot; None when corrupt
        (counted and event-logged, never raised — corruption is a vote to
        skip back, not a crash)."""
        try:
            def read() -> bytes:
                with open(self.filename(iteration), "rb") as f:
                    return f.read()

            data = (self._retry.call(read, op="checkpoint.load")
                    if self._retry is not None else read())
            payload_bytes, verified = _strip_footer(data)
            if verified is False:
                raise ValueError("checksum mismatch (torn write?)")
            payload = pickle.loads(payload_bytes)
            if not isinstance(payload, dict) or "state" not in payload:
                raise ValueError("malformed snapshot payload")
            return payload
        except Exception as e:
            self._c_corrupt.inc()
            self._events.emit("checkpoint_corrupt",
                              iteration=int(iteration),
                              error=f"{type(e).__name__}: {e}"[:200])
            return None

    def maybe_load(self, state: Any = None) -> tuple[Any, int]:
        """Resume from the newest iteration available AND intact on ALL
        ranks.

        Returns ``(loaded_state, iteration)``; when no common snapshot
        exists, returns ``(state, 0)`` unchanged (fresh start) — the
        reference's ``resume = checkpointer.maybe_load(trainer)`` contract.
        A corrupt copy anywhere (checksum/unpickle failure) makes every
        rank discard that iteration and re-agree on the next-newest — the
        skip-back loop is collective, so ranks never split over which
        snapshot to trust.
        """
        # pre-restore join: never race (or half-trust) a pending async
        # save — a failed one is just a missing/old snapshot to the
        # agreement below, so errors are not re-raised here
        self.wait_async(raise_errors=False)
        inject(CHECKPOINT_LOAD)
        local = set(self._local_iterations())
        while True:
            all_sets = self._comm.allgather_obj(local)
            common = set.intersection(*map(set, all_sets)) if all_sets else set()
            if not common:
                return state, 0
            it = max(common)
            t0 = time.time()
            payload = self._try_load(it)
            oks = self._comm.allgather_obj(payload is not None)
            if all(oks):
                world_now = self._world_size()
                if payload["world_size"] != world_now:
                    raise RuntimeError(
                        f"snapshot '{self.name}' iteration {it} was taken with "
                        f"{payload['world_size']} processes but this job has "
                        f"{world_now}; per-rank snapshots require the same "
                        "world size"
                    )
                dt = time.time() - t0
                self.stats["load"].append(dt)
                self._h_load.observe(dt)
                self._events.emit("checkpoint_load", iteration=int(it))
                return payload["state"], it
            # someone's copy of `it` is corrupt: skip back collectively
            local.discard(it)

    # -- misc ------------------------------------------------------------ #

    def get_stats(self) -> dict[str, float]:
        """Mean save/load seconds (reference exposes timing stats)."""
        return {
            k: (sum(v) / len(v) if v else 0.0) for k, v in self.stats.items()
        }

    def finalize(self) -> None:
        """Remove every snapshot this rank owns (reference ``finalize``).
        Joins pending async saves and stops the writer thread first."""
        self.wait_async(raise_errors=False)
        self._shutdown_writer()
        for it in self._local_iterations():
            try:
                os.remove(self.filename(it))
            except OSError:
                pass


def create_multi_node_checkpointer(
    name: str,
    comm: CommunicatorBase,
    path: Optional[str] = None,
    n_retains: int = 5,
    **kwargs,
) -> MultiNodeCheckpointer:
    """Reference ``create_multi_node_checkpointer(name, comm, ...)``."""
    return MultiNodeCheckpointer(name, comm, path, n_retains, **kwargs)
