"""Distributed checkpoint/resume for fail-and-restart fault tolerance.

Re-design of ``[U] chainermn/extensions/checkpoint.py`` (SURVEY.md S2.14 —
unverified cite). Reference semantics, kept exactly:

- each rank writes **iteration-stamped, rank-local** snapshots
  (``snapshot_<name>_<iteration>.<rank>``) of its training state;
- old snapshots are garbage-collected, keeping the newest ``n_retains``;
- on startup ``maybe_load`` resumes every rank from the **newest commonly
  available** iteration — agreement runs over the host-side object channel
  (the reference uses MPI obj-comm), so ranks that lost local files force
  the whole job back to the last iteration everyone still has;
- resume requires the same world size (snapshots are per-rank local).

Serialization: state is any pytree of jax/numpy arrays plus picklable leaves
(e.g. ``{"variables": ..., "opt_state": ..., "iterator": it.state_dict()}``).
Arrays are fetched to host (``jax.device_get``) and pickled; writes are
atomic (tmp + rename) so a crash mid-save can't corrupt the newest common
iteration. Loaded leaves come back as numpy — callers ``device_put`` them
back onto their mesh (sharding is a property of the run, not the snapshot;
this is also what makes these snapshots host-count-portable *per rank*).
"""

from __future__ import annotations

import os
import pickle
import re
import time
from typing import Any, Optional

import jax

from chainermn_tpu.communicators.communicator_base import CommunicatorBase


class MultiNodeCheckpointer:
    """See module docstring. Build via :func:`create_multi_node_checkpointer`."""

    def __init__(
        self,
        name: str,
        comm: CommunicatorBase,
        path: Optional[str] = None,
        n_retains: int = 5,
        *,
        rank: Optional[int] = None,
    ) -> None:
        if not re.fullmatch(r"[A-Za-z0-9_.-]+", name):
            raise ValueError(f"checkpoint name must be filename-safe, got {name!r}")
        self.name = name
        self._comm = comm
        self._rank = comm.rank if rank is None else rank
        self.path = os.path.abspath(path or os.getcwd())
        os.makedirs(self.path, exist_ok=True)
        self._n_retains = int(n_retains)
        self.stats: dict[str, list[float]] = {"save": [], "load": []}
        self._sweep_tmp()

    def _sweep_tmp(self) -> None:
        """Remove this rank's orphaned ``.tmp`` files from crashed saves."""
        pat = re.compile(
            rf"snapshot_{re.escape(self.name)}_\d+\.{self._rank}\.tmp$"
        )
        for f in os.listdir(self.path):
            if pat.fullmatch(f):
                try:
                    os.remove(os.path.join(self.path, f))
                except OSError:
                    pass

    def _world_size(self) -> int:
        """Per-rank snapshots exist per PROCESS; world agreement is over the
        process count (== inter_size except on declared multi-process-per-
        host launches)."""
        return max(
            1, getattr(self._comm, "process_size", None) or self._comm.inter_size
        )

    # -- naming ---------------------------------------------------------- #

    def filename(self, iteration: int, rank: Optional[int] = None) -> str:
        r = self._rank if rank is None else rank
        return os.path.join(
            self.path, f"snapshot_{self.name}_{int(iteration)}.{r}"
        )

    def _local_iterations(self) -> list[int]:
        pat = re.compile(
            rf"snapshot_{re.escape(self.name)}_(\d+)\.{self._rank}$"
        )
        its = []
        for f in os.listdir(self.path):
            m = pat.fullmatch(f)
            if m:
                its.append(int(m.group(1)))
        return sorted(its)

    # -- save ------------------------------------------------------------ #

    def save(self, state: Any, iteration: int) -> str:
        """Snapshot this rank's ``state`` at ``iteration``; GC old ones."""
        t0 = time.time()
        target = self.filename(iteration)
        tmp = target + ".tmp"
        payload = {
            "world_size": self._world_size(),
            "state": jax.device_get(state),
        }
        with open(tmp, "wb") as f:
            pickle.dump(payload, f, protocol=4)
        os.replace(tmp, target)
        self._gc()
        self.stats["save"].append(time.time() - t0)
        return target

    def _gc(self) -> None:
        its = self._local_iterations()
        for it in its[: max(0, len(its) - self._n_retains)]:
            try:
                os.remove(self.filename(it))
            except OSError:
                pass  # already gone; never fail training over GC

    # -- load ------------------------------------------------------------ #

    def maybe_load(self, state: Any = None) -> tuple[Any, int]:
        """Resume from the newest iteration available on ALL ranks.

        Returns ``(loaded_state, iteration)``; when no common snapshot
        exists, returns ``(state, 0)`` unchanged (fresh start) — the
        reference's ``resume = checkpointer.maybe_load(trainer)`` contract.
        """
        local = set(self._local_iterations())
        all_sets = self._comm.allgather_obj(local)
        common = set.intersection(*map(set, all_sets)) if all_sets else set()
        if not common:
            return state, 0
        it = max(common)
        t0 = time.time()
        with open(self.filename(it), "rb") as f:
            payload = pickle.load(f)
        world_now = self._world_size()
        if payload["world_size"] != world_now:
            raise RuntimeError(
                f"snapshot '{self.name}' iteration {it} was taken with "
                f"{payload['world_size']} processes but this job has "
                f"{world_now}; per-rank snapshots require the same world size"
            )
        self.stats["load"].append(time.time() - t0)
        return payload["state"], it

    # -- misc ------------------------------------------------------------ #

    def get_stats(self) -> dict[str, float]:
        """Mean save/load seconds (reference exposes timing stats)."""
        return {
            k: (sum(v) / len(v) if v else 0.0) for k, v in self.stats.items()
        }

    def finalize(self) -> None:
        """Remove every snapshot this rank owns (reference ``finalize``)."""
        for it in self._local_iterations():
            try:
                os.remove(self.filename(it))
            except OSError:
                pass


def create_multi_node_checkpointer(
    name: str,
    comm: CommunicatorBase,
    path: Optional[str] = None,
    n_retains: int = 5,
    **kwargs,
) -> MultiNodeCheckpointer:
    """Reference ``create_multi_node_checkpointer(name, comm, ...)``."""
    return MultiNodeCheckpointer(name, comm, path, n_retains, **kwargs)
