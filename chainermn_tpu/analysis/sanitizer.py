"""Runtime concurrency sanitizer — graftlint's dynamic counterpart.

The static lock checkers (:mod:`.checkers.locks`) *infer* lock
discipline and the cross-class acquisition-order graph; nothing proves
those inferences against real executions. This module is the tsan-style
runtime layer that does:

- :func:`make_lock` / :func:`make_rlock` — sanitizer-aware lock
  constructors. Disabled (the default), they return plain
  ``threading.Lock()`` / ``RLock()``: zero runtime cost. Enabled (via
  :func:`enable`, the test fixtures, or the ``CHAINERMN_TPU_SANITIZER``
  env var), they return :class:`SanLock` / :class:`SanRLock`, which
  maintain a per-thread held-lock stack and record every *observed*
  lock-order edge ``held -> acquired`` into a process-global graph.
  A runtime cycle (the dynamic shadow of an ABBA deadlock) or — when a
  static graph is supplied — an observed edge the static ``lock-order``
  checker did not predict raises :class:`LockOrderViolation`
  immediately, *before* blocking on the inner lock, with both
  acquisition stacks.
- :func:`guarded` — an attribute proxy enforcing the
  ``lock-discipline`` invariant dynamically: mutating a guarded
  container without holding its owning lock raises
  :class:`GuardViolation`. Reads stay free (the GIL-atomic torn-read
  contract the static checker's ``unguarded-ok`` escapes document).
- :func:`mutation_guard` — for classes that are single-writer *by
  design* and own no lock (``BlockPool``, ``PrefixCacheIndex``): a
  context manager that raises when two threads are observed inside a
  mutator simultaneously.
- :func:`fuzz` — a seeded interleaving fuzzer: deterministic per-thread
  yields at sanitizer sync points (:func:`sync_point`, lock acquires,
  mutation-guard windows) widen race windows for targeted regression
  tests without wall-clock flakiness.
- :func:`dump_artifact` / ``--runtime-report`` — the observed graph is
  dumped as JSON by the suite fixtures and merged back into the static
  graph by ``python -m chainermn_tpu.analysis --runtime-report``, which
  asserts observed ⊆ static.

Import hygiene: this module is stdlib-only at module level (the
analyzer never imports what it analyzes — and serving/fleet/monitor
import *this*, so it must not pull jax/numpy/monitor back in). The
telemetry hooks (``lock_hold_seconds`` histogram, ``lock_contended``
event) import monitor lazily at call time, guarded against recursion —
instrument locks are themselves sanitized.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
import traceback
from typing import Iterable, Optional

ENV_FLAG = "CHAINERMN_TPU_SANITIZER"
ARTIFACT_ENV = "CHAINERMN_TPU_SANITIZER_ARTIFACT"


class LockOrderViolation(RuntimeError):
    """Observed lock acquisition that can deadlock (cycle) or that the
    static lock-order graph did not predict."""


class GuardViolation(RuntimeError):
    """Guarded state mutated without its lock / by a second thread."""


# --------------------------------------------------------------------- #
# global state                                                           #
# --------------------------------------------------------------------- #


class _State:
    def __init__(self) -> None:
        self.depth = 0                 # enable() nesting count
        self.telemetry = True
        self.static_edges: Optional[set] = None   # {(clsA, clsB)}
        self.graph_lock = threading.Lock()        # plain: internal only
        # (held_name, acquired_name) -> {"count", "stack", "leaf"}
        self.edges: dict = {}
        self.succ: dict = {}           # non-leaf adjacency for cycles
        self.hold: dict = {}           # name -> [count, total_s, max_s]
        self.contended: dict = {}      # name -> count
        self.hist_cache: dict = {}     # name -> monitor Histogram
        self.fuzz: Optional["_Fuzz"] = None


_S = _State()
_TLS = threading.local()


def _held() -> list:
    got = getattr(_TLS, "held", None)
    if got is None:
        got = _TLS.held = []
    return got


def enabled() -> bool:
    return _S.depth > 0


def enable(*, static_graph: Optional[Iterable] = None,
           telemetry: bool = True) -> None:
    """Turn the sanitizer on (nestable). ``static_graph`` is a set of
    ``(holder_class, acquired_class)`` pairs — when given, an observed
    non-leaf cross-class edge outside it raises immediately."""
    _S.depth += 1
    _S.telemetry = telemetry
    if static_graph is not None:
        _S.static_edges = {tuple(e) for e in static_graph}


def disable() -> None:
    if _S.depth > 0:
        _S.depth -= 1
    if _S.depth == 0:
        _S.fuzz = None


def reset() -> None:
    """Forget the observed graph, stats, and static graph (not the
    enable depth) — test isolation."""
    with _S.graph_lock:
        _S.edges.clear()
        _S.succ.clear()
        _S.hold.clear()
        _S.contended.clear()
        _S.hist_cache.clear()
    _S.static_edges = None


if os.environ.get(ENV_FLAG, "") not in ("", "0"):
    enable()


def _cls(name: str) -> str:
    return name.split(".", 1)[0]


def _stack(skip: int = 2) -> str:
    return "".join(traceback.format_stack(limit=24)[:-skip])


# --------------------------------------------------------------------- #
# telemetry (lazy monitor imports, recursion-guarded)                    #
# --------------------------------------------------------------------- #


def _record_hold(name: str, dt: float) -> None:
    with _S.graph_lock:
        slot = _S.hold.setdefault(name, [0, 0.0, 0.0])
        slot[0] += 1
        slot[1] += dt
        if dt > slot[2]:
            slot[2] = dt
    if not _S.telemetry or getattr(_TLS, "in_telemetry", False):
        return
    _TLS.in_telemetry = True
    try:
        # cache the instrument per lock name: the registry get-or-create
        # (its own lock + label-tuple build) is too hot for every release
        hist = _S.hist_cache.get(name)
        if hist is None:
            from chainermn_tpu.monitor._state import get_registry
            hist = get_registry().histogram(
                "lock_hold_seconds", {"lock": name}, unit="s")
            _S.hist_cache[name] = hist
        hist.observe(dt)
    except Exception:
        pass
    finally:
        _TLS.in_telemetry = False


def _record_contended(name: str, waited_s: float) -> None:
    with _S.graph_lock:
        _S.contended[name] = _S.contended.get(name, 0) + 1
    if not _S.telemetry or getattr(_TLS, "in_telemetry", False):
        return
    _TLS.in_telemetry = True
    try:
        from chainermn_tpu.monitor._state import get_event_log
        get_event_log().emit("lock_contended", lock=name,
                             waited_s=round(waited_s, 6))
    except Exception:
        pass
    finally:
        _TLS.in_telemetry = False


def hold_stats() -> dict:
    """name -> {count, total_s, max_s} for every sanitized lock."""
    with _S.graph_lock:
        return {name: {"count": c, "total_s": t, "max_s": m}
                for name, (c, t, m) in sorted(_S.hold.items())}


def contention_counts() -> dict:
    with _S.graph_lock:
        return dict(sorted(_S.contended.items()))


# --------------------------------------------------------------------- #
# the observed lock-order graph                                          #
# --------------------------------------------------------------------- #


def _reachable(src: str, dst: str) -> Optional[str]:
    """First hop of a path ``src ->* dst`` in the observed non-leaf
    graph (call with graph_lock held), or None."""
    stack_, seen = [(src, None)], set()
    while stack_:
        node, first = stack_.pop()
        if node == dst and first is not None:
            return first
        if node in seen:
            continue
        seen.add(node)
        for nxt in _S.succ.get(node, ()):
            stack_.append((nxt, first if first is not None else nxt))
    return None


def _note_edge(held_name: str, held_leaf: bool, acq_name: str,
               acq_leaf: bool) -> None:
    """Record (and police) the edge held -> acquired. Raises before the
    caller blocks on the inner lock, so a would-be deadlock surfaces as
    a stack-carrying exception instead of a hang."""
    if held_leaf:
        raise LockOrderViolation(
            f"acquiring {acq_name} while LEAF lock {held_name} is held — "
            f"leaf locks must be terminal (no nested acquisition)\n"
            f"--- acquisition stack ---\n{_stack(3)}")
    key = (held_name, acq_name)
    leaf_edge = acq_leaf
    with _S.graph_lock:
        known = _S.edges.get(key)
        if known is not None:
            known["count"] += 1
            return
        if not leaf_edge:
            hop = _reachable(acq_name, held_name)
            if hop is not None:
                other = _S.edges.get((acq_name, hop), {})
                raise LockOrderViolation(
                    f"lock-order cycle: acquiring {acq_name} while "
                    f"holding {held_name}, but {acq_name} -> "
                    f"{hop} ->* {held_name} was already observed "
                    f"(ABBA deadlock)\n"
                    f"--- this acquisition ({held_name} -> {acq_name}) "
                    f"---\n{_stack(3)}"
                    f"--- prior acquisition ({acq_name} -> {hop}) ---\n"
                    f"{other.get('stack') or '<no stack recorded>'}")
            a_cls, b_cls = _cls(held_name), _cls(acq_name)
            if (_S.static_edges is not None and a_cls != b_cls
                    and (a_cls, b_cls) not in _S.static_edges):
                raise LockOrderViolation(
                    f"observed lock-order edge {held_name} -> {acq_name} "
                    f"({a_cls} -> {b_cls}) is absent from the static "
                    f"lock-order graph — either a latent hazard or a "
                    f"receiver the static checker cannot type; extend "
                    f"the graph or restructure the call\n"
                    f"--- acquisition stack ---\n{_stack(3)}")
        _S.edges[key] = {"count": 1, "stack": _stack(3),
                         "leaf": leaf_edge}
        if not leaf_edge:
            _S.succ.setdefault(held_name, set()).add(acq_name)


def observed_edges(*, leaf: bool = True) -> dict:
    """(held, acquired) -> count. ``leaf=False`` drops edges into leaf
    locks (terminal by construction, excluded from the static check)."""
    with _S.graph_lock:
        return {k: v["count"] for k, v in _S.edges.items()
                if leaf or not v["leaf"]}


def observed_class_edges(*, leaf: bool = False) -> set:
    """Observed edges collapsed to ``(holder_class, acquired_class)``,
    self-edges dropped — the granularity of the static graph."""
    out = set()
    for (a, b) in observed_edges(leaf=leaf):
        ca, cb = _cls(a), _cls(b)
        if ca != cb:
            out.add((ca, cb))
    return out


# --------------------------------------------------------------------- #
# instrumented locks                                                     #
# --------------------------------------------------------------------- #


class _Held:
    __slots__ = ("lock", "name", "leaf", "depth", "t0")

    def __init__(self, lock, name, leaf, t0) -> None:
        self.lock, self.name, self.leaf = lock, name, leaf
        self.depth, self.t0 = 1, t0


class SanLock:
    """Instrumented non-reentrant lock. API-compatible with
    ``threading.Lock`` (acquire/release/locked/context manager)."""

    _reentrant = False

    def __init__(self, name: str, *, leaf: bool = False) -> None:
        self._name = name
        self._leaf = leaf
        self._inner = self._make_inner()

    @staticmethod
    def _make_inner():
        return threading.Lock()

    @property
    def name(self) -> str:
        return self._name

    def held_by_me(self) -> bool:
        return any(h.lock is self for h in _held())

    def locked(self) -> bool:
        return self._inner.locked()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # telemetry-context acquisitions (the registry lock taken while
        # recording another lock's hold time) are invisible: no edges,
        # no held-stack entry — release() tolerates the missing entry
        if not enabled() or getattr(_TLS, "in_telemetry", False):
            return self._inner.acquire(blocking, timeout)
        held = _held()
        for h in held:
            if h.lock is self:
                if self._reentrant:
                    got = self._inner.acquire(blocking, timeout)
                    if got:
                        h.depth += 1
                    return got
                raise LockOrderViolation(
                    f"{self._name}: non-reentrant lock re-acquired by "
                    f"the holding thread (self-deadlock; the outer "
                    f"acquisition is in this stack)\n"
                    f"--- acquisition stack ---\n{_stack()}")
        sync_point(f"lock:{self._name}")
        for h in held:
            if h.name != self._name:
                _note_edge(h.name, h.leaf, self._name, self._leaf)
        got = self._inner.acquire(False)
        if not got:
            if not blocking:
                return False
            t0 = time.perf_counter()
            got = self._inner.acquire(True, timeout)
            if not got:
                return False
            _record_contended(self._name, time.perf_counter() - t0)
        held.append(_Held(self, self._name, self._leaf,
                          time.perf_counter()))
        return True

    def release(self) -> None:
        held = _held()
        entry = None
        for h in reversed(held):
            if h.lock is self:
                entry = h
                break
        dt = None
        if entry is not None:
            entry.depth -= 1
            if entry.depth == 0:
                held.remove(entry)
                if enabled() and not self._leaf:
                    dt = time.perf_counter() - entry.t0
        # physical release FIRST: hold telemetry re-enters the registry,
        # and recording while still holding the registry's own lock
        # would self-deadlock
        self._inner.release()
        if dt is not None:
            _record_hold(self._name, dt)

    def __enter__(self) -> "SanLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        kind = "SanRLock" if self._reentrant else "SanLock"
        return f"<{kind} {self._name} leaf={self._leaf}>"


class SanRLock(SanLock):
    """Instrumented reentrant lock (``threading.RLock`` semantics)."""

    _reentrant = True

    @staticmethod
    def _make_inner():
        return threading.RLock()


def make_lock(name: str, *, leaf: bool = False):
    """A lock for ``name`` (``"OwnerClass._attr"``): plain
    ``threading.Lock`` when the sanitizer is off, :class:`SanLock` when
    on. ``leaf=True`` marks terminal locks (metric instruments) that
    must never be held across another acquisition."""
    if not enabled():
        return threading.Lock()
    return SanLock(name, leaf=leaf)


def make_rlock(name: str):
    if not enabled():
        return threading.RLock()
    return SanRLock(name)


# --------------------------------------------------------------------- #
# guarded state                                                          #
# --------------------------------------------------------------------- #

_MUTATORS = frozenset({
    "add", "append", "appendleft", "clear", "discard", "extend",
    "insert", "pop", "popitem", "popleft", "remove", "reverse",
    "rotate", "setdefault", "sort", "update",
})


class _GuardedProxy:
    """Container proxy: mutators demand the owning lock be held by the
    calling thread; reads pass through untouched."""

    __slots__ = ("_obj", "_lock", "_name")

    def __init__(self, obj, lock, name) -> None:
        object.__setattr__(self, "_obj", obj)
        object.__setattr__(self, "_lock", lock)
        object.__setattr__(self, "_name", name)

    def _check(self) -> None:
        if not enabled():
            return
        lock = self._lock
        if lock is None or not isinstance(lock, SanLock):
            return
        if lock.held_by_me():
            return
        raise GuardViolation(
            f"{self._name} mutated without holding {lock.name} — the "
            f"lock-discipline invariant, enforced at runtime\n"
            f"--- mutation stack ---\n{_stack()}")

    def __getattr__(self, attr):
        got = getattr(self._obj, attr)
        if attr in _MUTATORS:
            def checked(*a, _fn=got, **kw):
                self._check()
                sync_point(f"guarded:{self._name}")
                return _fn(*a, **kw)
            return checked
        return got

    def __setitem__(self, key, value) -> None:
        self._check()
        sync_point(f"guarded:{self._name}")
        self._obj[key] = value

    def __delitem__(self, key) -> None:
        self._check()
        del self._obj[key]

    def __getitem__(self, key):
        return self._obj[key]

    def __contains__(self, key) -> bool:
        return key in self._obj

    def __iter__(self):
        return iter(self._obj)

    def __len__(self) -> int:
        return len(self._obj)

    def __bool__(self) -> bool:
        return bool(self._obj)

    def __eq__(self, other) -> bool:
        return self._obj == other

    def __ne__(self, other) -> bool:
        return self._obj != other

    def __repr__(self) -> str:
        return f"<guarded {self._name} {self._obj!r}>"


def guarded(obj, *, lock=None, name: str):
    """Wrap a container so mutations require ``lock`` held by the
    calling thread. Off: returns ``obj`` unchanged (zero cost)."""
    if not enabled():
        return obj
    return _GuardedProxy(obj, lock, name)


class _NoopGuard:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        return None


_NOOP = _NoopGuard()


class MutationGuard:
    """Single-writer contract for lock-free classes (``BlockPool``):
    two threads observed inside a mutation window simultaneously raise
    :class:`GuardViolation`. The window doubles as a fuzzer sync point,
    so the interleaving fuzzer can stretch it deterministically."""

    __slots__ = ("_name", "_owner", "_depth")

    def __init__(self, name: str) -> None:
        self._name = name
        self._owner = None
        self._depth = 0

    def __enter__(self) -> "MutationGuard":
        if not enabled():
            return self
        me = threading.current_thread()
        cur = self._owner
        if cur is not None and cur is not me:
            raise GuardViolation(
                f"{self._name}: concurrent mutation — {me.name} entered "
                f"a mutator while {cur.name} is still inside one; this "
                f"class is single-writer by design (no lock)\n"
                f"--- second writer's stack ---\n{_stack()}")
        self._owner = me
        self._depth += 1
        sync_point(f"mutate:{self._name}")
        return self

    def __exit__(self, *exc) -> None:
        if self._depth > 0:
            self._depth -= 1
            if self._depth == 0:
                self._owner = None


def mutation_guard(name: str):
    """A :class:`MutationGuard` when the sanitizer is on, a shared
    no-op context manager when off."""
    if not enabled():
        return _NOOP
    return MutationGuard(name)


# --------------------------------------------------------------------- #
# seeded interleaving fuzzer                                             #
# --------------------------------------------------------------------- #


class _Fuzz:
    def __init__(self, seed, p, sleep_s, points) -> None:
        self.seed = seed
        self.p = p
        self.sleep_s = sleep_s
        self.points = tuple(points) if points else None
        self._tls = threading.local()

    def maybe_yield(self, tag: str) -> None:
        if self.points is not None \
                and not any(tag.startswith(p) for p in self.points):
            return
        rng = getattr(self._tls, "rng", None)
        if rng is None:
            ident = threading.current_thread().name
            rng = self._tls.rng = random.Random(f"{self.seed}:{ident}")
        if rng.random() < self.p:
            time.sleep(self.sleep_s)


class _FuzzCtx:
    def __init__(self, fz) -> None:
        self._fz = fz

    def __enter__(self):
        _S.fuzz = self._fz
        return self._fz

    def __exit__(self, *exc) -> None:
        _S.fuzz = None


def fuzz(seed, *, p: float = 0.5, sleep_s: float = 0.0005,
         points: Optional[Iterable] = None):
    """Context manager arming the interleaving fuzzer: at every sync
    point, each thread draws from its own ``Random(f"{seed}:{thread
    name}")`` stream and yields with probability ``p`` for ``sleep_s``
    — deterministic per thread regardless of scheduling. ``points``
    restricts to tags with the given prefixes (``"lock:"``,
    ``"guarded:"``, ``"mutate:"``, or explicit :func:`sync_point`
    tags)."""
    return _FuzzCtx(_Fuzz(seed, p, sleep_s, points))


def sync_point(tag: str) -> None:
    """A named interleaving point: no-op unless :func:`fuzz` is armed.
    Production call sites cost one global read when the sanitizer is
    enabled and nothing measurable when it is not."""
    fz = _S.fuzz
    if fz is not None:
        fz.maybe_yield(tag)


# --------------------------------------------------------------------- #
# artifacts (the --runtime-report input)                                 #
# --------------------------------------------------------------------- #


def dump_artifact(path: Optional[str] = None) -> Optional[str]:
    """Write (merge-union) the observed graph as JSON. Default path:
    ``$CHAINERMN_TPU_SANITIZER_ARTIFACT``; returns the path written, or
    None when no path is configured."""
    path = path or os.environ.get(ARTIFACT_ENV) or None
    if not path:
        return None
    with _S.graph_lock:
        leaf = sorted(k for k, v in _S.edges.items() if v["leaf"])
        nonleaf = sorted(k for k, v in _S.edges.items()
                         if not v["leaf"])
    try:
        with open(path, encoding="utf-8") as f:
            prior = json.load(f)
        nonleaf = sorted({tuple(e) for e in prior.get("edges", ())}
                         | set(nonleaf))
        leaf = sorted({tuple(e) for e in prior.get("leaf_edges", ())}
                      | set(leaf))
    except (OSError, ValueError):
        pass
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1,
                   "edges": [list(e) for e in nonleaf],
                   "leaf_edges": [list(e) for e in leaf]}, f, indent=2)
        f.write("\n")
    return path


def load_artifact(path: str) -> dict:
    """Read a :func:`dump_artifact` file → {"edges": [(a, b)...],
    "leaf_edges": [(a, b)...]} as tuples."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {"edges": [tuple(e) for e in data.get("edges", ())],
            "leaf_edges": [tuple(e) for e in data.get("leaf_edges", ())]}


def artifact_class_edges(artifact: dict) -> set:
    """Non-leaf artifact edges collapsed to class pairs (self-edges
    dropped) — comparable against the static graph."""
    out = set()
    for (a, b) in artifact["edges"]:
        ca, cb = _cls(a), _cls(b)
        if ca != cb:
            out.add((ca, cb))
    return out


__all__ = [
    "ARTIFACT_ENV",
    "ENV_FLAG",
    "GuardViolation",
    "LockOrderViolation",
    "MutationGuard",
    "SanLock",
    "SanRLock",
    "artifact_class_edges",
    "contention_counts",
    "disable",
    "dump_artifact",
    "enable",
    "enabled",
    "fuzz",
    "guarded",
    "hold_stats",
    "load_artifact",
    "make_lock",
    "make_rlock",
    "mutation_guard",
    "observed_class_edges",
    "observed_edges",
    "reset",
    "sync_point",
]
