"""graftlint's shared visitor framework.

One :class:`Project` holds every parsed module (path, dotted name, AST
with parent links, raw source lines); checkers are small classes with a
``rule`` id, a ``suppress_token`` (the escape-hatch comment), and a
``check(project)`` generator of :class:`Finding`. The driver
(:func:`run_analysis`) parses each file once, runs every checker, then
applies the two suppression layers:

- **inline escapes** — ``# graftlint: <token>`` on the finding's line (or
  the line directly above, for long statements) waives that one finding;
  tokens are per-rule (``unguarded-ok``, ``lock-order-ok``,
  ``hot-sync-ok``, ``recompile-ok``, ``import-ok``, ``name-ok``);
- **baseline file** — a JSON list of finding *fingerprints* (stable
  hashes of rule + path + symbol, independent of line numbers) accepted
  at some point in the past. The merged tree keeps an empty baseline; the
  mechanism exists so a future sweep that lands a new checker can ratchet
  instead of big-banging.

Everything here is stdlib-only (``ast``, ``json``, ``hashlib``) — the
analyzer never imports the code it analyzes, so it runs identically on a
jax-less host and inside the tier-1 suite.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Iterable, Iterator, Optional

_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*([\w,\- ]+)")

# comment marker that adds a function to the host-sync hot set without
# editing the checker's built-in list (also what fixtures use)
HOT_MARK = "hot"


@dataclass
class Finding:
    """One rule violation at ``path:line``.

    ``symbol`` is the stable anchor (``Class.attr@method``,
    ``module->forbidden`` ...) the fingerprint hashes — findings survive
    unrelated edits shifting line numbers. ``severity`` is ``"error"``
    (gates the exit code) or ``"warning"`` (reported, never gates).
    """

    rule: str
    path: str
    line: int
    message: str
    symbol: str = ""
    severity: str = "error"
    col: int = 0

    @property
    def fingerprint(self) -> str:
        basis = f"{self.rule}|{self.path}|{self.symbol or self.message}"
        return hashlib.sha1(basis.encode()).hexdigest()[:16]

    def render(self) -> str:
        sev = "" if self.severity == "error" else f" [{self.severity}]"
        return f"{self.path}:{self.line}: {self.rule}{sev}: {self.message}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "col": self.col, "severity": self.severity,
            "message": self.message, "symbol": self.symbol,
            "fingerprint": self.fingerprint,
        }


class Module:
    """One parsed source file: AST (with ``.graft_parent`` links), dotted
    module name, and raw lines (for escape-comment lookup)."""

    def __init__(self, abspath: str, relpath: str, modname: str,
                 source: str) -> None:
        self.abspath = abspath
        self.path = relpath
        self.modname = modname
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                child.graft_parent = parent  # type: ignore[attr-defined]

    def line_tokens(self, lineno: int) -> set:
        """graftlint escape tokens on ``lineno`` or the line above it."""
        out: set = set()
        for ln in (lineno, lineno - 1):
            if 1 <= ln <= len(self.lines):
                m = _SUPPRESS_RE.search(self.lines[ln - 1])
                if m:
                    out.update(t.strip() for t in m.group(1).split(",")
                               if t.strip())
        return out


class Project:
    """Every module under the analyzed roots, plus the repo root (the
    directory holding the top-level package) so checkers can reach
    sibling surfaces: ``tests/`` for the referenced-by-a-test rule,
    ``README.md`` for doc drift."""

    def __init__(self, modules: list, root: Optional[str] = None) -> None:
        self.modules = modules
        self.root = root
        self._by_name = {m.modname: m for m in modules}

    def module(self, modname: str) -> Optional[Module]:
        return self._by_name.get(modname)

    def modules_under(self, prefix: str) -> list:
        return [m for m in self.modules
                if m.modname == prefix
                or m.modname.startswith(prefix + ".")]

    def read_root_file(self, *relparts: str) -> Optional[str]:
        if self.root is None:
            return None
        p = os.path.join(self.root, *relparts)
        try:
            with open(p, encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None

    def root_files(self, reldir: str, suffix: str = ".py") -> list:
        """(relpath, text) pairs under ``root/reldir`` — the tests scan."""
        if self.root is None:
            return []
        base = os.path.join(self.root, reldir)
        out = []
        for dirpath, _dirnames, filenames in os.walk(base):
            for fn in sorted(filenames):
                if not fn.endswith(suffix):
                    continue
                p = os.path.join(dirpath, fn)
                try:
                    with open(p, encoding="utf-8") as f:
                        out.append((os.path.relpath(p, self.root),
                                    f.read()))
                except OSError:
                    continue
        return out


class Checker:
    """Base class: subclasses set ``rule``/``suppress_token`` and
    implement ``check(project) -> iterator of Finding``."""

    rule = "base"
    suppress_token = "ok"

    def check(self, project: Project) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, module: Module, node, message: str, symbol: str = "",
                severity: str = "error") -> Finding:
        return Finding(rule=self.rule, path=module.path,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0),
                       message=message, symbol=symbol, severity=severity)


@dataclass
class AnalysisResult:
    findings: list = field(default_factory=list)      # active (not waived)
    suppressed: list = field(default_factory=list)    # inline-escaped
    baselined: list = field(default_factory=list)     # in the baseline file
    parse_errors: list = field(default_factory=list)  # Finding objects

    @property
    def errors(self) -> list:
        return [f for f in self.findings if f.severity == "error"]

    @property
    def warnings(self) -> list:
        return [f for f in self.findings if f.severity == "warning"]

    def counts_by_rule(self) -> dict:
        out: dict = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_json(self) -> dict:
        return {
            "findings": [f.to_json() for f in self.findings],
            "counts": {
                "active": len(self.findings),
                "errors": len(self.errors),
                "warnings": len(self.warnings),
                "suppressed": len(self.suppressed),
                "baselined": len(self.baselined),
                "parse_errors": len(self.parse_errors),
                "by_rule": self.counts_by_rule(),
            },
        }


def _iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__"
                                     and not d.startswith("."))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def _split_root(abspath: str) -> tuple:
    """(repo_root, relpath, modname) for one file, anchored at the
    outermost directory that is a package (has ``__init__.py``) — for
    this tree that is ``chainermn_tpu``, making ``root`` the repo dir."""
    d = os.path.dirname(abspath)
    pkg_dirs = []
    while os.path.isfile(os.path.join(d, "__init__.py")):
        pkg_dirs.append(os.path.basename(d))
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    root = d
    relpath = os.path.relpath(abspath, root)
    parts = list(reversed(pkg_dirs))
    base = os.path.splitext(os.path.basename(abspath))[0]
    if base != "__init__":
        parts.append(base)
    modname = ".".join(parts) if parts else base
    return root, relpath, modname


def load_project(paths: Iterable[str]) -> tuple:
    """Parse every ``.py`` under ``paths`` → (Project, parse_error
    Findings)."""
    modules: list = []
    errors: list = []
    root: Optional[str] = None
    for abspath in _iter_py_files(paths):
        abspath = os.path.abspath(abspath)
        file_root, relpath, modname = _split_root(abspath)
        if root is None:
            root = file_root
        try:
            with open(abspath, encoding="utf-8") as f:
                source = f.read()
            modules.append(Module(abspath, relpath, modname, source))
        except (OSError, SyntaxError, ValueError) as e:
            errors.append(Finding(
                rule="parse-error", path=relpath, line=1,
                message=f"{type(e).__name__}: {e}", symbol=relpath))
    return Project(modules, root=root), errors


def load_baseline(path: Optional[str]) -> set:
    if not path or not os.path.isfile(path):
        return set()
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict):
        data = data.get("fingerprints", [])
    return set(data)


def write_baseline(path: str, result: AnalysisResult) -> None:
    fps = sorted({f.fingerprint for f in result.findings}
                 | {f.fingerprint for f in result.baselined})
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"fingerprints": fps}, f, indent=2)
        f.write("\n")


def run_analysis(paths: Iterable[str], checkers: Iterable[Checker],
                 baseline: Optional[set] = None) -> AnalysisResult:
    """Parse, run every checker, apply inline escapes + baseline."""
    project, parse_errors = load_project(paths)
    return run_on_project(project, checkers, baseline=baseline,
                          parse_errors=parse_errors)


def run_on_project(project: Project, checkers: Iterable[Checker],
                   baseline: Optional[set] = None,
                   parse_errors: Optional[list] = None) -> AnalysisResult:
    baseline = baseline or set()
    result = AnalysisResult(parse_errors=list(parse_errors or []))
    by_path = {m.path: m for m in project.modules}
    for checker in checkers:
        for f in checker.check(project):
            mod = by_path.get(f.path)
            tokens = mod.line_tokens(f.line) if mod is not None else set()
            if checker.suppress_token in tokens or "all-ok" in tokens:
                result.suppressed.append(f)
            elif f.fingerprint in baseline:
                result.baselined.append(f)
            else:
                result.findings.append(f)
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    # parse errors always gate — a file the analyzer cannot read is a
    # file whose invariants nobody is checking
    result.findings.extend(result.parse_errors)
    return result


def analyze_source(source: str, checker: Checker, *,
                   path: str = "snippet.py",
                   modname: str = "snippet",
                   extra_modules: Optional[dict] = None,
                   root: Optional[str] = None) -> list:
    """Fixture-test entry point: run ONE checker over literal source
    (plus optional ``{modname: source}`` companions), inline escapes
    applied, no baseline. Returns the active findings."""
    modules = [Module(path, path, modname, source)]
    for name, src in (extra_modules or {}).items():
        modules.append(Module(name, name.replace(".", "/") + ".py",
                              name, src))
    project = Project(modules, root=root)
    return run_on_project(project, [checker]).findings


__all__ = [
    "AnalysisResult",
    "Checker",
    "Finding",
    "HOT_MARK",
    "Module",
    "Project",
    "analyze_source",
    "load_baseline",
    "load_project",
    "run_analysis",
    "run_on_project",
    "write_baseline",
]
