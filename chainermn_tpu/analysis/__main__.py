"""``python -m chainermn_tpu.analysis`` — run graftlint from the shell.

Exit codes: 0 = no gating findings, 1 = errors (or parse failures),
2 = usage error. ``--baseline`` accepts previously recorded
fingerprints; ``--write-baseline`` records the current findings so a
new checker can ratchet instead of big-banging (the merged tree keeps
the baseline empty).
"""

from __future__ import annotations

import argparse
import json
import sys

from chainermn_tpu.analysis.checkers import all_checkers
from chainermn_tpu.analysis.core import (
    load_baseline,
    load_project,
    run_analysis,
    write_baseline,
)


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m chainermn_tpu.analysis",
        description="graftlint: AST-based repo-invariant analysis")
    p.add_argument("paths", nargs="+",
                   help="files or directories to analyze")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable findings on stdout")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="JSON fingerprint file of accepted findings")
    p.add_argument("--write-baseline", default=None, metavar="FILE",
                   help="record current findings as the new baseline")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print available rule ids and exit")
    p.add_argument("--runtime-report", default=None, metavar="FILE",
                   help="sanitizer artifact (JSON) to merge with the "
                        "static lock-order graph; exits 1 on observed "
                        "edges absent from the static graph")
    return p


def _runtime_report(artifact_path: str, paths: list) -> int:
    """Merge the sanitizer's observed lock-order graph into the static
    one and assert observed ⊆ static (leaf-lock edges are terminal
    telemetry edges, reported but never gating)."""
    from chainermn_tpu.analysis.checkers.locks import static_lock_graph
    from chainermn_tpu.analysis.sanitizer import (
        artifact_class_edges,
        load_artifact,
    )

    artifact = load_artifact(artifact_path)
    observed = artifact_class_edges(artifact)
    project, parse_errors = load_project(paths)
    if parse_errors:
        for f in parse_errors:
            print(f.render())
        return 1
    static = static_lock_graph(project)

    both = sorted(observed & static)
    static_only = sorted(static - observed)
    observed_only = sorted(observed - static)
    leaf = sorted(tuple(e) for e in artifact.get("leaf_edges", ()))

    print("runtime lock-order report "
          f"({len(observed)} observed / {len(static)} static class edges)")
    for a, b in both:
        print(f"  both      {a} -> {b}")
    for a, b in static_only:
        print(f"  static    {a} -> {b}  (not exercised at runtime)")
    for a, b in leaf:
        print(f"  leaf      {a} -> {b}  (terminal telemetry lock)")
    for a, b in observed_only:
        print(f"  OBSERVED-ONLY  {a} -> {b}  — runtime took a lock "
              f"ordering the static graph does not know about")
    if observed_only:
        print("runtime-report: FAIL (observed graph is not a subgraph "
              "of the static graph)")
        return 1
    print("runtime-report: OK (observed ⊆ static)")
    return 0


def main(argv=None) -> int:
    parser = _build_parser()
    args = parser.parse_args(argv)

    checkers = all_checkers()
    if args.list_rules:
        for c in checkers:
            print(f"{c.rule}  (suppress: # graftlint: {c.suppress_token})")
        return 0
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = wanted - {c.rule for c in checkers}
        if unknown:
            parser.error(f"unknown rule(s): {', '.join(sorted(unknown))}")
        checkers = [c for c in checkers if c.rule in wanted]

    if args.runtime_report:
        return _runtime_report(args.runtime_report, args.paths)

    baseline = load_baseline(args.baseline)
    result = run_analysis(args.paths, checkers, baseline=baseline)

    if args.write_baseline:
        write_baseline(args.write_baseline, result)

    if args.as_json:
        print(json.dumps(result.to_json(), indent=2))
    else:
        for f in result.findings:
            print(f.render())
        counts = result.to_json()["counts"]
        print(f"graftlint: {counts['errors']} error(s), "
              f"{counts['warnings']} warning(s), "
              f"{counts['suppressed']} suppressed, "
              f"{counts['baselined']} baselined")
    return 1 if result.errors else 0


if __name__ == "__main__":
    sys.exit(main())
