"""Shared AST machinery for the graftlint checkers.

The two lock checkers (and, to a lesser degree, the hot-path checker)
need the same structural facts about a class: which attributes hold
``threading`` primitives, which statements execute under ``with
self._lock``, which methods acquire the lock (directly or through
intra-class calls), and — for the cross-class acquisition-order graph —
what *type* an expression like ``self._events`` or ``self.replicas[i]``
evaluates to. This module computes those facts once per class into a
:class:`ClassModel`; inference is deliberately under-approximate (an
expression whose type cannot be pinned creates no edge and no finding)
because a linter that cries wolf gets deleted.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

# make_lock / make_rlock are analysis.sanitizer's instrumented
# constructors — production code swapping threading.Lock() for them must
# keep full static lock coverage, so they count as lock factories here
LOCK_FACTORIES = {"Lock", "RLock", "Condition", "make_lock", "make_rlock"}
EVENT_FACTORIES = {"Event", "Semaphore", "BoundedSemaphore", "Barrier"}

# compiled-program attribute naming convention (ServingEngine._decode_fn,
# _prefill_fns, _insert_fn, ...): results of calling these are device
# values until fetched
COMPILED_ATTR_RE = re.compile(r"^_\w*fns?$")


def call_name(node: ast.AST) -> str:
    """Dotted name of a call target / attribute chain, '' if dynamic."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def is_self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` → ``"X"``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _threading_factory(call: ast.AST, names: set) -> bool:
    if not isinstance(call, ast.Call):
        return False
    dotted = call_name(call.func)
    if not dotted:
        return False
    leaf = dotted.rsplit(".", 1)[-1]
    return leaf in names


class ClassModel:
    """Structural facts about one class definition."""

    def __init__(self, module, node: ast.ClassDef) -> None:
        self.module = module
        self.node = node
        self.name = node.name
        self.methods: dict = {}
        self.properties: set = set()
        self.lock_attrs: set = set()
        self.event_attrs: set = set()
        self.reentrant: set = set()   # lock attrs built with RLock()
        self.attr_types: dict = {}    # attr -> (classname, is_list)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
                for dec in item.decorator_list:
                    if (isinstance(dec, ast.Name)
                            and dec.id in ("property", "cached_property")):
                        self.properties.add(item.name)
        for meth in self.methods.values():
            for sub in ast.walk(meth):
                if not isinstance(sub, ast.Assign):
                    continue
                for tgt in sub.targets:
                    attr = is_self_attr(tgt)
                    if attr is None:
                        continue
                    if _threading_factory(sub.value, LOCK_FACTORIES):
                        self.lock_attrs.add(attr)
                        factory = call_name(sub.value.func)
                        if factory.endswith("RLock") \
                                or factory.endswith("make_rlock"):
                            self.reentrant.add(attr)
                    elif _threading_factory(sub.value, EVENT_FACTORIES):
                        self.event_attrs.add(attr)
        self._locking_methods: Optional[set] = None

    # -- lock scope ------------------------------------------------------ #

    def is_own_lock_expr(self, expr: ast.AST) -> bool:
        attr = is_self_attr(expr)
        return attr is not None and attr in self.lock_attrs

    def under_own_lock(self, node: ast.AST) -> bool:
        """True when ``node`` sits inside ``with self.<lock>:`` (any of
        the class's locks), following parent links up to the method."""
        cur = getattr(node, "graft_parent", None)
        while cur is not None and not isinstance(cur, ast.ClassDef):
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    if self.is_own_lock_expr(item.context_expr):
                        return True
            cur = getattr(cur, "graft_parent", None)
        return False

    def method_locks_directly(self, meth: ast.AST) -> bool:
        for sub in ast.walk(meth):
            if isinstance(sub, (ast.With, ast.AsyncWith)):
                for item in sub.items:
                    if self.is_own_lock_expr(item.context_expr):
                        return True
            # explicit self._lock.acquire() counts too
            if isinstance(sub, ast.Call):
                func = sub.func
                if (isinstance(func, ast.Attribute)
                        and func.attr == "acquire"
                        and self.is_own_lock_expr(func.value)):
                    return True
        return False

    @property
    def locking_methods(self) -> set:
        """Methods that acquire an own lock — directly, or transitively
        through an intra-class ``self._m()`` call chain."""
        if self._locking_methods is not None:
            return self._locking_methods
        locking = {name for name, meth in self.methods.items()
                   if self.method_locks_directly(meth)}
        changed = True
        while changed:
            changed = False
            for name, meth in self.methods.items():
                if name in locking:
                    continue
                for sub in ast.walk(meth):
                    if not isinstance(sub, ast.Call):
                        continue
                    callee = is_self_attr(sub.func)
                    if callee in locking:
                        locking.add(name)
                        changed = True
                        break
        self._locking_methods = locking
        return locking

    @property
    def locking_properties(self) -> set:
        return {p for p in self.properties if p in self.locking_methods}


def iter_classes(module) -> list:
    """Top-level :class:`ClassModel` list for one module."""
    return [ClassModel(module, node) for node in module.tree.body
            if isinstance(node, ast.ClassDef)]


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    cur = getattr(node, "graft_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = getattr(cur, "graft_parent", None)
    return None


def enclosing_class(node: ast.AST) -> Optional[ast.ClassDef]:
    cur = getattr(node, "graft_parent", None)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur
        cur = getattr(cur, "graft_parent", None)
    return None


def func_qualname(func: ast.AST) -> str:
    cls = enclosing_class(func)
    return f"{cls.name}.{func.name}" if cls is not None else func.name


# ---------------------------------------------------------------------- #
# project-wide type inference (the lock-order graph's legs)               #
# ---------------------------------------------------------------------- #


class TypeWorld:
    """Name → class resolution across the project.

    Three layers, each deliberately shallow:

    - every top-level class in every analyzed module, by simple name;
    - *factory* functions — module-level defs whose return expression is
      ``KnownClass(...)`` or a module global assigned ``KnownClass(...)``
      (this resolves ``get_event_log()`` → ``EventLog`` without
      importing anything);
    - per-class attribute types from ``__init__`` assignment shapes:
      ``self.x = C(...)``, ``self.x = factory()``, ``self.x = a or
      C(...)``, and ``self.x = [C(...) ...]`` (list / comprehension →
      element type).
    """

    def __init__(self, class_models: list) -> None:
        self.classes: dict = {}
        for cm in class_models:
            self.classes.setdefault(cm.name, cm)
        self.factories: dict = {}

    def learn_factories(self, module) -> None:
        globals_types: dict = {}
        for node in module.tree.body:
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                cls = self._class_of_call(node.value)
                if cls is not None:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            globals_types[tgt.id] = cls
        for node in module.tree.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Return) or sub.value is None:
                    continue
                cls = self._class_of_call(sub.value)
                if cls is None and isinstance(sub.value, ast.Name):
                    cls = globals_types.get(sub.value.id)
                if cls is not None:
                    self.factories[node.name] = cls
                    break

    def _class_of_call(self, expr: ast.AST) -> Optional[str]:
        if not isinstance(expr, ast.Call):
            return None
        dotted = call_name(expr.func)
        leaf = dotted.rsplit(".", 1)[-1] if dotted else ""
        if leaf in self.classes:
            return leaf
        if leaf in self.factories:
            return self.factories[leaf]
        return None

    def infer_value(self, expr: ast.AST) -> Optional[tuple]:
        """``(classname, is_list)`` for an rvalue expression, or None."""
        cls = self._class_of_call(expr)
        if cls is not None:
            return (cls, False)
        if isinstance(expr, ast.BoolOp):
            for v in expr.values:
                got = self.infer_value(v)
                if got is not None:
                    return got
        if isinstance(expr, ast.ListComp):
            got = self._class_of_call(expr.elt)
            if got is not None:
                return (got, True)
        if isinstance(expr, ast.List) and expr.elts:
            got = self._class_of_call(expr.elts[0])
            if got is not None:
                return (got, True)
        return None

    def learn_attr_types(self, cm: ClassModel) -> None:
        init = cm.methods.get("__init__")
        if init is None:
            return
        for sub in ast.walk(init):
            if not isinstance(sub, ast.Assign):
                continue
            for tgt in sub.targets:
                attr = is_self_attr(tgt)
                if attr is None or attr in cm.attr_types:
                    continue
                got = self.infer_value(sub.value)
                if got is not None:
                    cm.attr_types[attr] = got

    # -- expression typing inside one method ----------------------------- #

    def local_types(self, cm: ClassModel, meth: ast.AST) -> dict:
        """name → (classname, is_list) for simple local bindings."""
        out: dict = {}
        for sub in ast.walk(meth):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name):
                got = (self.infer_value(sub.value)
                       or self._type_of_ref(cm, out, sub.value))
                if got is not None:
                    out[sub.targets[0].id] = got
            elif isinstance(sub, ast.For) and isinstance(sub.target,
                                                         ast.Name):
                got = self._type_of_ref(cm, out, sub.iter)
                if got is not None and got[1]:
                    out[sub.target.id] = (got[0], False)
        return out

    def _type_of_ref(self, cm: ClassModel, locals_: dict,
                     expr: ast.AST) -> Optional[tuple]:
        attr = is_self_attr(expr)
        if attr is not None:
            return cm.attr_types.get(attr)
        if isinstance(expr, ast.Name):
            return locals_.get(expr.id)
        if isinstance(expr, ast.Subscript):
            base = self._type_of_ref(cm, locals_, expr.value)
            if base is not None and base[1]:
                return (base[0], False)
        return None

    def receiver_class(self, cm: ClassModel, locals_: dict,
                       expr: ast.AST) -> Optional[str]:
        """Class of the receiver in ``receiver.method(...)``."""
        got = self._type_of_ref(cm, locals_, expr)
        if got is not None and not got[1]:
            return got[0]
        # direct factory call receiver: get_event_log().emit(...)
        cls = self._class_of_call(expr)
        if cls is not None:
            return cls
        return None


__all__ = [
    "COMPILED_ATTR_RE",
    "ClassModel",
    "TypeWorld",
    "call_name",
    "enclosing_class",
    "enclosing_function",
    "func_qualname",
    "is_self_attr",
    "iter_classes",
]
