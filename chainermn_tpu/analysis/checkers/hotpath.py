"""Host-sync-in-hot-path checker.

PERF.md's dispatch-bound regime means every host synchronization inside
the per-token / per-step loops — ``jax.device_get``,
``.block_until_ready()``, ``.item()``, ``float()/int()/np.asarray`` on a
device value — is a measurable TPOT/step-time hit. This checker taints
names assigned from calls of compiled-program attributes (the repo-wide
``self._*fn`` / ``self._*fns[...]`` convention for jitted programs) and
flags sync operations on tainted values inside the *hot set*:

- built-in hot bodies: ``ServingEngine.decode_step`` /
  ``decode_steps`` / ``spec_decode_step`` / ``admit_batch``,
  ``EngineReplica._loop``, ``ResilientTrainer.fit``;
- any function whose ``def`` line carries ``# graftlint: hot``.

The sanctioned route is ``chainermn_tpu.dataflow.device_fetch`` — it
has one documented sync point, counts ``loss_fetch_total``, and its
results are clean (assigning from it untaints). Escape hatch:
``# graftlint: hot-sync-ok`` for syncs that are the *point* of the line
(e.g. a deliberate flush before a timing fence).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from chainermn_tpu.analysis import astutil
from chainermn_tpu.analysis.core import HOT_MARK, Checker, Finding, Project

# (path suffix, qualname) pairs always treated as hot-loop bodies
HOT_FUNCTIONS = (
    ("serving/engine.py", "ServingEngine.decode_step"),
    # the multi-token rounds: the fori_loop window and the speculative
    # draft+verify round are dispatched once per WINDOW, but a stray sync
    # there still serializes every round — same rule as decode_step
    ("serving/engine.py", "ServingEngine.decode_steps"),
    ("serving/engine.py", "ServingEngine.spec_decode_step"),
    ("serving/engine.py", "ServingEngine.admit_batch"),
    ("fleet/replica.py", "EngineReplica._loop"),
    ("resilience/trainer.py", "ResilientTrainer.fit"),
    # the paged-decode read side: traced per decode step on every paged
    # path (kernel AND XLA fallback) — a host sync here would serialize
    # each token of every slot
    ("parallel/sequence.py", "paged_update_cache_and_attend"),
    ("parallel/paged_kernel.py", "paged_attend"),
)

# syncs that exist only to block on the device: flagged on any argument
ALWAYS_SYNC = {"jax.device_get", "jax.block_until_ready"}
# host coercions: flagged only when the argument is a tainted device value
COERCIONS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
             "float", "int", "bool"}
SYNC_METHODS = {"block_until_ready", "item"}

FETCH_NAMES = {"device_fetch", "dataflow.device_fetch"}


def _is_hot(module, func: ast.AST) -> bool:
    qual = astutil.func_qualname(func)
    for suffix, hot_qual in HOT_FUNCTIONS:
        if qual == hot_qual and module.path.endswith(suffix):
            return True
    return HOT_MARK in module.line_tokens(func.lineno)


class HostSyncChecker(Checker):
    rule = "host-sync"
    suppress_token = "hot-sync-ok"

    def check(self, project: Project) -> Iterator[Finding]:
        for module in project.modules:
            for node in ast.walk(module.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and _is_hot(module, node):
                    yield from self._check_hot(module, node)

    # -- one hot body ---------------------------------------------------- #

    def _check_hot(self, module, func: ast.AST) -> Iterator[Finding]:
        qual = astutil.func_qualname(func)
        tainted: set = set()
        yield from self._walk_stmts(module, qual, func.body, tainted)

    def _walk_stmts(self, module, qual, stmts, tainted
                    ) -> Iterator[Finding]:
        for stmt in stmts:
            # check uses against the taint state *before* this statement's
            # bindings take effect (x = np.asarray(x) must flag)
            yield from self._check_exprs(module, qual, stmt, tainted)
            self._apply_bindings(stmt, tainted)
            for body in self._nested_bodies(stmt):
                yield from self._walk_stmts(module, qual, body, tainted)

    @staticmethod
    def _nested_bodies(stmt) -> list:
        out = []
        for attr in ("body", "orelse", "finalbody"):
            blk = getattr(stmt, attr, None)
            if blk and isinstance(blk[0], ast.stmt):
                out.append(blk)
        for handler in getattr(stmt, "handlers", []) or []:
            out.append(handler.body)
        return out

    # -- taint ----------------------------------------------------------- #

    def _value_tainted(self, expr, tainted) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        if isinstance(expr, (ast.Subscript, ast.Attribute, ast.Starred)):
            return self._value_tainted(expr.value, tainted)
        if isinstance(expr, ast.Call):
            return self._is_compiled_call(expr)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self._value_tainted(e, tainted) for e in expr.elts)
        return False

    @staticmethod
    def _is_compiled_call(call: ast.Call) -> bool:
        func = call.func
        if isinstance(func, ast.Subscript):
            func = func.value
        attr = astutil.is_self_attr(func)
        return attr is not None and astutil.COMPILED_ATTR_RE.match(attr) \
            is not None

    def _apply_bindings(self, stmt, tainted) -> None:
        if not isinstance(stmt, ast.Assign):
            return
        value = stmt.value
        is_fetch = (isinstance(value, ast.Call)
                    and astutil.call_name(value.func) in FETCH_NAMES)
        taints = (not is_fetch) and self._value_tainted(value, tainted)
        for tgt in stmt.targets:
            names = [n.id for n in ast.walk(tgt) if isinstance(n, ast.Name)]
            for n in names:
                if taints:
                    tainted.add(n)
                else:
                    tainted.discard(n)

    # -- sync detection --------------------------------------------------- #

    def _check_exprs(self, module, qual, stmt, tainted
                     ) -> Iterator[Finding]:
        nested = set()
        for body in self._nested_bodies(stmt):
            for s in body:
                nested.update(id(n) for n in ast.walk(s))
        for sub in ast.walk(stmt):
            if id(sub) in nested or not isinstance(sub, ast.Call):
                continue
            found = self._sync_call(module, qual, sub, tainted)
            if found is not None:
                yield found

    def _sync_call(self, module, qual, call: ast.Call, tainted
                   ) -> Optional[Finding]:
        dotted = astutil.call_name(call.func)
        if dotted in FETCH_NAMES:
            return None
        if dotted in ALWAYS_SYNC:
            return self.finding(
                module, call,
                f"{dotted}() inside hot body {qual} — route through "
                f"dataflow.device_fetch (one counted sync point)",
                symbol=f"{qual}:{dotted}")
        if dotted in COERCIONS and call.args \
                and self._value_tainted(call.args[0], tainted):
            return self.finding(
                module, call,
                f"{dotted}() on a compiled-program result inside hot "
                f"body {qual} forces a host sync — use "
                f"dataflow.device_fetch",
                symbol=f"{qual}:{dotted}")
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in SYNC_METHODS \
                and self._value_tainted(call.func.value, tainted):
            return self.finding(
                module, call,
                f".{call.func.attr}() on a compiled-program result inside "
                f"hot body {qual} forces a host sync — use "
                f"dataflow.device_fetch",
                symbol=f"{qual}:.{call.func.attr}")
        return None


__all__ = ["HOT_FUNCTIONS", "HostSyncChecker"]
