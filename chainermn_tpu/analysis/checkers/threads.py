"""Thread-lifecycle checker.

Every ``threading.Thread`` the system spawns must have a defined end of
life: either it is a daemon (the interpreter may exit under it — only
acceptable for pure-observer loops) or some lifecycle method joins it.
A non-daemon thread that nobody joins turns ``close()`` into a hang and
test teardown into a leak; a *daemon* thread that touches shared state
during interpreter shutdown dies mid-mutation.

A ``Thread(...)`` construction site is compliant when any of:

- the constructor call carries ``daemon=True``;
- the bound name (``self._thread`` / local ``t``) gets a
  ``.daemon = True`` assignment before ``.start()``;
- the bound name is ``.join()``-ed somewhere in the same class (or
  module, for module-level threads) inside a *lifecycle-named*
  function — one matching ``stop/close/shutdown/join/exit/terminate/
  finish/drain/__del__/__exit__`` — so the teardown path provably
  reaps it.

An unbound ``Thread(...).start()`` can never be joined and is always
flagged. Escape hatch: ``# graftlint: thread-ok`` with a comment
explaining who reaps the thread.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from chainermn_tpu.analysis import astutil
from chainermn_tpu.analysis.core import Checker, Finding, Project

LIFECYCLE_RE = re.compile(
    r"(stop|close|shutdown|join|exit|terminate|finish|drain|"
    r"__del__|__exit__)", re.IGNORECASE)


def _is_thread_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    dotted = astutil.call_name(node.func)
    return bool(dotted) and dotted.rsplit(".", 1)[-1] == "Thread"


def _daemon_kw(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is True:
            return True
    return False


def _bound_name(call: ast.Call) -> Optional[str]:
    """``self._t = Thread(...)`` → ``"._t"``; ``t = Thread(...)`` →
    ``"t"``; unbound → None."""
    parent = getattr(call, "graft_parent", None)
    if isinstance(parent, ast.Assign):
        for tgt in parent.targets:
            attr = astutil.is_self_attr(tgt)
            if attr is not None:
                return f".{attr}"
            if isinstance(tgt, ast.Name):
                return tgt.id
    return None


def _name_matches(expr: ast.AST, bound: str) -> bool:
    if bound.startswith("."):
        return astutil.is_self_attr(expr) == bound[1:]
    return isinstance(expr, ast.Name) and expr.id == bound


class ThreadLifecycleChecker(Checker):
    rule = "thread-lifecycle"
    suppress_token = "thread-ok"

    def check(self, project: Project) -> Iterator[Finding]:
        for module in project.modules:
            for call in ast.walk(module.tree):
                if not _is_thread_call(call):
                    continue
                finding = self._check_site(module, call)
                if finding is not None:
                    yield finding

    def _check_site(self, module, call: ast.Call) -> Optional[Finding]:
        if _daemon_kw(call):
            return None
        bound = _bound_name(call)
        func = astutil.enclosing_function(call)
        where = astutil.func_qualname(func) if func is not None \
            else module.modname
        if bound is None:
            return self.finding(
                module, call,
                f"unbound Thread(...) in {where} — it can never be "
                f"joined; bind it and reap it on the stop/close path, "
                f"or pass daemon=True",
                symbol=f"{where}:Thread")
        # scope to search for .daemon = True and lifecycle joins: the
        # enclosing class for self-attrs, else the whole module
        scope: ast.AST = module.tree
        if bound.startswith("."):
            cls = astutil.enclosing_class(call)
            if cls is not None:
                scope = cls
        if self._daemon_assigned(scope, bound):
            return None
        if self._joined_in_lifecycle(scope, bound):
            return None
        return self.finding(
            module, call,
            f"Thread bound to {bound} in {where} is neither daemon nor "
            f"joined on a lifecycle path (stop/close/shutdown/...) — "
            f"teardown will leak or hang on it",
            symbol=f"{where}:Thread:{bound}")

    @staticmethod
    def _daemon_assigned(scope: ast.AST, bound: str) -> bool:
        for sub in ast.walk(scope):
            if not isinstance(sub, ast.Assign):
                continue
            if not (isinstance(sub.value, ast.Constant)
                    and sub.value.value is True):
                continue
            for tgt in sub.targets:
                if isinstance(tgt, ast.Attribute) and tgt.attr == "daemon" \
                        and _name_matches(tgt.value, bound):
                    return True
        return False

    @staticmethod
    def _joined_in_lifecycle(scope: ast.AST, bound: str) -> bool:
        for sub in ast.walk(scope):
            if not isinstance(sub, ast.Call):
                continue
            func = sub.func
            if not (isinstance(func, ast.Attribute)
                    and func.attr == "join"
                    and _name_matches(func.value, bound)):
                continue
            enc = astutil.enclosing_function(sub)
            if enc is not None and LIFECYCLE_RE.search(enc.name):
                return True
        return False


__all__ = ["LIFECYCLE_RE", "ThreadLifecycleChecker"]
