"""Recompile-hazard checker — the static complement to RecompileGuard.

``RecompileGuard`` counts recompiles at runtime; this checker flags the
call-site *shapes* that cause them, before any trace runs:

- **jit-in-loop** — ``jax.jit(...)`` evaluated inside a ``for``/``while``
  body or a hot-loop function: every evaluation makes a fresh callable
  with an empty cache. Cache-guarded one-time builds (``if fn is None:``
  at function scope) are fine and not flagged.
- **jit-then-call** — ``jax.jit(f)(x)`` in one expression: the compiled
  artifact is dropped on the floor, so every execution retraces.
- **varying-scalar-arg** — a tracked jitted binding (``X = jax.jit(f,
  static_argnums=...)``; module global or ``self._x``) called with a
  Python scalar that varies across calls (``len(...)``, ``.shape`` /
  ``.ndim`` / ``.size``, or a ``range()`` loop variable) at a position
  *not* marked static — each distinct value is a new trace.
- **traced-branch** (warning) — ``if`` on a parameter inside a
  ``@jax.jit``-decorated function (parameters named in
  ``static_argnames`` excluded): either it fails under tracing or the
  author meant ``lax.cond``/``jnp.where``.

Escape hatch: ``# graftlint: recompile-ok`` (e.g. deliberate one-time
``jax.jit(opt.init)(params)`` at setup).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from chainermn_tpu.analysis import astutil
from chainermn_tpu.analysis.checkers.hotpath import _is_hot
from chainermn_tpu.analysis.core import Checker, Finding, Project

JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
SHAPE_ATTRS = {"shape", "ndim", "size"}


def _is_jit_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and astutil.call_name(node.func) in JIT_NAMES)


def _static_positions(call: ast.Call) -> set:
    out: set = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            vals = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    out.add(v.value)
    return out


def _static_names(call: ast.Call) -> set:
    out: set = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            vals = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    out.add(v.value)
    return out


class RecompileChecker(Checker):
    rule = "recompile-hazard"
    suppress_token = "recompile-ok"

    def check(self, project: Project) -> Iterator[Finding]:
        for module in project.modules:
            yield from self._check_module(module)

    def _check_module(self, module) -> Iterator[Finding]:
        bindings: dict = {}   # key -> (node, static positions)
        for node in ast.walk(module.tree):
            if _is_jit_call(node):
                yield from self._jit_site(module, node)
            if isinstance(node, ast.Assign) and _is_jit_call(node.value):
                statics = _static_positions(node.value)
                for tgt in node.targets:
                    key = self._binding_key(tgt)
                    if key is not None:
                        bindings[key] = statics
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._traced_branches(module, node)
        if bindings:
            yield from self._varying_scalars(module, bindings)

    @staticmethod
    def _binding_key(tgt: ast.AST) -> Optional[str]:
        attr = astutil.is_self_attr(tgt)
        if attr is not None:
            return f"self.{attr}"
        if isinstance(tgt, ast.Name):
            return tgt.id
        return None

    # -- jit evaluation sites --------------------------------------------- #

    def _jit_site(self, module, node: ast.Call) -> Iterator[Finding]:
        where = self._loop_context(module, node)
        qual_fn = astutil.enclosing_function(node)
        qual = astutil.func_qualname(qual_fn) if qual_fn else "<module>"
        if where is not None:
            yield self.finding(
                module, node,
                f"jax.jit evaluated inside a {where} in {qual} — every "
                f"evaluation is a fresh callable with an empty trace "
                f"cache; hoist it or cache the compiled fn",
                symbol=f"{qual}:jit-in-loop")
        elif qual_fn is not None and _is_hot(module, qual_fn):
            yield self.finding(
                module, node,
                f"jax.jit evaluated inside hot body {qual} — hoist to "
                f"setup/warmup",
                symbol=f"{qual}:jit-in-hot")
        parent = getattr(node, "graft_parent", None)
        if isinstance(parent, ast.Call) and parent.func is node:
            yield self.finding(
                module, node,
                f"jax.jit(f)(...) called in one expression in {qual} — "
                f"the compiled callable is discarded, so every execution "
                f"retraces; bind it once",
                symbol=f"{qual}:jit-then-call")

    @staticmethod
    def _loop_context(module, node: ast.AST) -> Optional[str]:
        cur = getattr(node, "graft_parent", None)
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            if isinstance(cur, (ast.For, ast.AsyncFor)):
                return "for loop"
            if isinstance(cur, ast.While):
                return "while loop"
            cur = getattr(cur, "graft_parent", None)
        return None

    # -- varying scalars at call-sites ------------------------------------ #

    def _varying_scalars(self, module, bindings: dict
                         ) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            key = self._binding_key(node.func)
            if key is None or key not in bindings:
                continue
            statics = bindings[key]
            for i, arg in enumerate(node.args):
                if i in statics:
                    continue
                why = self._varying_scalar(module, node, arg)
                if why is None:
                    continue
                fn = astutil.enclosing_function(node)
                qual = astutil.func_qualname(fn) if fn else "<module>"
                yield self.finding(
                    module, arg,
                    f"jitted {key} called with {why} at positional arg "
                    f"{i} not in static_argnums — each distinct value "
                    f"retraces; mark it static or pass a device array",
                    symbol=f"{qual}:{key}:arg{i}")

    def _varying_scalar(self, module, call, arg) -> Optional[str]:
        if isinstance(arg, ast.Call) \
                and astutil.call_name(arg.func) == "len":
            return "len(...)"
        if isinstance(arg, ast.Attribute) and arg.attr in SHAPE_ATTRS:
            return f".{arg.attr}"
        if isinstance(arg, ast.Subscript) \
                and isinstance(arg.value, ast.Attribute) \
                and arg.value.attr in SHAPE_ATTRS:
            return f".{arg.value.attr}[...]"
        if isinstance(arg, ast.Name) \
                and arg.id in self._range_vars(call):
            return f"range-loop variable '{arg.id}'"
        return None

    @staticmethod
    def _range_vars(node: ast.AST) -> set:
        out: set = set()
        cur = getattr(node, "graft_parent", None)
        while cur is not None:
            if isinstance(cur, ast.For) \
                    and isinstance(cur.iter, ast.Call) \
                    and astutil.call_name(cur.iter.func) in ("range",
                                                             "enumerate"):
                for n in ast.walk(cur.target):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
            cur = getattr(cur, "graft_parent", None)
        return out

    # -- traced branches inside @jax.jit bodies --------------------------- #

    def _traced_branches(self, module, func) -> Iterator[Finding]:
        jit_dec = None
        for dec in func.decorator_list:
            if _is_jit_call(dec) or astutil.call_name(dec) in JIT_NAMES:
                jit_dec = dec
                break
        if jit_dec is None:
            return
        static = _static_names(jit_dec) if isinstance(jit_dec,
                                                      ast.Call) else set()
        params = {a.arg for a in func.args.args + func.args.kwonlyargs
                  if a.arg not in ("self", "cls")} - static
        if not params:
            return
        qual = astutil.func_qualname(func)
        for sub in ast.walk(func):
            if not isinstance(sub, ast.If):
                continue
            used = {n.id for n in ast.walk(sub.test)
                    if isinstance(n, ast.Name)} & params
            if used:
                name = sorted(used)[0]
                yield self.finding(
                    module, sub,
                    f"branch on traced parameter '{name}' inside jitted "
                    f"{qual} — shape-/value-dependent control flow "
                    f"retraces (or fails); use lax.cond/jnp.where or "
                    f"static_argnames",
                    symbol=f"{qual}:if-{name}",
                    severity="warning")


__all__ = ["RecompileChecker"]
