"""Cut-point & metric/event consistency checker.

Fault cut-points (``resilience.faults.inject``/``torn_fraction``) and
monitor metric/event names are *stringly-typed protocols*: a typo'd
point silently never fires, a renamed metric silently forks the time
series, and the README table rots. This checker pins all three surfaces
to two AST-parsed catalogs (never imported — the analyzer stays
stdlib-only):

- ``chainermn_tpu/resilience/cutpoints.py`` — UPPERCASE string
  constants (one per cut-point), ``DYNAMIC_PREFIXES`` for families like
  ``comm.<op>``, and helper functions (``comm_point``) that build
  dynamic points;
- ``chainermn_tpu/monitor/catalog.py`` — ``METRIC_NAMES`` and
  ``EVENT_KINDS`` frozensets.

Rules (errors unless noted):

- an ``inject(...)``/``torn_fraction(...)``/``point=`` argument that is
  a bare string literal (migrate to the catalog constant);
- a resolved point value absent from the catalog, and catalog constants
  no call-site uses (drift, both directions);
- catalog values violating the naming conventions (``seg.seg`` lowercase
  cut-points; ``^[a-z][a-z0-9_]*$`` metrics/events; counters end
  ``_total``; a name ends ``_seconds`` iff it is a histogram with
  ``unit="s"``);
- metric/event emission with a literal name not in the catalog, and
  catalog names never emitted;
- every cut-point must appear quoted in some file under ``tests/``
  (warning for metrics/events) and in the README cut-point docs.

Escape hatch: ``# graftlint: name-ok``.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from chainermn_tpu.analysis import astutil
from chainermn_tpu.analysis.core import Checker, Finding, Project

CUTPOINTS_MOD = "chainermn_tpu.resilience.cutpoints"
CATALOG_MOD = "chainermn_tpu.monitor.catalog"
FAULTS_MOD = "chainermn_tpu.resilience.faults"
REGISTRY_MOD = "chainermn_tpu.monitor.registry"

CUT_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)+$")
NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

INJECT_FUNCS = {"inject", "torn_fraction", "_inject"}
METRIC_FUNCS = {"counter", "gauge", "histogram"}


def _str_elts(expr: ast.AST) -> list:
    """String constants inside a set/tuple/list/frozenset(...) literal."""
    if isinstance(expr, ast.Call) and astutil.call_name(expr.func) in (
            "frozenset", "set", "tuple"):
        return _str_elts(expr.args[0]) if expr.args else []
    if isinstance(expr, (ast.Set, ast.Tuple, ast.List)):
        return [e.value for e in expr.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


class _Catalogs:
    def __init__(self) -> None:
        self.cutpoints: dict = {}        # CONST name -> value
        self.cut_nodes: dict = {}        # CONST name -> assign node
        self.dynamic_prefixes: list = []
        self.helpers: set = set()        # cutpoints module function names
        self.metric_names: set = set()
        self.event_kinds: set = set()
        self.cutpoints_mod = None
        self.catalog_mod = None

    def load(self, project: Project) -> None:
        cp = project.module(CUTPOINTS_MOD)
        if cp is not None:
            self.cutpoints_mod = cp
            for node in cp.tree.body:
                if isinstance(node, ast.FunctionDef):
                    self.helpers.add(node.name)
                if not isinstance(node, ast.Assign):
                    continue
                for tgt in node.targets:
                    if not isinstance(tgt, ast.Name):
                        continue
                    if tgt.id == "DYNAMIC_PREFIXES":
                        self.dynamic_prefixes = _str_elts(node.value)
                    elif tgt.id.isupper() and isinstance(node.value,
                                                         ast.Constant) \
                            and isinstance(node.value.value, str):
                        self.cutpoints[tgt.id] = node.value.value
                        self.cut_nodes[tgt.id] = node
        cat = project.module(CATALOG_MOD)
        if cat is not None:
            self.catalog_mod = cat
            for node in cat.tree.body:
                if not isinstance(node, ast.Assign):
                    continue
                for tgt in node.targets:
                    if not isinstance(tgt, ast.Name):
                        continue
                    if tgt.id == "METRIC_NAMES":
                        self.metric_names = set(_str_elts(node.value))
                    elif tgt.id == "EVENT_KINDS":
                        self.event_kinds = set(_str_elts(node.value))

    def point_known(self, value: str) -> bool:
        return value in self.cutpoints.values() or any(
            value.startswith(p) for p in self.dynamic_prefixes)


class ConsistencyChecker(Checker):
    rule = "consistency"
    suppress_token = "name-ok"

    def check(self, project: Project) -> Iterator[Finding]:
        cats = _Catalogs()
        cats.load(project)
        yield from self._missing_catalogs(project, cats)

        used_points: set = set()
        used_metrics: set = set()
        used_events: set = set()
        for module in project.modules:
            if module.modname == CUTPOINTS_MOD:
                continue
            yield from self._scan_module(module, cats, used_points,
                                         used_metrics, used_events)
        yield from self._catalog_side(project, cats, used_points,
                                      used_metrics, used_events)

    # -- presence --------------------------------------------------------- #

    def _missing_catalogs(self, project: Project, cats: _Catalogs
                          ) -> Iterator[Finding]:
        if project.module(FAULTS_MOD) is not None \
                and cats.cutpoints_mod is None:
            yield self.finding(
                project.module(FAULTS_MOD), None,
                f"fault injection exists but {CUTPOINTS_MOD} (the "
                f"cut-point catalog) is missing",
                symbol="missing:cutpoints")
        if project.module(REGISTRY_MOD) is not None \
                and cats.catalog_mod is None:
            yield self.finding(
                project.module(REGISTRY_MOD), None,
                f"metrics registry exists but {CATALOG_MOD} (the "
                f"metric/event catalog) is missing",
                symbol="missing:catalog")

    # -- per-module scan --------------------------------------------------- #

    def _scan_module(self, module, cats, used_points, used_metrics,
                     used_events) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._point_defaults(module, node, cats,
                                                used_points)
            if not isinstance(node, ast.Call):
                continue
            dotted = astutil.call_name(node.func)
            leaf = dotted.rsplit(".", 1)[-1] if dotted else ""
            if leaf in INJECT_FUNCS and module.modname != FAULTS_MOD:
                expr = node.args[0] if node.args else next(
                    (kw.value for kw in node.keywords
                     if kw.arg == "point"), None)
                if expr is not None:
                    yield from self._point_expr(module, node, expr, cats,
                                                used_points)
            elif any(kw.arg == "point" for kw in node.keywords):
                expr = next(kw.value for kw in node.keywords
                            if kw.arg == "point")
                yield from self._point_expr(module, node, expr, cats,
                                            used_points)
            # receiver methods match on the attribute name so that
            # get_registry().counter(...) / get_event_log().emit(...)
            # (dynamic receivers call_name cannot resolve) still count
            meth = node.func.attr if isinstance(node.func,
                                                ast.Attribute) else leaf
            if meth in METRIC_FUNCS and isinstance(node.func,
                                                   ast.Attribute) \
                    and module.modname != REGISTRY_MOD:
                yield from self._metric_site(module, node, meth, cats,
                                             used_metrics)
            if meth == "emit" and isinstance(node.func, ast.Attribute) \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str) \
                    and module.modname != "chainermn_tpu.monitor.events":
                yield from self._event_site(module, node, cats,
                                            used_events)

    # -- cut-points -------------------------------------------------------- #

    def _point_defaults(self, module, func, cats, used_points
                        ) -> Iterator[Finding]:
        args = func.args
        pos = args.args + args.kwonlyargs
        defaults = ([None] * (len(args.args) - len(args.defaults))
                    + list(args.defaults) + list(args.kw_defaults))
        for a, d in zip(pos, defaults):
            if a.arg != "point" or d is None:
                continue
            yield from self._point_expr(module, d, d, cats, used_points,
                                        context=f"default of "
                                        f"{astutil.func_qualname(func)}")

    def _point_expr(self, module, node, expr, cats, used_points,
                    context: str = "") -> Iterator[Finding]:
        value, kind = self._resolve_point(module, expr, cats)
        where = f" ({context})" if context else ""
        if kind == "literal":
            used_points.add(value)
            if cats.cutpoints_mod is not None:
                yield self.finding(
                    module, node,
                    f"bare cut-point literal {value!r}{where} — use the "
                    f"constant from resilience/cutpoints.py",
                    symbol=f"literal:{module.modname}:{value}")
            return
        if kind == "const":
            used_points.add(value)
            if not cats.point_known(value):
                yield self.finding(
                    module, node,
                    f"cut-point {value!r}{where} is not in the "
                    f"cutpoints catalog",
                    symbol=f"unknown:{module.modname}:{value}")
        elif kind == "helper":
            used_points.update(cats.dynamic_prefixes)
        elif kind == "unknown-const":
            yield self.finding(
                module, node,
                f"cut-point constant {value} is not defined in "
                f"resilience/cutpoints.py",
                symbol=f"unknown-const:{module.modname}:{value}")
        # kind None: unresolvable expression — no claim

    def _resolve_point(self, module, expr, cats,
                       depth: int = 0) -> tuple:
        """(value, kind) where kind ∈ {literal, const, helper,
        unknown-const, None}."""
        if depth > 4:
            return None, None
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value, "literal"
        if isinstance(expr, ast.Attribute) and expr.attr.isupper():
            if expr.attr in cats.cutpoints:
                return cats.cutpoints[expr.attr], "const"
            return expr.attr, "unknown-const"
        if isinstance(expr, ast.Name) and expr.id.isupper():
            if expr.id in cats.cutpoints:
                return cats.cutpoints[expr.id], "const"
            return expr.id, "unknown-const"
        if isinstance(expr, ast.Call):
            leaf = astutil.call_name(expr.func).rsplit(".", 1)[-1]
            if leaf in cats.helpers:
                return leaf, "helper"
            return None, None
        if isinstance(expr, ast.IfExp):
            v, k = self._resolve_point(module, expr.body, cats, depth + 1)
            if k is not None:
                return v, k
            return self._resolve_point(module, expr.orelse, cats,
                                       depth + 1)
        if isinstance(expr, ast.Name):
            func = astutil.enclosing_function(expr)
            if func is not None:
                for sub in ast.walk(func):
                    if isinstance(sub, ast.Assign) \
                            and len(sub.targets) == 1 \
                            and isinstance(sub.targets[0], ast.Name) \
                            and sub.targets[0].id == expr.id:
                        return self._resolve_point(module, sub.value,
                                                   cats, depth + 1)
        return None, None

    # -- metrics / events -------------------------------------------------- #

    def _metric_site(self, module, node, kind, cats, used_metrics
                     ) -> Iterator[Finding]:
        if not node.args or not isinstance(node.args[0], ast.Constant) \
                or not isinstance(node.args[0].value, str):
            return
        name = node.args[0].value
        used_metrics.add(name)
        sym = f"metric:{module.modname}:{name}"
        if not NAME_RE.match(name):
            yield self.finding(
                module, node,
                f"metric name {name!r} violates ^[a-z][a-z0-9_]*$",
                symbol=sym)
        if cats.catalog_mod is not None and name not in cats.metric_names:
            yield self.finding(
                module, node,
                f"metric {name!r} is not in monitor/catalog.py "
                f"METRIC_NAMES",
                symbol=sym)
        if kind == "counter" and not name.endswith("_total"):
            yield self.finding(
                module, node,
                f"counter {name!r} must end in _total",
                symbol=sym)
        unit = next((kw.value.value for kw in node.keywords
                     if kw.arg == "unit"
                     and isinstance(kw.value, ast.Constant)), "")
        is_secs_hist = kind == "histogram" and unit == "s"
        if name.endswith("_seconds") != is_secs_hist:
            why = ("ends in _seconds but is not a histogram with "
                   "unit='s'" if name.endswith("_seconds")
                   else "is a histogram with unit='s' but does not end "
                   "in _seconds")
            yield self.finding(module, node,
                               f"metric {name!r} {why}", symbol=sym)

    def _event_site(self, module, node, cats, used_events
                    ) -> Iterator[Finding]:
        kind = node.args[0].value
        used_events.add(kind)
        sym = f"event:{module.modname}:{kind}"
        if not NAME_RE.match(kind):
            yield self.finding(
                module, node,
                f"event kind {kind!r} violates ^[a-z][a-z0-9_]*$",
                symbol=sym)
        if cats.catalog_mod is not None and kind not in cats.event_kinds:
            yield self.finding(
                module, node,
                f"event kind {kind!r} is not in monitor/catalog.py "
                f"EVENT_KINDS",
                symbol=sym)

    # -- catalog-side rules ------------------------------------------------ #

    def _catalog_side(self, project, cats, used_points, used_metrics,
                      used_events) -> Iterator[Finding]:
        tests_text = "\n".join(text for _p, text
                               in project.root_files("tests"))
        readme = project.read_root_file("README.md") or ""

        def referenced(value: str, text: str) -> bool:
            return f'"{value}"' in text or f"'{value}'" in text

        cp_mod = cats.cutpoints_mod
        if cp_mod is not None:
            for const, value in sorted(cats.cutpoints.items()):
                node = cats.cut_nodes[const]
                sym = f"cutpoint:{const}"
                if not CUT_RE.match(value):
                    yield self.finding(
                        cp_mod, node,
                        f"cut-point {value!r} violates the "
                        f"subsystem.site naming convention", symbol=sym)
                if value not in used_points:
                    yield self.finding(
                        cp_mod, node,
                        f"catalog cut-point {const} = {value!r} is not "
                        f"used by any inject()/torn_fraction() site",
                        symbol=sym)
                if tests_text and not referenced(value, tests_text):
                    yield self.finding(
                        cp_mod, node,
                        f"cut-point {value!r} is not referenced by any "
                        f"test under tests/", symbol=sym)
                if readme and value not in readme:
                    yield self.finding(
                        cp_mod, node,
                        f"cut-point {value!r} is missing from the README "
                        f"cut-point docs", symbol=sym)

        cat_mod = cats.catalog_mod
        if cat_mod is not None:
            for name in sorted(cats.metric_names):
                sym = f"metric:{name}"
                if name not in used_metrics:
                    yield self.finding(
                        cat_mod, None,
                        f"catalog metric {name!r} is never created by "
                        f"any counter()/gauge()/histogram() site",
                        symbol=sym)
                elif tests_text and not referenced(name, tests_text):
                    yield self.finding(
                        cat_mod, None,
                        f"metric {name!r} is not referenced by any test",
                        symbol=sym, severity="warning")
            for kind in sorted(cats.event_kinds):
                sym = f"event:{kind}"
                if kind not in used_events:
                    yield self.finding(
                        cat_mod, None,
                        f"catalog event kind {kind!r} is never emitted "
                        f"with a literal kind", symbol=sym)
                elif tests_text and not referenced(kind, tests_text):
                    yield self.finding(
                        cat_mod, None,
                        f"event kind {kind!r} is not referenced by any "
                        f"test", symbol=sym, severity="warning")


__all__ = ["ConsistencyChecker"]
