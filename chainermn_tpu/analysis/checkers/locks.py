"""Lock checkers: per-class guarded-attribute discipline and the
cross-class lock-acquisition-order graph.

**lock-discipline** — for every class that owns a ``threading.Lock /
RLock / Condition``, the set of ``self._*`` attributes ever touched
under ``with self.<lock>:`` is that class's *guarded set*; any access to
a guarded attribute outside the lock (in any method except
``__init__``, which runs before the object is shared) is a finding.
Escape hatch ``# graftlint: unguarded-ok`` for single-writer or
torn-read-tolerant sites.

**lock-order** — an edge ``A → B`` means "some method of A may acquire
B's lock while holding A's own lock". Lock acquisition is tracked
*transitively across classes*: every (class, method) gets a fixpoint
set of lock **sinks** — the classes whose locks the call may end up
acquiring through any chain of typed calls (``FleetRouter._bind_locked
→ replica.submit → FCFSScheduler.submit`` sinks to ``FCFSScheduler``
even though ``EngineReplica`` owns no lock). Cycles in the edge graph
are the static shadow of an ABBA deadlock and gate the run, as does
re-acquiring a non-reentrant own lock (nested ``with self._lock`` or
calling one of the class's own locking methods under it). Receivers are
typed with :class:`~chainermn_tpu.analysis.astutil.TypeWorld`
(constructor / factory / list-element inference); untypeable receivers
create no edge. Escape hatch ``# graftlint: lock-order-ok``.

:func:`static_lock_graph` exposes the same edge set as data — the
runtime sanitizer (:mod:`chainermn_tpu.analysis.sanitizer`) and the
``--runtime-report`` CLI mode assert every *observed* edge is in it.
"""

from __future__ import annotations

import ast
from typing import Iterator

from chainermn_tpu.analysis import astutil
from chainermn_tpu.analysis.core import Checker, Finding, Project


# container/collection methods that mutate their receiver — a call to
# one of these under the lock marks the receiver attr as lock-protected
MUTATORS = {
    "add", "append", "appendleft", "clear", "discard", "extend",
    "insert", "pop", "popitem", "popleft", "push", "remove", "reverse",
    "rotate", "setdefault", "sort", "update",
}


class LockDisciplineChecker(Checker):
    rule = "lock-discipline"
    suppress_token = "unguarded-ok"

    def check(self, project: Project) -> Iterator[Finding]:
        for module in project.modules:
            for cm in astutil.iter_classes(module):
                if not cm.lock_attrs:
                    continue
                yield from self._check_class(module, cm)

    def _excluded(self, cm: astutil.ClassModel) -> set:
        # locks guard data, not other synchronizers or bound methods
        return cm.lock_attrs | cm.event_attrs | set(cm.methods)

    @staticmethod
    def _root_self_attr(expr: ast.AST):
        """Underlying ``self._x`` of ``self._x[k]...`` chains."""
        while isinstance(expr, ast.Subscript):
            expr = expr.value
        return astutil.is_self_attr(expr)

    def _accesses(self, cm: astutil.ClassModel):
        """(attr, mutates, under_lock, method, node) records for every
        ``self._*`` access outside ``__init__``. Methods named
        ``*_locked`` are the repo's called-with-lock-held convention and
        count as under the lock throughout."""
        excluded = self._excluded(cm)
        for name, meth in cm.methods.items():
            if name == "__init__":
                continue
            assumed = name.endswith("_locked")
            for sub in ast.walk(meth):
                attr = mutates = None
                if isinstance(sub, ast.Attribute):
                    attr = astutil.is_self_attr(sub)
                    mutates = isinstance(sub.ctx, (ast.Store, ast.Del))
                elif isinstance(sub, ast.Subscript) \
                        and isinstance(sub.ctx, (ast.Store, ast.Del)):
                    attr = self._root_self_attr(sub)
                    mutates = True
                elif isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in MUTATORS:
                    attr = self._root_self_attr(sub.func.value)
                    mutates = True
                if attr is None or not attr.startswith("_") \
                        or attr in excluded:
                    continue
                under = assumed or cm.under_own_lock(sub)
                yield attr, mutates, under, name, sub

    def _check_class(self, module, cm: astutil.ClassModel
                     ) -> Iterator[Finding]:
        records = list(self._accesses(cm))
        mutated_under = {a for a, mut, under, _m, _n in records
                         if mut and under}
        read_under = {a for a, mut, under, _m, _n in records if under}
        mutated_anywhere = {a for a, mut, _u, _m, _n in records if mut}
        # guarded = mutated while holding the lock, or read under the
        # lock AND mutated somewhere after construction (a never-
        # reassigned reference to a thread-safe object is not shared
        # mutable state, even if it is touched inside critical sections)
        guarded = mutated_under | (read_under & mutated_anywhere)
        if not guarded:
            return
        seen: set = set()
        for attr, _mut, under, name, sub in records:
            if attr not in guarded or under:
                continue
            key = (cm.name, attr, name)
            if key in seen:
                continue
            seen.add(key)
            yield self.finding(
                module, sub,
                f"{cm.name}.{attr} is guarded by "
                f"{'/'.join(sorted(cm.lock_attrs))} elsewhere but "
                f"accessed without it in {name}()",
                symbol=f"{cm.name}.{attr}@{name}")


class LockOrderChecker(Checker):
    rule = "lock-order"
    suppress_token = "lock-order-ok"

    def check(self, project: Project) -> Iterator[Finding]:
        yield from self._run(project, {})

    def _run(self, project: Project, edges: dict) -> Iterator[Finding]:
        """Full pass; ``edges[(A, B)] = (module, node, caller, callee)``
        is filled as a side effect (:func:`static_lock_graph` reads it
        back without caring about the findings)."""
        models: list = []
        per_module: dict = {}
        for module in project.modules:
            cms = astutil.iter_classes(module)
            per_module[module.modname] = cms
            models.extend(cms)
        world = astutil.TypeWorld(models)
        for module in project.modules:
            world.learn_factories(module)
        for cm in models:
            world.learn_attr_types(cm)
        sinks = self._lock_sinks(models, world)

        for module in project.modules:
            for cm in per_module[module.modname]:
                if not cm.lock_attrs:
                    continue
                yield from self._scan_class(module, cm, world, sinks,
                                            edges)

        yield from self._cycles(edges)

    # -- transitive lock sinks ------------------------------------------- #

    def _lock_sinks(self, models: list, world: astutil.TypeWorld) -> dict:
        """``(class name, method name) → frozenset of class names``
        whose locks the method may acquire — directly or through any
        chain of typed intra-/cross-class calls, to fixpoint."""
        canon = [cm for cm in models
                 if world.classes.get(cm.name) is cm]
        callees: dict = {}
        sinks: dict = {}
        for cm in canon:
            for name, meth in cm.methods.items():
                key = (cm.name, name)
                callees[key] = self._method_callees(cm, world, meth)
                sinks[key] = (frozenset({cm.name})
                              if cm.method_locks_directly(meth)
                              else frozenset())
        changed = True
        while changed:
            changed = False
            for key, calls in callees.items():
                cur = sinks[key]
                acc = set(cur)
                for c in calls:
                    acc |= sinks.get(c, frozenset())
                if acc != cur:
                    sinks[key] = frozenset(acc)
                    changed = True
        return sinks

    @staticmethod
    def _method_callees(cm, world, meth) -> list:
        locals_ = world.local_types(cm, meth)
        out: list = []
        for sub in ast.walk(meth):
            if not isinstance(sub, ast.Call) \
                    or not isinstance(sub.func, ast.Attribute):
                continue
            if astutil.is_self_attr(sub.func) is not None:
                out.append((cm.name, sub.func.attr))
                continue
            cls_name = world.receiver_class(cm, locals_, sub.func.value)
            if cls_name:
                out.append((cls_name, sub.func.attr))
        return out

    # -- per-class scan -------------------------------------------------- #

    def _scan_class(self, module, cm: astutil.ClassModel,
                    world: astutil.TypeWorld, sinks: dict, edges: dict
                    ) -> Iterator[Finding]:
        for name, meth in cm.methods.items():
            locals_ = world.local_types(cm, meth)
            for sub in ast.walk(meth):
                if not cm.under_own_lock(sub):
                    continue
                found = self._finding_at(module, cm, world, locals_,
                                         sinks, name, sub, edges)
                if found is not None:
                    yield found

    def _finding_at(self, module, cm, world, locals_, sinks, meth_name,
                    sub, edges):
        # nested re-acquire of a non-reentrant own lock
        if isinstance(sub, (ast.With, ast.AsyncWith)):
            for item in sub.items:
                attr = astutil.is_self_attr(item.context_expr)
                if (attr in cm.lock_attrs and attr not in cm.reentrant
                        and self._outer_holds(cm, sub, attr)):
                    return self.finding(
                        module, sub,
                        f"{cm.name}.{meth_name} re-enters non-reentrant "
                        f"lock {attr} already held by an enclosing with",
                        symbol=f"{cm.name}.{meth_name}:self-reacquire")
            return None

        callee_cls, callee = self._typed_callee(cm, world, locals_, sub)
        if callee_cls is None:
            return None
        if callee_cls is cm and callee in cm.locking_methods \
                and not cm.reentrant:
            return self.finding(
                module, sub,
                f"{cm.name}.{meth_name} calls own locking method "
                f"{callee}() while already holding the (non-reentrant)"
                f" lock — use an _unlocked variant",
                symbol=f"{cm.name}.{meth_name}->{callee}")
        for sink in sorted(sinks.get((callee_cls.name, callee), ())):
            if sink == cm.name:
                continue
            edges.setdefault((cm.name, sink),
                             (module, sub, f"{cm.name}.{meth_name}",
                              f"{callee_cls.name}.{callee}"))
        return None

    def _typed_callee(self, cm, world, locals_, sub):
        """(ClassModel, method/property name) when ``sub`` invokes a
        method or property of a typed receiver, else (None, None)."""
        if isinstance(sub, ast.Call) and isinstance(sub.func,
                                                    ast.Attribute):
            recv, meth = sub.func.value, sub.func.attr
            if astutil.is_self_attr(sub.func) is not None:
                return cm, meth
            cls_name = world.receiver_class(cm, locals_, recv)
            target = world.classes.get(cls_name) if cls_name else None
            if target is not None:
                return target, meth
        elif isinstance(sub, ast.Attribute) and getattr(
                getattr(sub, "graft_parent", None), "func", None) is not sub:
            # @property access (receiver.prop) — skip when the
            # attribute is itself the callee of a Call (handled above)
            cls_name = world.receiver_class(cm, locals_, sub.value)
            target = world.classes.get(cls_name) if cls_name else None
            if target is not None and sub.attr in target.properties:
                return target, sub.attr
        return None, None

    def _outer_holds(self, cm, node, lock_attr: str) -> bool:
        cur = getattr(node, "graft_parent", None)
        while cur is not None and not isinstance(cur, ast.ClassDef):
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    if astutil.is_self_attr(item.context_expr) == lock_attr:
                        return True
            cur = getattr(cur, "graft_parent", None)
        return False

    # -- graph ----------------------------------------------------------- #

    def _cycles(self, edges: dict) -> Iterator[Finding]:
        graph: dict = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)

        emitted: set = set()

        def dfs(start, node, path):
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    yield path + [nxt]
                elif nxt not in path:
                    yield from dfs(start, nxt, path + [nxt])

        for start in sorted(graph):
            for cyc in dfs(start, start, [start]):
                key = frozenset(cyc)
                if key in emitted:
                    continue
                emitted.add(key)
                module, node, caller, callee = edges[(cyc[0], cyc[1])]
                chain = " -> ".join(cyc)
                yield self.finding(
                    module, node,
                    f"lock-acquisition cycle {chain} (ABBA deadlock "
                    f"hazard); representative edge {caller} -> {callee} "
                    f"under {cyc[0]}'s lock",
                    symbol=f"cycle:{'->'.join(sorted(key))}")


def static_lock_graph(project: Project) -> set:
    """The static lock-order edge set as ``{(holder_class,
    acquired_class)}`` — the reference graph the runtime sanitizer's
    *observed* edges must be a subset of (``--runtime-report``)."""
    edges: dict = {}
    for _ in LockOrderChecker()._run(project, edges):
        pass
    return set(edges)


__all__ = ["LockDisciplineChecker", "LockOrderChecker",
           "static_lock_graph"]
