"""Import-hygiene checker: the static module-level import graph.

``tests/monitor_tests/test_import_hygiene.py`` proves in a subprocess
that monitor / fleet / deploy import without jax, extensions, or the
serving stack; this checker proves the same property over *every*
module, without running anything, and names the offending chain.

Only module-level imports count — an import inside a function body is
the sanctioned lazy pattern. Importing ``a.b.c`` executes every ancestor
package ``__init__`` on the way down, so edges are added for ``a.b`` as
well (the bare top-level ``chainermn_tpu`` package is excluded,
mirroring the hygiene test's parent-package stub). ``if TYPE_CHECKING:``
blocks are ignored.

Rules enforced (prefix-matched, transitively over analyzed modules):

- ``chainermn_tpu.monitor`` must not reach ``chainermn_tpu.extensions``;
- ``chainermn_tpu.fleet`` / ``chainermn_tpu.deploy`` must not reach
  ``chainermn_tpu.extensions``, ``chainermn_tpu.serving``, or ``jax``;
- ``chainermn_tpu.analysis`` must not reach *any* ``chainermn_tpu.*``
  outside itself, nor ``jax`` / ``numpy`` — the analyzer never imports
  what it analyzes.

Escape hatch: ``# graftlint: import-ok`` on the import line.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from chainermn_tpu.analysis.core import Checker, Finding, Project

TOP_PACKAGE = "chainermn_tpu"


def _prefixed(name: str, prefix: str) -> bool:
    return name == prefix or name.startswith(prefix + ".")


class Rule:
    def __init__(self, source: str, forbidden: tuple,
                 allowed: tuple = ()) -> None:
        self.source = source
        self.forbidden = forbidden
        self.allowed = allowed

    def violates(self, name: str) -> Optional[str]:
        for ok in self.allowed:
            if _prefixed(name, ok):
                return None
        for bad in self.forbidden:
            if _prefixed(name, bad):
                return bad
        return None


RULES = (
    Rule("chainermn_tpu.monitor",
         forbidden=("chainermn_tpu.extensions",)),
    Rule("chainermn_tpu.fleet",
         forbidden=("chainermn_tpu.extensions", "chainermn_tpu.serving",
                    "jax")),
    Rule("chainermn_tpu.deploy",
         forbidden=("chainermn_tpu.extensions", "chainermn_tpu.serving",
                    "jax")),
    Rule("chainermn_tpu.analysis",
         forbidden=("chainermn_tpu", "jax", "numpy"),
         allowed=("chainermn_tpu.analysis",)),
)


def eager_imports(module) -> list:
    """(dotted name, import node) pairs for module-level imports,
    ancestors included, function bodies and TYPE_CHECKING blocks not."""
    out: list = []

    is_package = module.path.endswith("__init__.py")
    pkg_parts = module.modname.split(".")
    if not is_package:
        pkg_parts = pkg_parts[:-1]

    def add(name: str, node) -> None:
        parts = name.split(".")
        for i in range(1, len(parts) + 1):
            anc = ".".join(parts[:i])
            if anc != TOP_PACKAGE:
                out.append((anc, node))

    def visit(stmts) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    add(alias.name, stmt)
            elif isinstance(stmt, ast.ImportFrom):
                if stmt.level:
                    base_parts = pkg_parts[:len(pkg_parts)
                                           - (stmt.level - 1)]
                    base = ".".join(base_parts)
                    name = f"{base}.{stmt.module}" if stmt.module else base
                else:
                    name = stmt.module or ""
                if name:
                    add(name, stmt)
                    # `from pkg import sub` may bind a submodule: add the
                    # candidate only when it is an analyzed module
                    for alias in stmt.names:
                        out.append((f"{name}.{alias.name}", stmt))
            elif isinstance(stmt, (ast.If,)):
                tests = " ".join(n.id for n in ast.walk(stmt.test)
                                 if isinstance(n, ast.Name))
                if "TYPE_CHECKING" not in tests:
                    visit(stmt.body)
                    visit(stmt.orelse)
            elif isinstance(stmt, ast.Try):
                visit(stmt.body)
                visit(stmt.orelse)
                visit(stmt.finalbody)
                for h in stmt.handlers:
                    visit(h.body)
            elif isinstance(stmt, ast.ClassDef):
                visit(stmt.body)
    visit(module.tree.body)
    return out


class ImportHygieneChecker(Checker):
    rule = "import-hygiene"
    suppress_token = "import-ok"

    def check(self, project: Project) -> Iterator[Finding]:
        eager: dict = {m.modname: eager_imports(m)
                       for m in project.modules}
        for rule in RULES:
            for module in project.modules_under(rule.source):
                yield from self._check_module(project, eager, rule,
                                              module)

    def _check_module(self, project: Project, eager: dict, rule: Rule,
                      module) -> Iterator[Finding]:
        seen: set = set()
        reported: set = set()
        # queue entries: (name, origin import node, chain string)
        queue = [(name, node, module.modname)
                 for name, node in eager.get(module.modname, ())]
        while queue:
            name, node, chain = queue.pop(0)
            if name in seen:
                continue
            seen.add(name)
            bad = rule.violates(name)
            if bad is not None:
                key = (module.modname, bad)
                if key in reported:
                    continue
                reported.add(key)
                via = f"{chain} -> {name}"
                yield self.finding(
                    module, node,
                    f"{module.modname} eagerly reaches {name} "
                    f"({via}) — forbidden by the {rule.source} "
                    f"lazy-import rule; move the import into the "
                    f"function that needs it",
                    symbol=f"{module.modname}->{bad}")
                continue
            nxt = eager.get(name)
            if nxt is not None and name != module.modname:
                for sub_name, _sub_node in nxt:
                    if sub_name not in seen:
                        queue.append((sub_name, node, f"{chain} -> {name}"))
        return


__all__ = ["RULES", "ImportHygieneChecker", "eager_imports"]
