"""Blocking-under-lock checker.

Holding a hot lock across blocking work — device fetches, file/socket
I/O, ``time.sleep``, thread ``join``, blocking ``queue.Queue``
get/put — stalls *every* thread contending on it: the scheduler's
submit path, the router's bind path, the metric scrape. The static
lock-order checker proves ordering; this one proves the critical
sections stay non-blocking.

A statement is "under the lock" when it sits lexically inside ``with
self.<lock>:`` for any of the class's lock attributes, or anywhere in a
``*_locked``-named method (the repo's called-with-lock-held
convention), or inside ``with <MODULE_LOCK>:`` for a module-level lock
global. Flagged inside such regions (errors):

- ``time.sleep`` / bare ``sleep``;
- file I/O and filesystem metadata: ``open``, ``os.replace/rename/
  remove/unlink/fsync/makedirs``, ``shutil.*``;
- ``jax.device_get`` / ``jax.block_until_ready`` /
  ``.block_until_ready()`` and even the sanctioned
  ``dataflow.device_fetch`` — a counted sync point is still a sync;
- ``.join()`` (thread/process) — string-literal separators
  (``", ".join``) are skipped;
- ``.wait()`` — except on the class's own ``Condition`` lock attrs
  (``cv.wait()`` *releases* the lock; that is the sanctioned pattern);
- socket ops (``recv/send/sendall/accept/connect``);
- blocking ``get()``/``put()`` on attributes assigned a
  ``queue.Queue`` family constructor (``get_nowait``/``put_nowait``
  stay legal; plain dict ``.get`` is untouched because only
  queue-typed attributes count).

Escape hatch: ``# graftlint: blocking-ok`` for sections where the
blocking is the point and the exposure is documented (the checkpoint
writer's atomic publish under its I/O lock).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from chainermn_tpu.analysis import astutil
from chainermn_tpu.analysis.core import Checker, Finding, Project

# dotted call names that block regardless of receiver
BLOCKING_CALLS = {
    "time.sleep", "sleep",
    "open", "os.replace", "os.rename", "os.remove", "os.unlink",
    "os.fsync", "os.makedirs",
    "shutil.rmtree", "shutil.copy", "shutil.copyfile", "shutil.move",
    "subprocess.run", "subprocess.check_call", "subprocess.check_output",
    "jax.device_get", "jax.block_until_ready",
    "device_fetch", "dataflow.device_fetch",
}

# receiver.method() calls that block on any receiver
BLOCKING_METHODS = {
    "join", "wait", "block_until_ready",
    "recv", "send", "sendall", "accept", "connect",
}

QUEUE_FACTORIES = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"}
QUEUE_BLOCKING = {"get", "put"}


def _queue_attrs(cm: astutil.ClassModel) -> set:
    """Self-attrs assigned a queue.Queue-family constructor."""
    out: set = set()
    for meth in cm.methods.values():
        for sub in ast.walk(meth):
            if not isinstance(sub, ast.Assign):
                continue
            if not isinstance(sub.value, ast.Call):
                continue
            leaf = astutil.call_name(sub.value.func).rsplit(".", 1)[-1]
            if leaf not in QUEUE_FACTORIES:
                continue
            for tgt in sub.targets:
                attr = astutil.is_self_attr(tgt)
                if attr is not None:
                    out.add(attr)
    return out


def _module_locks(module) -> set:
    """Module-level globals assigned a lock factory."""
    out: set = set()
    for node in module.tree.body:
        if isinstance(node, ast.Assign) \
                and astutil._threading_factory(node.value,
                                               astutil.LOCK_FACTORIES):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


class BlockingUnderLockChecker(Checker):
    rule = "blocking-under-lock"
    suppress_token = "blocking-ok"

    def check(self, project: Project) -> Iterator[Finding]:
        for module in project.modules:
            yield from self._check_classes(module)
            yield from self._check_module_locks(module)

    # -- class locks ------------------------------------------------------ #

    def _check_classes(self, module) -> Iterator[Finding]:
        for cm in astutil.iter_classes(module):
            if not cm.lock_attrs:
                continue
            queues = _queue_attrs(cm)
            expanded: set = set()
            for name, meth in cm.methods.items():
                assumed = name.endswith("_locked")
                local_defs = {sub.name: sub for sub in ast.walk(meth)
                              if isinstance(sub, ast.FunctionDef)
                              and sub is not meth}
                for sub in ast.walk(meth):
                    if not isinstance(sub, ast.Call):
                        continue
                    if not assumed and not cm.under_own_lock(sub):
                        continue
                    found = self._blocking_call(
                        module, sub, holder=cm.name,
                        where=f"{cm.name}.{name}", cm=cm, queues=queues)
                    if found is not None:
                        yield found
                        continue
                    yield from self._expand_callee(
                        module, cm, queues, name, sub, local_defs,
                        expanded)

    def _expand_callee(self, module, cm, queues, caller: str,
                       call: ast.Call, local_defs: dict,
                       expanded: set) -> Iterator[Finding]:
        """One level of indirection: a helper defined in the method
        (``def write(): ...`` then ``write()`` under the lock) or an
        intra-class ``self._m()`` call still runs with the lock held —
        flag blocking calls inside the callee body too. Callees that
        take the class lock themselves are skipped (their own bodies
        are already scanned as lock-held regions)."""
        callee_def = None
        where = None
        if isinstance(call.func, ast.Name) and call.func.id in local_defs:
            callee_def = local_defs[call.func.id]
            where = f"{cm.name}.{caller}.{call.func.id}"
        else:
            attr = astutil.is_self_attr(call.func)
            if attr in cm.methods \
                    and not cm.method_locks_directly(cm.methods[attr]):
                callee_def = cm.methods[attr]
                where = f"{cm.name}.{attr}"
        if callee_def is None or id(callee_def) in expanded:
            return
        expanded.add(id(callee_def))
        for inner in ast.walk(callee_def):
            if not isinstance(inner, ast.Call):
                continue
            found = self._blocking_call(module, inner, holder=cm.name,
                                        where=where, cm=cm, queues=queues)
            if found is not None:
                yield found

    # -- module-level locks ------------------------------------------------ #

    def _check_module_locks(self, module) -> Iterator[Finding]:
        locks = _module_locks(module)
        if not locks:
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            held = [item.context_expr.id for item in node.items
                    if isinstance(item.context_expr, ast.Name)
                    and item.context_expr.id in locks]
            if not held:
                continue
            func = astutil.enclosing_function(node)
            where = astutil.func_qualname(func) if func is not None \
                else module.modname
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                found = self._blocking_call(module, sub, holder=held[0],
                                            where=where)
                if found is not None:
                    yield found

    # -- one call site ----------------------------------------------------- #

    def _blocking_call(self, module, call: ast.Call, *, holder: str,
                       where: str, cm: Optional[astutil.ClassModel] = None,
                       queues: set = frozenset()) -> Optional[Finding]:
        dotted = astutil.call_name(call.func)
        if dotted in BLOCKING_CALLS:
            return self.finding(
                module, call,
                f"{dotted}() while holding {holder}'s lock in {where} — "
                f"blocking work under a lock stalls every contending "
                f"thread; move it outside the critical section",
                symbol=f"{where}:{dotted}")
        if not isinstance(call.func, ast.Attribute):
            return None
        meth = call.func.attr
        recv = call.func.value
        if meth in BLOCKING_METHODS:
            # ", ".join(parts) — a string separator, not a thread
            if isinstance(recv, ast.Constant):
                return None
            # cv.wait() on an own Condition releases the lock: sanctioned
            if cm is not None and meth == "wait" \
                    and astutil.is_self_attr(recv) in cm.lock_attrs:
                return None
            return self.finding(
                module, call,
                f".{meth}() while holding {holder}'s lock in {where} — "
                f"blocking work under a lock stalls every contending "
                f"thread; move it outside the critical section",
                symbol=f"{where}:.{meth}")
        if meth in QUEUE_BLOCKING and cm is not None:
            attr = astutil.is_self_attr(recv)
            if attr in queues and not self._nonblocking_kw(call):
                return self.finding(
                    module, call,
                    f"blocking queue .{meth}() on self.{attr} while "
                    f"holding {holder}'s lock in {where} — use the "
                    f"_nowait variant or move it outside the critical "
                    f"section",
                    symbol=f"{where}:queue.{meth}")
        return None

    @staticmethod
    def _nonblocking_kw(call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return True
        return False


__all__ = ["BLOCKING_CALLS", "BLOCKING_METHODS",
           "BlockingUnderLockChecker"]
