"""Checker registry: the eight graftlint rules, in report order."""

from chainermn_tpu.analysis.checkers.locks import (
    LockDisciplineChecker,
    LockOrderChecker,
)
from chainermn_tpu.analysis.checkers.hotpath import HostSyncChecker
from chainermn_tpu.analysis.checkers.recompile import RecompileChecker
from chainermn_tpu.analysis.checkers.imports import ImportHygieneChecker
from chainermn_tpu.analysis.checkers.names import ConsistencyChecker
from chainermn_tpu.analysis.checkers.blocking import BlockingUnderLockChecker
from chainermn_tpu.analysis.checkers.threads import ThreadLifecycleChecker


def all_checkers() -> list:
    """Fresh instances of every registered checker."""
    return [
        LockDisciplineChecker(),
        LockOrderChecker(),
        BlockingUnderLockChecker(),
        ThreadLifecycleChecker(),
        HostSyncChecker(),
        RecompileChecker(),
        ImportHygieneChecker(),
        ConsistencyChecker(),
    ]


__all__ = [
    "BlockingUnderLockChecker",
    "ConsistencyChecker",
    "HostSyncChecker",
    "ImportHygieneChecker",
    "LockDisciplineChecker",
    "LockOrderChecker",
    "RecompileChecker",
    "ThreadLifecycleChecker",
    "all_checkers",
]
