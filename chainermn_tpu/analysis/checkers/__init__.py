"""Checker registry: the five graftlint rules, in report order."""

from chainermn_tpu.analysis.checkers.locks import (
    LockDisciplineChecker,
    LockOrderChecker,
)
from chainermn_tpu.analysis.checkers.hotpath import HostSyncChecker
from chainermn_tpu.analysis.checkers.recompile import RecompileChecker
from chainermn_tpu.analysis.checkers.imports import ImportHygieneChecker
from chainermn_tpu.analysis.checkers.names import ConsistencyChecker


def all_checkers() -> list:
    """Fresh instances of every registered checker."""
    return [
        LockDisciplineChecker(),
        LockOrderChecker(),
        HostSyncChecker(),
        RecompileChecker(),
        ImportHygieneChecker(),
        ConsistencyChecker(),
    ]


__all__ = [
    "ConsistencyChecker",
    "HostSyncChecker",
    "ImportHygieneChecker",
    "LockDisciplineChecker",
    "LockOrderChecker",
    "RecompileChecker",
    "all_checkers",
]
