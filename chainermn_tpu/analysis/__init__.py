"""graftlint — AST-based repo-invariant analysis.

The distributed-training thesis this repo reproduces is *discipline*:
every rank doing the same thing in the same order. By PR 10 the tree has
ten threaded subsystems (scheduler, fleet replica supervisors, prefetcher,
async checkpoint writer, watchdogs, the HTTP server) whose invariants —
lock coverage, zero recompiles after warmup, no host syncs in hot loops,
lazy-import hygiene, cut-point/metric naming — were enforced only by
runtime tests that must happen to exercise the bad interleaving. This
package proves them *by construction* instead: a stdlib-``ast`` pass over
the whole tree, on every PR, with no imports of the code under analysis
(and no jax/numpy — the analyzer itself stays a pure host-logic import,
pinned by ``tests/monitor_tests/test_import_hygiene.py``).

The checkers ride one shared visitor framework (:mod:`.core`):

``lock-discipline``
    For classes owning a ``threading.Lock/RLock/Condition``, infer which
    ``self._*`` attributes are ever touched under ``with self._lock`` and
    flag accesses to the same attribute outside it (escape hatch:
    ``# graftlint: unguarded-ok`` for single-writer / torn-read-tolerant
    reads).
``lock-order``
    Cross-class lock-acquisition graph (who calls whose locking methods
    while holding their own lock); cycles — the static shadow of an
    ABBA deadlock — and nested non-reentrant self-acquires fail the run.
``host-sync``
    ``jax.device_get`` / ``.block_until_ready()`` / ``float()/np.asarray``
    on compiled-program results inside known hot-loop bodies (decode
    step, admission, replica drive, resilient-fit step) unless routed
    through ``dataflow.device_fetch`` — every stray sync in the PERF.md
    dispatch-bound regime is a measurable TPOT hit.
``recompile-hazard``
    The static complement to ``RecompileGuard``: ``jax.jit`` evaluated
    inside loops/hot bodies, jit-then-call-in-one-expression, varying
    Python scalars (``len``/``.shape``/loop vars) at non-static argument
    positions, and traced-value branches inside jitted functions.
``blocking-under-lock``
    Blocking work inside lock-held regions (``time.sleep``, file/socket
    I/O, thread ``.join``, blocking queue ops, device fetches) — one
    call level expanded through local helpers and same-class methods;
    a lock held across a disk write serializes every other path
    through that lock behind the disk.
``thread-lifecycle``
    Every ``threading.Thread(...)`` is ``daemon=True`` or joined inside
    a stop/close/shutdown-named function — no thread outlives the
    intent of its owner.
``consistency`` / ``import-hygiene``
    Every fault cut-point and metric/event name must come from the
    central catalogs (``resilience/cutpoints.py``,
    ``monitor/catalog.py``), follow the naming convention, and be pinned
    by tests/docs; the static import graph enforces the lazy-import
    rules (monitor/fleet/deploy never reach extensions — fleet/deploy
    never reach jax/serving — at module level) that the subprocess
    hygiene test checks dynamically.

Run it: ``python -m chainermn_tpu.analysis chainermn_tpu/`` (human or
``--json`` output, exit-code gating, fingerprint ``--baseline`` file), or
in-process via :func:`run_analysis`. ``tests/analysis_tests/
test_repo_clean.py`` runs the full suite over the tree as a tier-1 test,
so the repo is lint-clean at merge.

The static model is cross-checked against real schedules by the
opt-in runtime concurrency sanitizer (:mod:`.sanitizer`): instrumented
locks build the *observed* lock-order graph (cycles and
static-graph-absent edges raise with both acquisition stacks),
``guarded()`` proxies enforce lock-discipline dynamically, and
``--runtime-report`` asserts observed ⊆ static off the tier-1
``SANITIZER.json`` artifact.
"""

from chainermn_tpu.analysis.core import (
    AnalysisResult,
    Checker,
    Finding,
    Module,
    Project,
    analyze_source,
    load_baseline,
    run_analysis,
)

__all__ = [
    "AnalysisResult",
    "Checker",
    "Finding",
    "Module",
    "Project",
    "analyze_source",
    "load_baseline",
    "run_analysis",
]
