"""Multi-node iterators.

Re-design of ``[U] chainermn/iterators/multi_node_iterator.py`` and
``[U] chainermn/iterators/synchronized_iterator.py`` (SURVEY.md S2.13 —
unverified cites). The reference wraps Chainer's ``Iterator`` protocol; the
rebuild carries a minimal protocol of its own (no host framework to lean on):

- an *iterator* yields batches via ``__next__`` and exposes ``epoch``,
  ``epoch_detail``, ``is_new_epoch``, ``reset()``, and
  ``state_dict()/load_state_dict()`` (the checkpointer's serialization hook —
  the reference uses Chainer serializers for this).

:class:`SerialIterator` is the in-package reference implementation (the
analog of ``chainer.iterators.SerialIterator``, which the reference assumes
from its host framework).

``create_multi_node_iterator`` — the master process runs the real iterator
and broadcasts every batch over the host-side object channel; the other
processes run a stub that receives. For dataset sources that cannot be
scattered (stateful readers, streams) — SURVEY.md S2.13.

``create_synchronized_iterator`` — every process keeps its own iterator but
their shuffle RNGs are forced into lockstep (root's seed is broadcast), so
all ranks draw the same order. Cheaper than broadcasting batches when the
data itself is visible everywhere.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from chainermn_tpu.communicators.communicator_base import CommunicatorBase


class SerialIterator:
    """Minimal epoch-aware batch iterator over an indexable dataset.

    Batches are lists of dataset records (examples collate to arrays at the
    device_put boundary, not here). With ``repeat=False`` iteration raises
    ``StopIteration`` at epoch end, after flushing a final short batch.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        repeat: bool = True,
        shuffle: bool = False,
        seed: Optional[int] = None,
    ) -> None:
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self._repeat = bool(repeat)
        self._shuffle = bool(shuffle)
        self._seed = seed
        self.reset()

    # -- protocol ------------------------------------------------------- #

    def __iter__(self):
        return self

    def __next__(self) -> list:
        n = len(self.dataset)
        if n == 0 or self._exhausted:
            raise StopIteration
        if self._cursor >= n:
            self._order = self._draw_order()
            self._cursor = 0
        begin = self._cursor
        end = min(begin + self.batch_size, n)
        batch = [self.dataset[int(self._order[i])] for i in range(begin, end)]
        self._cursor = end
        self._consumed += end - begin
        if end >= n:
            self.epoch += 1
            self.is_new_epoch = True
            if not self._repeat:
                self._exhausted = True
        else:
            self.is_new_epoch = False
        return batch

    next = __next__

    @property
    def epoch_detail(self) -> float:
        return self._consumed / max(1, len(self.dataset))

    def reset(self) -> None:
        self._rng = np.random.RandomState(self._seed)
        self.epoch = 0
        self.is_new_epoch = False
        self._exhausted = False
        self._consumed = 0
        self._order = self._draw_order()
        self._cursor = 0

    def reseed(self, seed: int) -> None:
        """Replace the shuffle RNG (synchronized_iterator hook)."""
        self._seed = int(seed)
        self._rng = np.random.RandomState(self._seed)
        self._order = self._draw_order()

    # -- checkpointing --------------------------------------------------- #

    def state_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "cursor": self._cursor,
            "consumed": self._consumed,
            "order": np.asarray(self._order).tolist(),
            "rng": self._rng.get_state(),
            "exhausted": self._exhausted,
            "is_new_epoch": self.is_new_epoch,
        }

    def load_state_dict(self, state: dict) -> None:
        self.epoch = int(state["epoch"])
        self._cursor = int(state["cursor"])
        self._consumed = int(state["consumed"])
        self._order = np.asarray(state["order"], dtype=np.int64)
        self._rng.set_state(state["rng"])
        self._exhausted = bool(state["exhausted"])
        # a snapshot taken exactly at an epoch boundary must restore the
        # boundary flag too (epoch-cadenced callers key off it); absent in
        # pre-PR4 snapshots -> False, matching mid-epoch behavior
        self.is_new_epoch = bool(state.get("is_new_epoch", False))

    # -- internals ------------------------------------------------------- #

    def _draw_order(self) -> np.ndarray:
        n = len(self.dataset)
        if self._shuffle:
            return self._rng.permutation(n)
        return np.arange(n, dtype=np.int64)


_STOP = "__chainermn_tpu_iterator_stop__"


class _MultiNodeIteratorMaster:
    def __init__(self, actual_iterator, comm: CommunicatorBase, rank_master: int) -> None:
        self._it = actual_iterator
        self._comm = comm
        self._rank_master = rank_master
        self.epoch = getattr(actual_iterator, "epoch", 0)
        self.epoch_detail = getattr(actual_iterator, "epoch_detail", 0.0)
        self.is_new_epoch = getattr(actual_iterator, "is_new_epoch", False)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch = next(self._it)
        except StopIteration:
            self._comm.bcast_obj(
                (_STOP, None, None, None), root=self._rank_master
            )
            raise
        payload = (
            batch,
            getattr(self._it, "epoch", 0),
            getattr(self._it, "epoch_detail", 0.0),
            getattr(self._it, "is_new_epoch", False),
        )
        self._comm.bcast_obj(payload, root=self._rank_master)
        self.epoch, self.epoch_detail, self.is_new_epoch = payload[1:]
        return batch

    next = __next__

    def reset(self) -> None:
        if hasattr(self._it, "reset"):
            self._it.reset()

    def state_dict(self) -> dict:
        return self._it.state_dict() if hasattr(self._it, "state_dict") else {}

    def load_state_dict(self, state: dict) -> None:
        if hasattr(self._it, "load_state_dict"):
            self._it.load_state_dict(state)


class _MultiNodeIteratorSlave:
    def __init__(self, comm: CommunicatorBase, rank_master: int) -> None:
        self._comm = comm
        self._rank_master = rank_master
        self.epoch = 0
        self.epoch_detail = 0.0
        self.is_new_epoch = False

    def __iter__(self):
        return self

    def __next__(self):
        payload = self._comm.bcast_obj(None, root=self._rank_master)
        if payload[0] == _STOP:
            raise StopIteration
        batch, self.epoch, self.epoch_detail, self.is_new_epoch = payload
        return batch

    next = __next__

    def reset(self) -> None:
        pass

    def state_dict(self) -> dict:
        return {}

    def load_state_dict(self, state: dict) -> None:
        pass


def create_multi_node_iterator(
    actual_iterator, communicator: CommunicatorBase, rank_master: int = 0
):
    """Reference ``create_multi_node_iterator``: rank ``rank_master`` drives
    the real iterator and broadcasts each batch; every other process gets a
    stub that receives. Pass the real iterator on the master and ``None``
    elsewhere (passing it everywhere also works — non-masters ignore it)."""
    if communicator.rank == rank_master:
        if actual_iterator is None:
            raise ValueError("master rank must supply the actual iterator")
        return _MultiNodeIteratorMaster(actual_iterator, communicator, rank_master)
    return _MultiNodeIteratorSlave(communicator, rank_master)


def create_synchronized_iterator(
    actual_iterator, communicator: CommunicatorBase, seed: Optional[int] = None
):
    """Reference ``create_synchronized_iterator``: force all ranks' shuffle
    RNGs into lockstep so every process draws the same order. Root draws a
    fresh seed (or uses ``seed`` — handy when emulating ranks within one
    process) and broadcasts it; iterators exposing ``reseed`` (ours) or a
    ``_rng`` attribute are re-seeded in place."""
    if communicator.rank == 0 and seed is None:
        seed = int(np.random.randint(0, 2**31 - 1))
    seed = communicator.bcast_obj(seed, root=0)
    if hasattr(actual_iterator, "reseed"):
        actual_iterator.reseed(seed)
    elif hasattr(actual_iterator, "_rng"):
        actual_iterator._rng = np.random.RandomState(seed)
        if hasattr(actual_iterator, "reset"):
            actual_iterator.reset()
    else:
        raise TypeError(
            "iterator has no reseed()/_rng hook to synchronize; wrap a "
            "SerialIterator or add a reseed(seed) method"
        )
    return actual_iterator


__all__ = [
    "SerialIterator",
    "create_multi_node_iterator",
    "create_synchronized_iterator",
]
