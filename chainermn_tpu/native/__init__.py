"""Native (C++) runtime components.

The reference's native surface is NCCL bindings + CUDA pack kernels
(SURVEY.md S2.9); on TPU, XLA owns the device side, so the native layer here
is host-side: the :mod:`objstore` TCP object-transport sidecar (DCN control
plane) and the :mod:`dataloader` batch-assembly/prefetch loader (input
pipeline — the reference's MultiprocessIterator slot). Everything degrades
gracefully to pure-Python paths when the toolchain is unavailable.
"""
