"""JPEG directory input pipeline (ctypes over jpeg_loader.cc).

Closes the round-4 input-pipeline gap: the reference's ImageNet example
decodes JPEGs in MultiprocessIterator worker processes (``[U]``
examples/imagenet/train_imagenet.py, SURVEY.md S2.15 — unverified cite);
the rebuild previously fed pre-decoded arrays only. This module adds the
decode story the TPU-native way:

- **decode + resize + normalize in C++** (``dl_decode_jpegs``): libjpeg
  with DCT scaling (decode work drops ~4x per halving), bilinear resize
  (half-pixel centers), fused ``(x/255 - mean) / std`` — multithreaded,
  GIL released for the whole batch;
- **prefetch depth >= 2** on a producer thread: file reads + decodes for
  the next batches overlap the training step;
- **PIL fallback** when libjpeg/g++ is unavailable: PIL decodes (itself
  libjpeg-based, with ``draft`` mirroring the DCT prescale), then a numpy
  bilinear that mirrors the C++ formula exactly.

``JpegDirectoryLoader`` reads an ImageFolder-style tree
(``root/<class_name>/*.jpg``, classes sorted lexicographically).
"""

from __future__ import annotations

import ctypes
import os
import queue
import threading
from typing import Optional, Sequence

import numpy as np

from chainermn_tpu.native.dataloader import IMAGENET_MEAN, IMAGENET_STD

_lib = None
_lib_error: Optional[str] = None

_EXTS = (".jpg", ".jpeg", ".JPG", ".JPEG")


def _load():
    global _lib, _lib_error
    if _lib is not None:
        return _lib
    if _lib_error is not None:
        raise RuntimeError(f"jpeg library unavailable: {_lib_error}")
    try:
        from chainermn_tpu.native._build import build_and_load

        lib = build_and_load("jpeg_loader.cc", "jpeg_loader",
                             extra_flags=("-ljpeg",))
    except Exception as e:
        _lib_error = f"{type(e).__name__}: {e}"
        raise RuntimeError(f"jpeg library unavailable: {_lib_error}")
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.dl_decode_jpegs.argtypes = [u8p, u64p, u64p, ctypes.c_uint64,
                                    ctypes.c_uint64, ctypes.c_uint64,
                                    f32p, f32p, f32p, ctypes.c_int]
    lib.dl_decode_jpegs.restype = ctypes.c_int
    _lib = lib
    return lib


def native_available() -> bool:
    try:
        _load()
        return True
    except Exception:
        return False


def _resize_normalize_np(img_u8: np.ndarray, oh: int, ow: int,
                         mean: np.ndarray, stdinv: np.ndarray) -> np.ndarray:
    """Numpy mirror of jpeg_loader.cc's resize_normalize (bilinear,
    half-pixel centers, clamped edges) — pinned against the C++ by
    ``test_resize_matches_native``."""
    sh, sw = img_u8.shape[:2]
    fy = np.clip((np.arange(oh) + 0.5) * (sh / oh) - 0.5, 0, sh - 1)
    fx = np.clip((np.arange(ow) + 0.5) * (sw / ow) - 0.5, 0, sw - 1)
    y0 = fy.astype(np.int64)
    x0 = fx.astype(np.int64)
    y1 = np.minimum(y0 + 1, sh - 1)
    x1 = np.minimum(x0 + 1, sw - 1)
    wy = (fy - y0).astype(np.float32)[:, None, None]
    wx = (fx - x0).astype(np.float32)[None, :, None]
    img = img_u8.astype(np.float32)
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    v = (top * (1 - wy) + bot * wy) / 255.0
    return (v - mean) * stdinv


def decode_jpeg_batch(blobs: Sequence[bytes], image_size: int,
                      *, mean=IMAGENET_MEAN, std=IMAGENET_STD,
                      n_threads: Optional[int] = None,
                      force_fallback: bool = False):
    """Decode a batch of JPEG byte strings to a normalized float32 array
    ``[B, image_size, image_size, 3]``. Returns ``(batch, n_failed)``;
    failed decodes are zero rows (training shrugs off a corrupt file
    instead of crashing an epoch in)."""
    meanf = np.asarray(mean, np.float32)
    stdinvf = (1.0 / np.asarray(std, np.float32)).astype(np.float32)
    n = len(blobs)
    out = np.empty((n, image_size, image_size, 3), np.float32)
    if not force_fallback and native_available():
        blob = np.frombuffer(b"".join(blobs), np.uint8)
        sizes = np.asarray([len(b) for b in blobs], np.uint64)
        offsets = np.zeros(n, np.uint64)
        np.cumsum(sizes[:-1], out=offsets[1:])
        lib = _load()
        nfail = lib.dl_decode_jpegs(
            blob.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            n, image_size, image_size,
            meanf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            stdinvf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n_threads or min(8, os.cpu_count() or 1),
        )
        return out, int(nfail)
    # PIL fallback: decode (PIL is libjpeg-based; draft applies the same
    # DCT prescale the native path uses), then the mirrored numpy resize
    from PIL import Image
    import io

    nfail = 0
    for i, b in enumerate(blobs):
        try:
            img = Image.open(io.BytesIO(b))
            img.draft("RGB", (image_size, image_size))
            arr = np.asarray(img.convert("RGB"), np.uint8)
            out[i] = _resize_normalize_np(arr, image_size, image_size,
                                          meanf, stdinvf)
        except Exception:
            out[i] = 0.0
            nfail += 1
    return out, nfail


def scan_image_directory(root: str):
    """ImageFolder-style scan: ``root/<class>/*.jpg`` -> (paths, labels,
    class_names), classes sorted lexicographically (the torchvision/
    reference-example convention)."""
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    if not classes:
        raise ValueError(f"no class subdirectories under {root!r}")
    paths, labels = [], []
    for ci, cname in enumerate(classes):
        cdir = os.path.join(root, cname)
        for f in sorted(os.listdir(cdir)):
            if f.endswith(_EXTS):
                paths.append(os.path.join(cdir, f))
                labels.append(ci)
    if not paths:
        raise ValueError(f"no JPEG files under {root!r}")
    return paths, np.asarray(labels, np.int32), classes


class JpegDirectoryLoader:
    """Iterate normalized float32 batches from a directory of JPEGs.

    ``rank``/``size`` shard the FILE LIST (each rank owns
    ``paths[rank::size]``) for data-parallel launches; the per-epoch
    shuffle is seeded identically everywhere so shards stay disjoint.
    A producer thread keeps ``prefetch_depth`` decoded batches ahead of
    the training loop (file read + native decode both release the GIL).
    Yields ``(images [B, S, S, 3] float32, labels [B] int32)``.
    """

    def __init__(self, root: str, batch_size: int, *, image_size: int = 224,
                 mean=IMAGENET_MEAN, std=IMAGENET_STD, shuffle: bool = True,
                 repeat: bool = True, seed: int = 0, rank: int = 0,
                 size: int = 1, n_threads: Optional[int] = None,
                 prefetch_depth: int = 2):
        paths, labels, self.class_names = scan_image_directory(root)
        self._paths = paths[rank::size]
        self._labels = labels[rank::size]
        if batch_size > len(self._paths):
            raise ValueError(
                f"batch_size {batch_size} > shard size {len(self._paths)} "
                f"(rank {rank}/{size}, {len(paths)} files total)"
            )
        self._batch = batch_size
        self._size = image_size
        self._mean, self._std = mean, std
        self._shuffle, self._repeat, self._seed = shuffle, repeat, seed
        self._n_threads = n_threads
        self._depth = max(1, prefetch_depth)
        self.epoch = 0
        self.is_new_epoch = False
        self.failed_decodes = 0

    def _index_batches(self):
        n = len(self._paths)
        epoch = 0
        while True:
            order = (np.random.RandomState(self._seed + epoch).permutation(n)
                     if self._shuffle else np.arange(n))
            n_full = n // self._batch
            for i in range(n_full):
                yield order[i * self._batch:(i + 1) * self._batch], \
                    i == n_full - 1
            epoch += 1
            if not self._repeat:
                return

    def _make_batch(self, sel: np.ndarray):
        blobs = []
        for j in sel:
            with open(self._paths[j], "rb") as f:
                blobs.append(f.read())
        imgs, nfail = decode_jpeg_batch(
            blobs, self._size, mean=self._mean, std=self._std,
            n_threads=self._n_threads)
        self.failed_decodes += nfail
        return imgs, self._labels[sel]

    def __iter__(self):
        q: queue.Queue = queue.Queue(maxsize=self._depth)
        stop = threading.Event()

        def producer():
            # any failure must reach the consumer: a dead producer with no
            # sentinel would hang the training loop on q.get() forever
            # (and strand every other rank in its next collective)
            try:
                for sel, last in self._index_batches():
                    if stop.is_set():
                        return
                    q.put((self._make_batch(sel), last))
                q.put(None)
            except BaseException as e:  # noqa: BLE001
                q.put(e)

        worker = threading.Thread(target=producer, daemon=True)
        worker.start()
        try:
            while True:
                item = q.get()
                if item is None:
                    return
                if isinstance(item, BaseException):
                    raise RuntimeError(
                        "JpegDirectoryLoader producer failed") from item
                batch, last = item
                self.is_new_epoch = last
                if last:
                    self.epoch += 1
                yield batch
        finally:
            stop.set()
            try:  # unblock a producer waiting on a full queue
                q.get_nowait()
            except queue.Empty:
                pass

    def __len__(self) -> int:
        return len(self._paths) // self._batch


__all__ = ["JpegDirectoryLoader", "decode_jpeg_batch",
           "scan_image_directory", "native_available"]


def _bench(n_imgs=64, src=256, tgt=224, n=5) -> None:
    """``python -m chainermn_tpu.native.jpeg``: native libjpeg vs PIL
    decode+resize+normalize on a JPEG batch (the input-pipeline analog of
    dataloader._bench's assembly comparison)."""
    import io
    import time

    from PIL import Image

    rs = np.random.RandomState(0)
    blobs = []
    for _ in range(n_imgs):
        arr = (np.kron(rs.rand(src // 8, src // 8, 3),
                       np.ones((8, 8, 1)))[:src, :src] * 255).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, "JPEG", quality=90)
        blobs.append(buf.getvalue())
    if not native_available():
        print(f"WARNING: native library unavailable ({_lib_error}); "
              "both rows below are the PIL fallback")
    for force_fallback in (False, True):
        decode_jpeg_batch(blobs[:2], tgt, force_fallback=force_fallback)  # warm
        t0 = time.perf_counter()
        for _ in range(n):
            _, nfail = decode_jpeg_batch(blobs, tgt,
                                         force_fallback=force_fallback)
            assert nfail == 0
        ms = (time.perf_counter() - t0) / n * 1e3
        label = ("PIL   " if force_fallback or not native_available()
                 else "native")
        print(f"{label}: {ms:6.1f} ms/batch of {n_imgs} "
              f"({n_imgs / ms * 1e3:.0f} img/s)")


if __name__ == "__main__":
    _bench()
