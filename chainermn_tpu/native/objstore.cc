// Native object store sidecar: host-side (DCN) object transport.
//
// TPU-native replacement for the byte-moving half of the reference's
// pickle-over-MPI object path ([U] chainermn/communicators/
// mpi_communicator_base.py — chunked raw sends after a typed header;
// SURVEY.md S2.2/S7 "hard part 3": obj-comm without MPI). One process (the
// store host, normally process 0) runs a TCP server holding a key->bytes
// map; every process connects as a client. Unlike the jax.distributed KV
// store (string values => base64, +33% bytes and extra copies), frames carry
// raw bytes end-to-end with a CRC32 integrity check per frame.
//
// Protocol (all integers little-endian):
//   request:  [op:u8][klen:u32][key][vlen:u64][value][crc:u32]
//             crc = CRC32(key || value)
//   response: [status:u8][vlen:u64][value][crc:u32]
//   ops: 1=PUT  2=GET(blocking; vlen carries timeout_ms as the "value")
//        3=DEL_PREFIX  4=DIR(list keys with prefix, '\n'-joined)  5=PING
//   status: 0=ok 1=timeout 2=bad-frame
//
// Concurrency: thread-per-connection (obj traffic is low-rate control
// plane; simplicity beats epoll here). GET parks on a condition variable
// until the key exists — the blocking-get semantics the object comm's
// sequencing layer expects.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---- CRC32 (IEEE 802.3 polynomial, table-driven) -------------------------
uint32_t kCrcTable[256];
struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      kCrcTable[i] = c;
    }
  }
} crc_init_once;

uint32_t Crc32(const uint8_t* data, size_t n, uint32_t crc = 0) {
  crc = ~crc;
  for (size_t i = 0; i < n; ++i)
    crc = kCrcTable[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

// ---- wire helpers --------------------------------------------------------
bool ReadN(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool WriteN(int fd, const void* buf, size_t n) {
  const auto* p = static_cast<const uint8_t*>(buf);
  while (n) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool SendResponse(int fd, uint8_t status, const std::string& value) {
  uint64_t vlen = value.size();
  uint32_t crc = Crc32(reinterpret_cast<const uint8_t*>(value.data()),
                       value.size());
  std::vector<uint8_t> hdr(1 + 8);
  hdr[0] = status;
  std::memcpy(&hdr[1], &vlen, 8);
  if (!WriteN(fd, hdr.data(), hdr.size())) return false;
  if (!value.empty() && !WriteN(fd, value.data(), value.size())) return false;
  return WriteN(fd, &crc, 4);
}

// ---- store ---------------------------------------------------------------
struct Store {
  std::map<std::string, std::string> kv;
  std::mutex m;
  std::condition_variable cv;
  int listen_fd = -1;
  uint16_t port = 0;
  std::thread accept_thread;
  std::vector<std::thread> conns;
  std::vector<int> conn_fds;  // so shutdown can unblock recv()-parked threads
  bool shutting_down = false;
};

// The listener is unauthenticated; any stray connection (port scan, health
// probe speaking HTTP) gets parsed as a frame header. Cap lengths BEFORE
// allocating so garbage headers can't trigger multi-GB allocations, and
// treat anything over the cap as an unrecoverable framing error (the stream
// can't be resynced, so the connection is dropped).
constexpr uint32_t kMaxKeyLen = 1u << 16;        // 64 KiB
constexpr uint64_t kMaxValueLen = 1ull << 31;    // 2 GiB

void ServeConnLoop(Store* s, int fd) {
  for (;;) {
    uint8_t op;
    uint32_t klen;
    uint64_t vlen;
    if (!ReadN(fd, &op, 1) || !ReadN(fd, &klen, 4)) break;
    if (klen > kMaxKeyLen) break;
    std::string key(klen, '\0');
    if (klen && !ReadN(fd, key.data(), klen)) break;
    if (!ReadN(fd, &vlen, 8)) break;
    if (vlen > kMaxValueLen) break;
    std::string value(vlen, '\0');
    if (vlen && !ReadN(fd, value.data(), vlen)) break;
    uint32_t crc;
    if (!ReadN(fd, &crc, 4)) break;
    uint32_t want = Crc32(reinterpret_cast<const uint8_t*>(key.data()),
                          key.size());
    want = Crc32(reinterpret_cast<const uint8_t*>(value.data()), value.size(),
                 want);
    if (crc != want) {
      SendResponse(fd, 2, "");
      continue;
    }
    switch (op) {
      case 1: {  // PUT
        {
          std::lock_guard<std::mutex> lk(s->m);
          s->kv[key] = std::move(value);
        }
        s->cv.notify_all();
        if (!SendResponse(fd, 0, "")) goto done;
        break;
      }
      case 2: {  // GET (blocking; value field = decimal timeout_ms)
        long timeout_ms = 600000;
        if (!value.empty()) {
          // strtol, not stol: non-numeric input from a stray connection must
          // not throw. Garbage keeps the default timeout.
          char* end = nullptr;
          errno = 0;
          long parsed = ::strtol(value.c_str(), &end, 10);
          if (errno == 0 && end && *end == '\0' && parsed >= 0)
            timeout_ms = parsed;
        }
        std::unique_lock<std::mutex> lk(s->m);
        bool ok = s->cv.wait_for(
            lk, std::chrono::milliseconds(timeout_ms), [&] {
              return s->shutting_down || s->kv.count(key) > 0;
            });
        std::string out;
        uint8_t status = 1;
        if (ok && !s->shutting_down) {
          out = s->kv[key];
          status = 0;
        }
        lk.unlock();
        if (!SendResponse(fd, status, out)) goto done;
        break;
      }
      case 3: {  // DEL_PREFIX
        {
          std::lock_guard<std::mutex> lk(s->m);
          auto it = s->kv.lower_bound(key);
          while (it != s->kv.end() && it->first.compare(0, key.size(), key) == 0)
            it = s->kv.erase(it);
        }
        if (!SendResponse(fd, 0, "")) goto done;
        break;
      }
      case 4: {  // DIR
        std::string out;
        {
          std::lock_guard<std::mutex> lk(s->m);
          auto it = s->kv.lower_bound(key);
          for (; it != s->kv.end() &&
                 it->first.compare(0, key.size(), key) == 0;
               ++it) {
            out += it->first;
            out += '\n';
          }
        }
        if (!SendResponse(fd, 0, out)) goto done;
        break;
      }
      case 5: {  // PING
        if (!SendResponse(fd, 0, "pong")) goto done;
        break;
      }
      default:
        SendResponse(fd, 2, "");
    }
  }
done:
  return;
}

void ServeConn(Store* s, int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  // A throwing handler (bad_alloc on a huge-but-under-cap value, etc.) must
  // kill only this connection, never the store-host process — every rank's
  // blocking gets hang if the store dies.
  try {
    ServeConnLoop(s, fd);
  } catch (...) {
  }
  // Drop our fd from the shutdown list before closing it: the number can be
  // recycled by the OS, and objstore_server_stop must not shutdown() an
  // unrelated live socket (e.g. a jax.distributed connection).
  {
    std::lock_guard<std::mutex> lk(s->m);
    for (auto it = s->conn_fds.begin(); it != s->conn_fds.end(); ++it) {
      if (*it == fd) {
        s->conn_fds.erase(it);
        break;
      }
    }
  }
  ::close(fd);
}

}  // namespace

extern "C" {

// Start a server on `port` (0 = ephemeral). Returns handle, or 0 on error.
// `out_port` receives the bound port.
void* objstore_server_start(uint16_t port, uint16_t* out_port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(port);
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 128) != 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  auto* s = new Store;
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  if (out_port) *out_port = s->port;
  s->accept_thread = std::thread([s] {
    for (;;) {
      int cfd = ::accept(s->listen_fd, nullptr, nullptr);
      if (cfd < 0) break;  // listen_fd closed => shutdown
      std::lock_guard<std::mutex> lk(s->m);
      if (s->shutting_down) {
        ::close(cfd);
        break;
      }
      s->conn_fds.push_back(cfd);
      s->conns.emplace_back(ServeConn, s, cfd);
    }
  });
  return s;
}

void objstore_server_stop(void* handle) {
  auto* s = static_cast<Store*>(handle);
  if (!s) return;
  {
    std::lock_guard<std::mutex> lk(s->m);
    s->shutting_down = true;
    for (int fd : s->conn_fds) ::shutdown(fd, SHUT_RDWR);
  }
  s->cv.notify_all();
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  for (auto& t : s->conns)
    if (t.joinable()) t.join();
  delete s;
}

// ---- client --------------------------------------------------------------

struct Client {
  int fd;
  std::mutex m;
};

void* objstore_client_connect(const char* host, uint16_t port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return nullptr;
  }
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return nullptr;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* c = new Client;
  c->fd = fd;
  return c;
}

namespace {
// Send one request and read the response. Returns status (<0 = transport
// error); on success *out/*out_len hold a malloc'd payload copy.
int Roundtrip(Client* c, uint8_t op, const char* key, uint32_t klen,
              const uint8_t* val, uint64_t vlen, uint8_t** out,
              uint64_t* out_len) {
  std::lock_guard<std::mutex> lk(c->m);
  uint32_t crc = Crc32(reinterpret_cast<const uint8_t*>(key), klen);
  crc = Crc32(val, vlen, crc);
  if (!WriteN(c->fd, &op, 1) || !WriteN(c->fd, &klen, 4) ||
      (klen && !WriteN(c->fd, key, klen)) || !WriteN(c->fd, &vlen, 8) ||
      (vlen && !WriteN(c->fd, val, vlen)) || !WriteN(c->fd, &crc, 4))
    return -1;
  uint8_t status;
  uint64_t rlen;
  if (!ReadN(c->fd, &status, 1) || !ReadN(c->fd, &rlen, 8)) return -1;
  uint8_t* buf = nullptr;
  if (rlen) {
    buf = static_cast<uint8_t*>(::malloc(rlen));
    if (!buf || !ReadN(c->fd, buf, rlen)) {
      ::free(buf);
      return -1;
    }
  }
  uint32_t rcrc;
  if (!ReadN(c->fd, &rcrc, 4)) {
    ::free(buf);
    return -1;
  }
  if (rcrc != Crc32(buf, rlen)) {
    ::free(buf);
    return -2;  // corrupted response
  }
  if (out) {
    *out = buf;
    *out_len = rlen;
  } else {
    ::free(buf);
  }
  return status;
}
}  // namespace

int objstore_put(void* handle, const char* key, uint32_t klen,
                 const uint8_t* val, uint64_t vlen) {
  return Roundtrip(static_cast<Client*>(handle), 1, key, klen, val, vlen,
                   nullptr, nullptr);
}

// Blocking get; on status 0, *out is malloc'd (caller frees via
// objstore_free) and *out_len set.
int objstore_get(void* handle, const char* key, uint32_t klen,
                 long timeout_ms, uint8_t** out, uint64_t* out_len) {
  std::string t = std::to_string(timeout_ms);
  return Roundtrip(static_cast<Client*>(handle), 2, key, klen,
                   reinterpret_cast<const uint8_t*>(t.data()), t.size(), out,
                   out_len);
}

int objstore_del_prefix(void* handle, const char* key, uint32_t klen) {
  return Roundtrip(static_cast<Client*>(handle), 3, key, klen, nullptr, 0,
                   nullptr, nullptr);
}

// '\n'-joined key list with the given prefix (malloc'd; caller frees).
int objstore_dir(void* handle, const char* key, uint32_t klen, uint8_t** out,
                 uint64_t* out_len) {
  return Roundtrip(static_cast<Client*>(handle), 4, key, klen, nullptr, 0,
                   out, out_len);
}

int objstore_ping(void* handle) {
  uint8_t* out = nullptr;
  uint64_t n = 0;
  int st = Roundtrip(static_cast<Client*>(handle), 5, "", 0, nullptr, 0, &out,
                     &n);
  ::free(out);
  return st;
}

void objstore_client_close(void* handle) {
  auto* c = static_cast<Client*>(handle);
  if (!c) return;
  ::close(c->fd);
  delete c;
}

void objstore_free(uint8_t* buf) { ::free(buf); }

uint32_t objstore_crc32(const uint8_t* data, uint64_t n) {
  return Crc32(data, n);
}

}  // extern "C"
