// Native batch assembly for the input pipeline.
//
// The reference feeds its ImageNet example through Chainer's
// MultiprocessIterator (worker processes doing decode + batch assembly,
// SURVEY.md S2.15); the TPU rebuild's equivalent offloads the per-batch
// gather + uint8->float normalize to C++ threads with the GIL released
// (ctypes releases it around foreign calls), so the Python training loop
// only hands out indices and receives ready float batches. See
// dataloader.py for the prefetching iterator built on top.
//
// C ABI (all plain pointers; caller owns every buffer):
//   dl_gather_f32(base, rec_elems, channels, idx, n, mean, stdinv, out,
//                 n_threads)
//     out[i*rec_elems + e] = ((float)base[idx[i]*rec_elems + e] / 255.f
//                             - mean[e % channels]) * stdinv[e % channels]
//   dl_gather_u8(base, rec_elems, idx, n, out, n_threads)
//     raw record gather (no conversion).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

void gather_f32_range(const uint8_t* base, uint64_t rec_elems,
                      uint64_t channels, const int64_t* idx,
                      const float* mean, const float* stdinv, float* out,
                      uint64_t lo, uint64_t hi) {
  for (uint64_t i = lo; i < hi; ++i) {
    const uint8_t* src = base + (uint64_t)idx[i] * rec_elems;
    float* dst = out + i * rec_elems;
    for (uint64_t e = 0; e < rec_elems; ++e) {
      uint64_t c = e % channels;
      dst[e] = ((float)src[e] * (1.0f / 255.0f) - mean[c]) * stdinv[c];
    }
  }
}

void gather_u8_range(const uint8_t* base, uint64_t rec_elems,
                     const int64_t* idx, uint8_t* out, uint64_t lo,
                     uint64_t hi) {
  for (uint64_t i = lo; i < hi; ++i) {
    std::memcpy(out + i * rec_elems, base + (uint64_t)idx[i] * rec_elems,
                rec_elems);
  }
}

template <typename Fn>
void run_threaded(uint64_t n, int n_threads, Fn fn) {
  if (n_threads <= 1 || n < 2) {
    fn(0, n);
    return;
  }
  uint64_t nt = (uint64_t)n_threads < n ? (uint64_t)n_threads : n;
  std::vector<std::thread> ts;
  ts.reserve(nt);
  uint64_t chunk = (n + nt - 1) / nt;
  for (uint64_t t = 0; t < nt; ++t) {
    uint64_t lo = t * chunk;
    uint64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    ts.emplace_back([=] { fn(lo, hi); });
  }
  for (auto& th : ts) th.join();
}

}  // namespace

extern "C" {

void dl_gather_f32(const uint8_t* base, uint64_t rec_elems, uint64_t channels,
                   const int64_t* idx, uint64_t n, const float* mean,
                   const float* stdinv, float* out, int n_threads) {
  run_threaded(n, n_threads, [=](uint64_t lo, uint64_t hi) {
    gather_f32_range(base, rec_elems, channels, idx, mean, stdinv, out, lo,
                     hi);
  });
}

void dl_gather_u8(const uint8_t* base, uint64_t rec_elems, const int64_t* idx,
                  uint64_t n, uint8_t* out, int n_threads) {
  run_threaded(n, n_threads, [=](uint64_t lo, uint64_t hi) {
    gather_u8_range(base, rec_elems, idx, out, lo, hi);
  });
}

}  // extern "C"
