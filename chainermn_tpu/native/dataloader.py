"""Prefetching native batch loader (ctypes over dataloader.cc).

The reference's ImageNet example feeds data through Chainer's
MultiprocessIterator — worker processes doing decode + batch assembly
(``[U] examples/imagenet/train_imagenet.py``, SURVEY.md S2.15 — unverified
cite). The TPU rebuild's input path re-designs that as:

- **batch assembly in C++** (``dl_gather_f32``): gather the sampled records
  from a contiguous uint8 array and fuse the uint8 -> float32
  ``(x/255 - mean) / std`` normalize, multithreaded, GIL released for the
  whole call;
- **prefetch** on a Python producer thread (``prefetch_depth`` batches
  ahead, default 2): while the training step runs, the next batches are
  being assembled — the loop's input cost is max(0, assembly - step)
  instead of assembly + step. Abandoning iteration early stops AND joins
  the producer (no thread leak per epoch). Compose with
  :class:`chainermn_tpu.dataflow.DevicePrefetcher` to also move the H2D
  transfer off the critical path.

Falls back to a numpy implementation when the g++ toolchain is missing
(``native_available()`` tells you which path you got — same posture as the
objstore sidecar).
"""

from __future__ import annotations

import ctypes
import os
import queue
import threading
from typing import Optional, Sequence

import numpy as np

_lib = None
_lib_error: Optional[str] = None

# The ImageNet per-channel normalization the reference's example applies via
# a mean image; shared so every input path normalizes identically.
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


def _load():
    global _lib, _lib_error
    if _lib is not None:
        return _lib
    if _lib_error is not None:
        raise RuntimeError(f"dataloader library unavailable: {_lib_error}")
    try:
        from chainermn_tpu.native._build import build_and_load

        lib = build_and_load("dataloader.cc", "dataloader")
    except Exception as e:
        _lib_error = f"{type(e).__name__}: {e}"
        raise RuntimeError(f"dataloader library unavailable: {_lib_error}")
    u8p = ctypes.POINTER(ctypes.c_uint8)
    f32p = ctypes.POINTER(ctypes.c_float)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.dl_gather_f32.argtypes = [u8p, ctypes.c_uint64, ctypes.c_uint64,
                                  i64p, ctypes.c_uint64, f32p, f32p, f32p,
                                  ctypes.c_int]
    lib.dl_gather_u8.argtypes = [u8p, ctypes.c_uint64, i64p,
                                 ctypes.c_uint64, u8p, ctypes.c_int]
    _lib = lib
    return lib


def native_available() -> bool:
    try:
        _load()
        return True
    except Exception:
        return False


class NativeBatchLoader:
    """Iterate normalized float32 batches over ``(images_u8, labels)``.

    ``images_u8``: contiguous ``[N, ...]`` uint8 array whose trailing axis is
    channels (NHWC); ``labels``: per-SAMPLE ints. ``rows`` (optional) maps
    each sample to its row in ``images_u8`` — samples may alias base rows
    (e.g. a small synthetic pool) or be a shard's subset, with no copy of
    the base array. Yields ``(batch_f32 [B, ...], labels [B])`` forever
    (``repeat=True``) or for one epoch. Shuffles with a per-epoch seeded
    permutation — every process of an SPMD launch constructs the same
    order, matching the synchronized-iterator posture of the host
    framework.
    """

    def __init__(
        self,
        images_u8: np.ndarray,
        labels: Sequence[int],
        batch_size: int,
        *,
        rows: Optional[Sequence[int]] = None,
        mean: Sequence[float] = IMAGENET_MEAN,
        std: Sequence[float] = IMAGENET_STD,
        shuffle: bool = True,
        repeat: bool = True,
        seed: int = 0,
        n_threads: Optional[int] = None,
        prefetch: bool = True,
        prefetch_depth: int = 2,
    ) -> None:
        self._x = np.ascontiguousarray(images_u8)
        if self._x.dtype != np.uint8:
            raise TypeError(f"images must be uint8, got {self._x.dtype}")
        self._y = np.asarray(labels, np.int32)
        self._rows = (np.arange(len(self._x), dtype=np.int64) if rows is None
                      else np.asarray(rows, np.int64))
        if len(self._rows) != len(self._y):
            raise ValueError(f"{len(self._rows)} rows vs {len(self._y)} labels")
        if len(self._rows) and (self._rows.min() < 0
                                or self._rows.max() >= len(self._x)):
            raise ValueError(
                f"rows reference [{self._rows.min()}, {self._rows.max()}] "
                f"outside the base array's {len(self._x)} rows"
            )
        if batch_size > len(self._rows):
            raise ValueError(
                f"batch_size {batch_size} > dataset size {len(self._rows)}"
            )
        self._batch = batch_size
        self._channels = int(self._x.shape[-1])
        self._rec_elems = int(np.prod(self._x.shape[1:]))
        self._mean = np.asarray(mean, np.float32)
        self._stdinv = (1.0 / np.asarray(std, np.float32)).astype(np.float32)
        if len(self._mean) != self._channels or len(self._stdinv) != self._channels:
            raise ValueError(
                f"{len(self._mean)} mean / {len(self._stdinv)} std values "
                f"for {self._channels} channels"
            )
        self._shuffle = shuffle
        self._repeat = repeat
        self._seed = seed
        self._n_threads = n_threads or min(8, os.cpu_count() or 1)
        self._native = native_available()
        self._prefetch = prefetch
        if prefetch_depth < 1:
            raise ValueError(
                f"prefetch_depth must be >= 1, got {prefetch_depth}")
        self._prefetch_depth = int(prefetch_depth)
        self._producers: list[threading.Thread] = []
        self.epoch = 0
        self.is_new_epoch = False

    # -- batch assembly ------------------------------------------------- #

    def _assemble(self, row_idx: np.ndarray) -> np.ndarray:
        """Gather base rows -> normalized float32 images."""
        from chainermn_tpu.resilience.cutpoints import DATALOADER_ASSEMBLE
        from chainermn_tpu.resilience.faults import inject

        inject(DATALOADER_ASSEMBLE, batch=len(row_idx))
        out = np.empty((len(row_idx),) + self._x.shape[1:], np.float32)
        if self._native:
            lib = _load()
            idx64 = np.ascontiguousarray(row_idx, np.int64)
            lib.dl_gather_f32(
                self._x.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                self._rec_elems, self._channels,
                idx64.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                len(idx64),
                self._mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                self._stdinv.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                self._n_threads,
            )
        else:  # pure-python fallback: same math
            gathered = self._x[row_idx].astype(np.float32) / 255.0
            out[:] = (gathered - self._mean) * self._stdinv
        return out

    # -- iteration with one-batch-ahead prefetch ------------------------ #

    def _index_batches(self):
        n = len(self._rows)
        epoch = 0
        while True:
            order = (np.random.RandomState(self._seed + epoch).permutation(n)
                     if self._shuffle else np.arange(n))
            n_full = n // self._batch
            for i in range(n_full):
                last = i == n_full - 1
                sel = order[i * self._batch:(i + 1) * self._batch]
                yield sel, last
            epoch += 1
            if not self._repeat:
                return

    def __iter__(self):
        if not self._prefetch:
            for sel, last in self._index_batches():
                self.is_new_epoch = last
                if last:
                    self.epoch += 1
                yield self._assemble_sel(sel)
            return
        # per-iterator state: multiple live iterators (or a closed earlier
        # one) must not stop each other's producer
        q: queue.Queue = queue.Queue(maxsize=self._prefetch_depth)
        stop = threading.Event()

        def offer(item) -> bool:
            # a bounded put that close() can always interrupt — a producer
            # parked in a plain q.put() would outlive abandoned iteration
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            for sel, last in self._index_batches():
                if stop.is_set():
                    return
                if not offer((self._assemble_sel(sel), last)):
                    return
            offer(None)

        worker = threading.Thread(target=producer, daemon=True)
        self._producers = [t for t in self._producers if t.is_alive()]
        self._producers.append(worker)
        worker.start()
        try:
            while True:
                item = q.get()
                if item is None:
                    return
                batch, last = item
                self.is_new_epoch = last
                if last:
                    self.epoch += 1
                yield batch
        finally:
            # abandoned-early or exhausted: stop, drain (unblocks a full-
            # queue put), and JOIN — no daemon-thread leak per epoch
            stop.set()
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            worker.join(timeout=5.0)

    def _assemble_sel(self, sel: np.ndarray):
        """Sample positions -> (normalized images, labels)."""
        return self._assemble(self._rows[sel]), self._y[sel]

    def __len__(self) -> int:
        return len(self._rows) // self._batch


__all__ = ["NativeBatchLoader", "native_available",
           "IMAGENET_MEAN", "IMAGENET_STD"]


def _bench(batch=128, size=224, n=20) -> None:
    """`python -m chainermn_tpu.native.dataloader`: native vs numpy batch
    assembly on an ImageNet-shaped batch."""
    import time

    rng = np.random.RandomState(0)
    x = rng.randint(0, 256, (max(512, batch), size, size, 3), np.uint8)
    y = rng.randint(0, 1000, len(x)).astype(np.int32)
    if not native_available():
        print(f"WARNING: native library unavailable ({_lib_error}); "
              "both rows below are the numpy fallback")
    for native in (True, False):
        loader = NativeBatchLoader(x, y, batch, prefetch=False, shuffle=True)
        loader._native = native and native_available()
        it = iter(loader)
        next(it)  # warm (build/load the library)
        t0 = time.perf_counter()
        for _ in range(n):
            next(it)
        ms = (time.perf_counter() - t0) / n * 1e3
        label = "native" if loader._native else "numpy "
        print(f"{label}: {ms:6.1f} ms/batch "
              f"({batch * size * size * 3 / ms / 1e6:.2f} GB/s)")


if __name__ == "__main__":
    _bench()
