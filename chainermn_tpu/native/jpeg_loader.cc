// Native JPEG decode for the input pipeline (libjpeg + fused resize +
// normalize), closing the round-4 gap: the reference's ImageNet example
// decodes JPEGs in MultiprocessIterator workers (SURVEY.md S2.15); the
// rebuild's native loader previously only assembled pre-decoded arrays.
//
// Per image, on a C++ thread with the GIL released (ctypes):
//   1. libjpeg decompress with DCT scaling (largest 1/2^k reduction that
//      keeps both dims >= target — decode work scales down ~4x per step);
//   2. bilinear resize (half-pixel centers) to (out_h, out_w);
//   3. fused uint8 -> float32 (x/255 - mean[c]) * stdinv[c] normalize.
//
// C ABI:
//   int dl_decode_jpegs(blob, offsets, sizes, n, out_h, out_w, mean,
//                       stdinv, out, n_threads)
//     blob: concatenated JPEG byte streams; image i is
//       blob[offsets[i] .. offsets[i]+sizes[i]).
//     out: [n, out_h, out_w, 3] float32. Returns the number of images
//     that FAILED to decode (their rows are zeroed); 0 = all good.

#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <jpeglib.h>

namespace {

struct ErrMgr {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

void err_longjmp(j_common_ptr cinfo) {
  ErrMgr* m = reinterpret_cast<ErrMgr*>(cinfo->err);
  longjmp(m->jb, 1);
}

// Decode one JPEG to tightly-packed RGB u8; returns false on any decode
// error (the default libjpeg handler would exit() the process).
bool decode_one(const uint8_t* data, uint64_t size, uint64_t tgt_h,
                uint64_t tgt_w, std::vector<uint8_t>& pix, uint64_t* w,
                uint64_t* h) {
  jpeg_decompress_struct cinfo;
  ErrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = err_longjmp;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, size);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;  // grayscale/CMYK -> RGB
  cinfo.scale_num = 1;
  cinfo.scale_denom = 1;
  for (unsigned d = 2; d <= 8; d *= 2) {
    if (cinfo.image_width / d >= tgt_w && cinfo.image_height / d >= tgt_h) {
      cinfo.scale_denom = d;
    } else {
      break;
    }
  }
  jpeg_start_decompress(&cinfo);
  if (cinfo.output_components != 3) {  // should not happen after JCS_RGB
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  *w = cinfo.output_width;
  *h = cinfo.output_height;
  pix.resize(*w * *h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = pix.data() + uint64_t(cinfo.output_scanline) * *w * 3;
    JSAMPROW rows[1] = {row};
    jpeg_read_scanlines(&cinfo, rows, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// Bilinear resize (half-pixel centers, edges clamped) + fused normalize.
// The numpy fallback in jpeg.py mirrors this formula exactly.
void resize_normalize(const uint8_t* src, uint64_t sw, uint64_t sh,
                      uint64_t ow, uint64_t oh, const float* mean,
                      const float* stdinv, float* dst) {
  const float sx = float(sw) / float(ow);
  const float sy = float(sh) / float(oh);
  for (uint64_t y = 0; y < oh; ++y) {
    float fy = (float(y) + 0.5f) * sy - 0.5f;
    if (fy < 0.f) fy = 0.f;
    if (fy > float(sh - 1)) fy = float(sh - 1);
    const uint64_t y0 = uint64_t(fy);
    const uint64_t y1 = y0 + 1 < sh ? y0 + 1 : sh - 1;
    const float wy = fy - float(y0);
    for (uint64_t x = 0; x < ow; ++x) {
      float fx = (float(x) + 0.5f) * sx - 0.5f;
      if (fx < 0.f) fx = 0.f;
      if (fx > float(sw - 1)) fx = float(sw - 1);
      const uint64_t x0 = uint64_t(fx);
      const uint64_t x1 = x0 + 1 < sw ? x0 + 1 : sw - 1;
      const float wx = fx - float(x0);
      const uint8_t* p00 = src + (y0 * sw + x0) * 3;
      const uint8_t* p01 = src + (y0 * sw + x1) * 3;
      const uint8_t* p10 = src + (y1 * sw + x0) * 3;
      const uint8_t* p11 = src + (y1 * sw + x1) * 3;
      float* o = dst + (y * ow + x) * 3;
      for (int c = 0; c < 3; ++c) {
        const float top = float(p00[c]) * (1.f - wx) + float(p01[c]) * wx;
        const float bot = float(p10[c]) * (1.f - wx) + float(p11[c]) * wx;
        const float v = (top * (1.f - wy) + bot * wy) * (1.0f / 255.0f);
        o[c] = (v - mean[c]) * stdinv[c];
      }
    }
  }
}

}  // namespace

extern "C" {

int dl_decode_jpegs(const uint8_t* blob, const uint64_t* offsets,
                    const uint64_t* sizes, uint64_t n, uint64_t out_h,
                    uint64_t out_w, const float* mean, const float* stdinv,
                    float* out, int n_threads) {
  const uint64_t rec = out_h * out_w * 3;
  std::vector<int> failed(n, 0);
  auto work = [&](uint64_t lo, uint64_t hi) {
    std::vector<uint8_t> pix;  // reused decode buffer per thread
    for (uint64_t i = lo; i < hi; ++i) {
      uint64_t w = 0, h = 0;
      if (decode_one(blob + offsets[i], sizes[i], out_h, out_w, pix, &w,
                     &h)) {
        resize_normalize(pix.data(), w, h, out_w, out_h, mean, stdinv,
                         out + i * rec);
      } else {
        std::memset(out + i * rec, 0, rec * sizeof(float));
        failed[i] = 1;
      }
    }
  };
  if (n_threads <= 1 || n < 2) {
    work(0, n);
  } else {
    uint64_t nt = uint64_t(n_threads) < n ? uint64_t(n_threads) : n;
    std::vector<std::thread> ts;
    ts.reserve(nt);
    const uint64_t chunk = (n + nt - 1) / nt;
    for (uint64_t t = 0; t < nt; ++t) {
      const uint64_t lo = t * chunk;
      const uint64_t hi = lo + chunk < n ? lo + chunk : n;
      if (lo >= hi) break;
      ts.emplace_back([&work, lo, hi] { work(lo, hi); });
    }
    for (auto& th : ts) th.join();
  }
  int nfail = 0;
  for (uint64_t i = 0; i < n; ++i) nfail += failed[i];
  return nfail;
}

}  // extern "C"
