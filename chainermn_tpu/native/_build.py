"""Shared build-on-demand machinery for the native (C++) components.

Each component is a single .cc compiled with the system g++ into a cached
.so next to the source (no pybind11 — C ABI + ctypes keeps the binding
dependency-free). Builds are serialized with an flock so concurrent
processes don't race the compiler; a stale .so (older than its source) is
rebuilt. Callers catch the RuntimeError and fall back to pure Python.
"""

from __future__ import annotations

import ctypes
import fcntl
import os
import subprocess
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))


def build_and_load(src_basename: str, stem: str,
                   extra_flags: tuple = ()) -> ctypes.CDLL:
    """Compile ``<native>/<src_basename>`` (if needed) and dlopen it.
    ``extra_flags`` append to the g++ line (e.g. ``("-ljpeg",)``)."""
    src = os.path.join(_DIR, src_basename)
    lib_path = os.path.join(
        _DIR, f"_{stem}_py{sys.version_info[0]}{sys.version_info[1]}.so"
    )
    if not (os.path.exists(lib_path)
            and os.path.getmtime(lib_path) >= os.path.getmtime(src)):
        lock_path = lib_path + ".lock"
        with open(lock_path, "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            if not (os.path.exists(lib_path)
                    and os.path.getmtime(lib_path) >= os.path.getmtime(src)):
                tmp = lib_path + ".tmp"
                subprocess.run(
                    ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                     "-pthread", src, "-o", tmp, *extra_flags],
                    check=True, capture_output=True, text=True,
                )
                os.replace(tmp, lib_path)
    return ctypes.CDLL(lib_path)
