"""ctypes bindings + object-comm adapter for the C++ objstore sidecar.

See ``objstore.cc`` for the wire protocol. The library is built on demand
with the system ``g++`` (pybind11 is not assumed; the C ABI + ctypes keeps
the binding dependency-free) and cached next to the source; builds are
serialized with an ``flock`` so concurrent processes don't race the
compiler. ``available()`` never raises — callers fall back to the
jax.distributed KV-store transport (``_object_comm.KVStoreObjectComm``).

Deployment contract (mirrors the reference's "mpiexec provides the world"):
the store host — normally process 0's launcher — runs ``serve()`` (or any
process calls ``ObjStoreServer()``), and every process gets
``CHAINERMN_TPU_OBJSTORE=host:port`` in its environment.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

from chainermn_tpu.communicators._object_comm import KVStoreObjectComm

_lib: Optional[ctypes.CDLL] = None
_lib_error: Optional[str] = None


def _load() -> ctypes.CDLL:
    global _lib, _lib_error
    if _lib is not None:
        return _lib
    if _lib_error is not None:
        raise RuntimeError(f"objstore library unavailable: {_lib_error}")
    try:
        from chainermn_tpu.native._build import build_and_load

        lib = build_and_load("objstore.cc", "objstore")
    except Exception as e:  # missing g++, sandboxed fs, ...
        _lib_error = f"{type(e).__name__}: {e}"
        raise RuntimeError(f"objstore library unavailable: {_lib_error}")
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.objstore_server_start.restype = ctypes.c_void_p
    lib.objstore_server_start.argtypes = [ctypes.c_uint16,
                                          ctypes.POINTER(ctypes.c_uint16)]
    lib.objstore_server_stop.argtypes = [ctypes.c_void_p]
    lib.objstore_client_connect.restype = ctypes.c_void_p
    lib.objstore_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_uint16]
    lib.objstore_client_close.argtypes = [ctypes.c_void_p]
    lib.objstore_put.restype = ctypes.c_int
    lib.objstore_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_uint32, u8p, ctypes.c_uint64]
    lib.objstore_get.restype = ctypes.c_int
    lib.objstore_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_uint32, ctypes.c_long,
                                 ctypes.POINTER(u8p),
                                 ctypes.POINTER(ctypes.c_uint64)]
    lib.objstore_del_prefix.restype = ctypes.c_int
    lib.objstore_del_prefix.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_uint32]
    lib.objstore_dir.restype = ctypes.c_int
    lib.objstore_dir.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_uint32, ctypes.POINTER(u8p),
                                 ctypes.POINTER(ctypes.c_uint64)]
    lib.objstore_ping.restype = ctypes.c_int
    lib.objstore_ping.argtypes = [ctypes.c_void_p]
    lib.objstore_free.argtypes = [u8p]
    lib.objstore_crc32.restype = ctypes.c_uint32
    lib.objstore_crc32.argtypes = [u8p, ctypes.c_uint64]
    _lib = lib
    return lib


def available() -> bool:
    """True when the sidecar can be used for this launch: the library builds
    (or is cached) AND ``CHAINERMN_TPU_OBJSTORE`` names the store host."""
    if "CHAINERMN_TPU_OBJSTORE" not in os.environ:
        return False
    try:
        _load()
        return True
    except Exception:
        return False


class ObjStoreServer:
    """Owns the in-process store + TCP acceptor (normally on process 0)."""

    def __init__(self, port: int = 0) -> None:
        lib = _load()
        out_port = ctypes.c_uint16(0)
        self._h = lib.objstore_server_start(port, ctypes.byref(out_port))
        if not self._h:
            raise RuntimeError(f"objstore server failed to bind port {port}")
        self.port = out_port.value

    def stop(self) -> None:
        if self._h:
            _load().objstore_server_stop(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


# Mirror objstore.cc's frame caps: the server drops a connection mid-stream
# on oversize frames (it cannot resync), so the client must refuse first
# with a diagnosable error.
MAX_KEY_LEN = 1 << 16
MAX_VALUE_LEN = 1 << 31


class ObjStoreClient:
    """One TCP connection to the store (thread-safe; the C side serializes
    roundtrips per connection).

    ``retry`` (a :class:`~chainermn_tpu.resilience.retry.RetryPolicy`, or
    None) wraps each put/get roundtrip — a dropped frame or an injected
    ``objstore.put``/``objstore.get`` fault is absorbed before the caller
    sees a failed transfer. The fault cut-points sit INSIDE the retried
    body, so injected transients exercise the retry path exactly like
    real ones."""

    def __init__(self, host: str, port: int, *, retry=None) -> None:
        lib = _load()
        self._lib = lib
        self.retry = retry
        self._h = lib.objstore_client_connect(host.encode(), port)
        if not self._h:
            raise RuntimeError(f"objstore connect failed: {host}:{port}")
        if lib.objstore_ping(self._h) != 0:
            raise RuntimeError(f"objstore ping failed: {host}:{port}")

    def put(self, key: str, value: bytes) -> None:
        kb = key.encode()
        if len(kb) > MAX_KEY_LEN or len(value) > MAX_VALUE_LEN:
            raise ValueError(
                f"objstore frame too large (key {len(kb)}B, value "
                f"{len(value)}B; caps {MAX_KEY_LEN}/{MAX_VALUE_LEN}) — "
                "chunk the payload (NativeObjectComm does this automatically)"
            )
        if self.retry is not None:
            return self.retry.call(self._put_once, kb, value,
                                   op="objstore.put")
        return self._put_once(kb, value)

    def _put_once(self, kb: bytes, value: bytes) -> None:
        from chainermn_tpu.resilience.cutpoints import OBJSTORE_PUT
        from chainermn_tpu.resilience.faults import inject

        inject(OBJSTORE_PUT, key=kb.decode(), nbytes=len(value))
        buf = (ctypes.c_uint8 * len(value)).from_buffer_copy(value) if value else None
        rc = self._lib.objstore_put(self._h, kb, len(kb), buf, len(value))
        if rc != 0:
            raise RuntimeError(f"objstore put({kb!r}) failed: rc={rc}")

    def get(self, key: str, timeout_ms: int = 600_000) -> bytes:
        kb = key.encode()
        if self.retry is not None:
            return self.retry.call(self._get_once, kb, timeout_ms,
                                   op="objstore.get")
        return self._get_once(kb, timeout_ms)

    def _get_once(self, kb: bytes, timeout_ms: int) -> bytes:
        from chainermn_tpu.resilience.cutpoints import OBJSTORE_GET
        from chainermn_tpu.resilience.faults import inject

        inject(OBJSTORE_GET, key=kb.decode())
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_uint64(0)
        rc = self._lib.objstore_get(self._h, kb, len(kb), timeout_ms,
                                    ctypes.byref(out), ctypes.byref(n))
        if rc == 1:
            raise TimeoutError(f"objstore get({kb!r}) timed out ({timeout_ms}ms)")
        if rc != 0:
            raise RuntimeError(f"objstore get({kb!r}) failed: rc={rc}")
        try:
            return ctypes.string_at(out, n.value) if n.value else b""
        finally:
            if n.value:
                self._lib.objstore_free(out)

    def delete_prefix(self, prefix: str) -> None:
        kb = prefix.encode()
        self._lib.objstore_del_prefix(self._h, kb, len(kb))

    def list_prefix(self, prefix: str) -> list[str]:
        kb = prefix.encode()
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_uint64(0)
        rc = self._lib.objstore_dir(self._h, kb, len(kb),
                                    ctypes.byref(out), ctypes.byref(n))
        if rc != 0:
            raise RuntimeError(f"objstore dir({prefix!r}) failed: rc={rc}")
        try:
            raw = ctypes.string_at(out, n.value) if n.value else b""
        finally:
            if n.value:
                self._lib.objstore_free(out)
        return [k for k in raw.decode().split("\n") if k]

    def close(self) -> None:
        if self._h:
            self._lib.objstore_client_close(self._h)
            self._h = None


def crc32(data: bytes) -> int:
    """The sidecar's CRC32 (exposed for checkpoint integrity stamps)."""
    lib = _load()
    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) if data else None
    return int(lib.objstore_crc32(buf, len(data)))


class NativeObjectComm(KVStoreObjectComm):
    """The object-comm interface over the native sidecar.

    Reuses the KV-store comm's sequencing + ack-GC protocol (the logic is
    transport-independent) with the raw-bytes TCP transport swapped in —
    no base64, CRC-checked frames. Payloads live under ``<key>/`` (chunked
    ``c<i>`` frames + ``hdr``) so the shared GC (which deletes the
    ``<key>/`` subtree) covers them.
    """

    def __init__(self, rank: Optional[int] = None, size: Optional[int] = None,
                 address: Optional[str] = None) -> None:
        import jax

        address = address or os.environ["CHAINERMN_TPU_OBJSTORE"]
        host, port = address.rsplit(":", 1)
        self._store = ObjStoreClient(host, int(port))
        self._init_protocol_state(
            jax.process_index() if rank is None else rank,
            jax.process_count() if size is None else size,
        )

    # Payloads above the wire-frame cap are split across numbered keys. The
    # tiny header frame is always written LAST, and readers block only on it
    # — its presence implies every data frame is already in the store.
    _CHUNK = 256 << 20

    def _put(self, key: str, payload: bytes) -> None:
        n = -(-len(payload) // self._CHUNK) if payload else 1
        for i in range(n):
            self._store.put(
                f"{key}/c{i}", payload[i * self._CHUNK : (i + 1) * self._CHUNK]
            )
        self._store.put(key + "/hdr", f"{len(payload)}:{n}".encode())

    def _get(self, key: str, timeout_ms: int = 600_000) -> bytes:
        hdr = self._store.get(key + "/hdr", timeout_ms)
        total, n = (int(v) for v in hdr.decode().split(":"))
        payload = b"".join(
            self._store.get(f"{key}/c{i}", timeout_ms) for i in range(n)
        )
        assert len(payload) == total
        return payload

    def _delete_dir(self, key_prefix: str) -> None:
        try:
            self._store.delete_prefix(key_prefix + "/")
        except Exception:
            pass

    def _ack(self, round_key: str) -> None:
        self._store.put(f"{round_key}/ack/{self.rank}", b"1")

    def _count_acks(self, prefix: str) -> int:
        return len(self._store.list_prefix(prefix))


def serve(port: int = 0) -> ObjStoreServer:
    """Start a store server and print/export its address (launcher helper)."""
    server = ObjStoreServer(port)
    os.environ.setdefault("CHAINERMN_TPU_OBJSTORE", f"127.0.0.1:{server.port}")
    return server
