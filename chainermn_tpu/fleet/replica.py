"""One serving replica: an engine + scheduler on its own thread, under a
supervisor that lifts PR 3's engine exception boundary one level.

Inside a replica, the :class:`~chainermn_tpu.serving.scheduler.
FCFSScheduler` runs with ``restart_on_error=False``: an engine-side
failure still fails every in-flight request loudly (terminal ERRORED —
the PR 3 contract), but the *recovery* decision escalates here instead of
being taken inside the scheduler. The supervisor then:

1. drains the scheduler's QUEUED work (:meth:`FCFSScheduler.
   drain_queued`) and hands it to the router's failure callback — queued
   requests never even started, so they re-route to a healthy replica
   with nothing lost;
2. warm-``restart()``\\ s the engine (fresh caches/slot mirrors/trie,
   SAME compiled programs — zero recompiles across the restart) while the
   replica reports ``RESTARTING``;
3. past ``max_restarts`` — or on a hard :class:`ReplicaKilled` poison
   (the bench continuity probe) — **quarantines**: the replica stops
   accepting work and its thread exits; the fleet's capacity shrinks by
   one replica instead of the service dying.

A replica also watches its engine's :class:`~chainermn_tpu.extensions.
profiling.Watchdog` (configure it with ``on_timeout='warn'`` for fleet
use — abort mode kills the whole process, which is exactly what the
fleet tier exists to avoid): a fired watchdog after a device call is
treated as a replica failure, so a wedged collective on ONE mesh drains
and restarts one replica while the others keep serving.

Every transition is observable: a ``fleet_replica_state`` gauge per
replica (0 starting, 1 healthy, 2 restarting, 3 quarantined, 4 stopped,
5 draining, 6 retired),
``fleet_replica_restarts_total{replica=}``, and
``fleet_replica_error`` / ``fleet_replica_quarantine`` flight-recorder
events.

This module must not import ``chainermn_tpu.extensions`` (or jax, or the
serving package) at module level — serving/resilience are imported
lazily at construction/call time; pinned by
``tests/monitor_tests/test_import_hygiene.py``.
"""

from __future__ import annotations

import enum
import sys
import threading
from typing import Callable, Optional

from chainermn_tpu.analysis import sanitizer
from chainermn_tpu.monitor._state import get_event_log, get_registry
from chainermn_tpu.fleet.routing import ReplicaSnapshot


class ReplicaState(enum.Enum):
    STARTING = "starting"
    HEALTHY = "healthy"
    RESTARTING = "restarting"
    QUARANTINED = "quarantined"
    STOPPED = "stopped"
    DRAINING = "draining"     # graceful retire in progress (not accepting)
    RETIRED = "retired"       # drained clean and released (terminal)


_STATE_CODE = {
    ReplicaState.STARTING: 0,
    ReplicaState.HEALTHY: 1,
    ReplicaState.RESTARTING: 2,
    ReplicaState.QUARANTINED: 3,
    ReplicaState.STOPPED: 4,
    ReplicaState.DRAINING: 5,
    ReplicaState.RETIRED: 6,
}


class ReplicaKilled(RuntimeError):
    """Hard-kill poison: the replica fails terminally (no restart budget
    consulted — straight to quarantine). The bench continuity probe and
    the kill-one-replica tests use this to simulate a dead worker."""


class ReplicaHang(RuntimeError):
    """The replica's engine watchdog fired during a device call — the
    step eventually returned (or the injected hang cleared), but the
    replica is treated as failed and restarted."""


def _inject(point: str, **ctx) -> None:
    # lazy: resilience's package init pulls the trainer (-> extensions);
    # importing it at module level would break fleet's import hygiene
    from chainermn_tpu.resilience.faults import inject

    inject(point, **ctx)


class EngineReplica:
    """One engine + scheduler + driving thread, supervised.

    Parameters
    ----------
    replica_id : int
        Fleet-unique id (labels, routing, events).
    engine : ServingEngine
        Built by the caller (model/sharding/sampler config stays in one
        place, exactly like :class:`~chainermn_tpu.serving.client.
        ServingClient`). Warmup runs ON the replica thread at start, so
        N replicas warm their compiled-program families in parallel.
    eos_id / retry : forwarded to the replica's scheduler.
    max_restarts : int
        Warm restarts before quarantine (the supervisor's budget — the
        scheduler's own restart path is disabled in fleet mode).
    on_failure : callable(replica, drained, exc, restarted)
        The router's failover hook, invoked from the replica thread after
        in-flight work was failed, QUEUED work drained, and the
        restart/quarantine decision taken.
    """

    def __init__(self, replica_id: int, engine, *,
                 eos_id: Optional[int] = None,
                 max_restarts: int = 2,
                 idle_wait_s: float = 0.02,
                 retry=None,
                 on_failure: Optional[Callable] = None,
                 labels: Optional[dict] = None,
                 autostart: bool = True,
                 fair=None, tenant_weights=None, brownout=None,
                 chunk_tokens_per_step: Optional[int] = None) -> None:
        from chainermn_tpu.serving.metrics import ServingMetrics
        from chainermn_tpu.serving.scheduler import FCFSScheduler

        self.replica_id = int(replica_id)
        self.engine = engine
        self.metrics = ServingMetrics(engine.n_slots)
        # restart_on_error=False: failure ESCALATES to this supervisor
        # (in-flight still errors loudly inside the scheduler first)
        self.scheduler = FCFSScheduler(
            engine, eos_id=eos_id, metrics=self.metrics, retry=retry,
            restart_on_error=False, fair=fair,
            tenant_weights=tenant_weights, brownout=brownout,
            chunk_tokens_per_step=chunk_tokens_per_step)
        self.max_restarts = int(max_restarts)
        self.restarts = 0
        self._idle_wait_s = idle_wait_s
        self._on_failure = on_failure
        self._state = ReplicaState.STARTING
        # guards the state FIELD only (leaf: nothing nests under it) —
        # the warmup thread's STARTING->HEALTHY CAS races the retire
        # path's DRAINING; the metric gauge is updated outside the lock
        self._state_lock = sanitizer.make_lock(
            "EngineReplica._state_lock", leaf=True)
        self._poison: Optional[BaseException] = None
        self._work = threading.Event()
        self._stop = threading.Event()
        self.ready = threading.Event()
        self._events = get_event_log()
        reg = get_registry()
        # caller-supplied labels (the router's fleet= instance tag) keep
        # successive fleets' replica-N series apart in the registry
        labels = dict(labels or {}, replica=str(self.replica_id))
        self._g_state = reg.gauge("fleet_replica_state", labels)
        self._c_restarts = reg.counter("fleet_replica_restarts_total",
                                       labels)
        self._g_state.set(_STATE_CODE[self._state])
        self._thread = threading.Thread(
            target=self._loop, name=f"chainermn-fleet-replica-{replica_id}",
            daemon=True)
        if autostart:
            self.start()

    # ------------------------------------------------------------------ #
    # public surface (router-facing, any thread)                          #
    # ------------------------------------------------------------------ #

    @property
    def state(self) -> ReplicaState:
        return self._state  # graftlint: unguarded-ok — atomic enum read

    @property
    def accepting(self) -> bool:
        """Routable: warming up or serving (a RESTARTING replica is mid-
        recovery — don't pile new work onto it; QUARANTINED/STOPPED are
        out of the fleet)."""
        # the lock exists for check-then-set transitions, not snapshots
        # graftlint: unguarded-ok — one atomic enum read
        return self._state in (ReplicaState.STARTING, ReplicaState.HEALTHY)

    @property
    def busy(self) -> bool:
        """Work queued or decoding right now — the decode-stall deadman's
        ``active_fn`` gate (an idle replica not minting tokens is fine; a
        busy one not minting tokens is stalled)."""
        return self.scheduler.has_work

    def start(self) -> None:
        if not self._thread.is_alive() and not self._stop.is_set():
            self._thread.start()

    def submit(self, prompt, max_new_tokens: int, *, rng=None,
               stream_cb=None, deadline_s=None, tenant: str = "default",
               priority: str = "interactive"):
        """Enqueue onto this replica's scheduler (thread-safe) and wake
        the drive loop. The router owns the routing decision; this is
        mechanism only."""
        if not self.accepting:
            raise RuntimeError(
                # graftlint: unguarded-ok — diagnostic read only
                f"replica {self.replica_id} is {self._state.value}, "
                "not accepting work")
        req = self.scheduler.submit(prompt, max_new_tokens, rng=rng,
                                    stream_cb=stream_cb,
                                    deadline_s=deadline_s,
                                    tenant=tenant, priority=priority)
        self._work.set()
        return req

    def submit_migrated(self, req, payload: dict):
        """Accept a prefill-complete request handed over from a prefill-
        tier peer (thread-safe). The SAME Request object continues on
        this replica's scheduler — its stream/trace/waiter follow it.
        Raises when not accepting, so the source keeps decoding in
        place (the migration handshake never loses a request)."""
        if not self.accepting:
            raise RuntimeError(
                # graftlint: unguarded-ok — diagnostic read only
                f"replica {self.replica_id} is {self._state.value}, "
                "not accepting migrated work")
        out = self.scheduler.enqueue_migrated(req, payload)
        self._work.set()
        return out

    def request_prefix_export(self, tokens, *, min_blocks: int = 1):
        """Ask this replica's drive thread to export its cached KV for
        ``tokens``'s prefix (thread-safe); returns the scheduler's
        :class:`~chainermn_tpu.serving.scheduler.KvReuseTicket` — the
        caller bounds its own wait. Raises when not accepting (a dying
        holder has nothing shareable)."""
        if not self.accepting:
            raise RuntimeError(
                # graftlint: unguarded-ok — diagnostic read only
                f"replica {self.replica_id} is {self._state.value}, "
                "not accepting export work")
        ticket = self.scheduler.request_prefix_export(
            tokens, min_blocks=min_blocks)
        self._work.set()
        return ticket

    def enqueue_prefix_import(self, payload: dict, on_done=None):
        """Hand a shared-prefix KV payload to this replica's drive
        thread for adoption into its block pool + trie (thread-safe;
        returns the scheduler's ticket — wait on it for a deterministic
        adopt-before-admit, or ignore it for fire-and-forget; any
        failure decays to a plain prefill). Raises when not accepting."""
        if not self.accepting:
            raise RuntimeError(
                # graftlint: unguarded-ok — diagnostic read only
                f"replica {self.replica_id} is {self._state.value}, "
                "not accepting import work")
        ticket = self.scheduler.enqueue_prefix_import(payload,
                                                      on_done=on_done)
        self._work.set()
        return ticket

    def request_rebalance(self, place_cb):
        """Ask this replica's drive thread to hand its cheapest live
        decode slot to ``place_cb`` (thread-safe); returns the ticket.
        Raises when not accepting — a quarantining replica's work moves
        through the supervisor drain instead."""
        if not self.accepting:
            raise RuntimeError(
                # graftlint: unguarded-ok — diagnostic read only
                f"replica {self.replica_id} is {self._state.value}, "
                "not accepting rebalance work")
        ticket = self.scheduler.request_rebalance(place_cb)
        self._work.set()
        return ticket

    def snapshot(self) -> ReplicaSnapshot:
        """Routing-time occupancy (host counters only — the policy's
        input)."""
        occ = self.engine.occupancy()
        ewma = self.metrics.ttft_ewma
        return ReplicaSnapshot(
            replica_id=self.replica_id,
            healthy=self.accepting,
            queue_depth=self.scheduler.queue_depth,
            active_slots=occ["active_slots"],
            n_slots=occ["n_slots"],
            ttft_ewma_s=float(ewma) if ewma is not None else 0.0,
            kv_free_frac=occ["kv_free_frac"],
        )

    def kill(self, exc: Optional[BaseException] = None) -> None:
        """Poison the replica: the drive loop raises on its next
        iteration and the supervisor quarantines (no restart) — the
        kill-one-replica continuity probe."""
        self._poison = exc if exc is not None else ReplicaKilled(
            f"replica {self.replica_id} killed")
        self._work.set()

    def stop(self, timeout: float = 10.0) -> None:
        """Stop the drive thread (in-flight work is abandoned; the
        router cancels outstanding requests)."""
        self._stop.set()
        self._work.set()
        if self._thread.is_alive():
            self._thread.join(timeout)
        with self._state_lock:
            if self._state not in (ReplicaState.QUARANTINED,
                                   ReplicaState.RETIRED):
                self._state = ReplicaState.STOPPED
            st = self._state
        self._g_state.set(_STATE_CODE[st])

    # ------------------------------------------------------------------ #
    # graceful retire (the scale-down actuator)                           #
    # ------------------------------------------------------------------ #

    def begin_retire(self) -> None:
        """Enter DRAINING: stop accepting new work while the drive loop
        keeps stepping the in-flight requests to completion. The router's
        :meth:`~chainermn_tpu.fleet.router.FleetRouter.retire_replica`
        owns the full sequence (drain QUEUED, wait in-flight, stop)."""
        with self._state_lock:
            if self._state not in (ReplicaState.STARTING,
                                   ReplicaState.HEALTHY):
                raise RuntimeError(
                    f"replica {self.replica_id} is {self._state.value}, "
                    "cannot retire")
            self._state = ReplicaState.DRAINING
        self._g_state.set(_STATE_CODE[ReplicaState.DRAINING])

    def finish_retire(self, timeout: float = 10.0) -> None:
        """Stop the drive thread and mark RETIRED (only reached when the
        drain completed; a failure mid-drain quarantines instead)."""
        self._stop.set()
        self._work.set()
        if self._thread.is_alive():
            self._thread.join(timeout)
        self._transition_if(ReplicaState.DRAINING, ReplicaState.RETIRED)

    # ------------------------------------------------------------------ #
    # the drive loop (one thread per replica)                             #
    # ------------------------------------------------------------------ #

    def _set_state(self, state: ReplicaState) -> None:
        with self._state_lock:
            self._state = state
        self._g_state.set(_STATE_CODE[state])

    def _transition_if(self, frm: ReplicaState, to: ReplicaState) -> bool:
        """Compare-and-set state transition. The guard matters: a replica
        retired (or killed) while its warmup is still compiling must NOT
        be resurrected to HEALTHY when the warmup lands — the controller
        scales down faster than a cold engine warms."""
        with self._state_lock:
            if self._state is not frm:
                return False
            self._state = to
        self._g_state.set(_STATE_CODE[to])
        return True

    def _loop(self) -> None:
        try:
            # each replica warms its OWN compiled-program family, in
            # parallel with its peers (warmup is idempotent)
            self.engine.warmup()
            self._transition_if(ReplicaState.STARTING, ReplicaState.HEALTHY)
        except Exception as e:  # noqa: BLE001 — a replica that cannot warm
            self._quarantine(e)  # up must not take traffic
            self.ready.set()
            return
        finally:
            self.ready.set()
        from chainermn_tpu.resilience.cutpoints import FLEET_REPLICA

        while not self._stop.is_set():
            try:
                # the replica-level fault cut-point: a raise here models a
                # worker-process death (not just one device call failing)
                _inject(FLEET_REPLICA, replica=self.replica_id)
                if self._poison is not None:
                    poison, self._poison = self._poison, None
                    raise poison
                if self.scheduler.has_work:
                    # interleaving point: the fuzzer stretches the gap
                    # between the has_work check and the step — the
                    # submit/step race window the router exercises
                    sanitizer.sync_point("replica:step")
                    self.scheduler.step()
                    self._check_watchdog()
                else:
                    self._work.clear()
                    if self.scheduler.has_work:
                        continue
                    self._work.wait(self._idle_wait_s)
            except Exception as e:  # noqa: BLE001 — the supervisor boundary
                self._supervise_failure(e)
                # graftlint: unguarded-ok — own-thread read after verdict
                if self._state is not ReplicaState.HEALTHY:
                    return

    def _check_watchdog(self) -> None:
        wd = getattr(self.engine, "watchdog", None)
        if wd is not None and wd.fired:
            raise ReplicaHang(
                f"replica {self.replica_id} watchdog fired mid-step")

    # ------------------------------------------------------------------ #
    # the supervisor boundary                                             #
    # ------------------------------------------------------------------ #

    def _supervise_failure(self, e: BaseException) -> None:
        """PR 3's exception boundary, one level up: fail in-flight work
        loudly (idempotent — a failure inside ``step()`` already did),
        drain QUEUED work for re-routing, then warm-restart within budget
        or quarantine. The router's callback runs LAST, once this
        replica's fate is decided, so re-routing sees the true fleet."""
        # a failure while DRAINING must not warm-restart the replica back
        # into the accepting pool — the retire decision stands, so the
        # failure is terminal (quarantine; in-flight work re-routes)
        # graftlint: unguarded-ok — atomic read on the replica's own thread
        fatal_drain = self._state is ReplicaState.DRAINING
        self._set_state(ReplicaState.RESTARTING)
        self.scheduler.fail_inflight(e)
        drained = self.scheduler.drain_queued()
        fatal = isinstance(e, ReplicaKilled) or fatal_drain
        restarted = False
        if (not fatal and self.restarts < self.max_restarts
                and not self._stop.is_set()):
            try:
                self.engine.restart()
                wd = getattr(self.engine, "watchdog", None)
                if wd is not None:
                    wd._fired.clear()   # re-arm hang detection post-restart
                self.restarts += 1
                self._c_restarts.inc()
                self._set_state(ReplicaState.HEALTHY)
                restarted = True
            except Exception as restart_exc:  # noqa: BLE001
                e = restart_exc
        if not restarted:
            self._quarantine(e)
        self._events.emit("fleet_replica_error", replica=self.replica_id,
                          error=type(e).__name__, detail=str(e)[:200],
                          drained=len(drained), restarted=restarted,
                          restarts=self.restarts)
        if self._on_failure is not None:
            try:
                self._on_failure(self, drained, e, restarted)
            except Exception as cb_exc:  # noqa: BLE001 — never kill the loop
                print(f"chainermn_tpu.fleet: replica {self.replica_id} "
                      f"failure callback raised "
                      f"{type(cb_exc).__name__}: {cb_exc}",
                      file=sys.stderr, flush=True)

    def _quarantine(self, e: BaseException) -> None:
        self._set_state(ReplicaState.QUARANTINED)
        self._events.emit("fleet_replica_quarantine",
                          replica=self.replica_id,
                          error=type(e).__name__, detail=str(e)[:200],
                          restarts=self.restarts)


__all__ = [
    "EngineReplica",
    "ReplicaHang",
    "ReplicaKilled",
    "ReplicaState",
]
