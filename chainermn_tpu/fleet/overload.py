"""Fleet-edge overload protection: per-tenant retry budgets and a
per-tenant circuit breaker.

A saturated fleet sheds work; naive clients retry the sheds; the
retries deepen the saturation — the classic retry storm, where offered
load *amplifies* under overload instead of backing off (goodput falls
to ``1/(1+r)`` of capacity for r retries per shed, all of them queueing
ahead of fresh work). Two complementary edge guards break the loop:

- :class:`RetryBudget` — a token bucket per tenant. Every *retry* (not
  first submissions) spends one token; the bucket refills at
  ``rate_per_s`` up to ``burst``, so transient sheds retry freely while
  a sustained storm runs its tenant's budget dry and is denied at the
  edge (``fleet_retry_denied_total``) before it touches the router.

- :class:`TenantBreaker` — a shed-rate circuit breaker per tenant,
  sliding ``window_s`` of submit outcomes. When a tenant's shed rate
  holds above its threshold, the breaker *opens* for that tenant only
  (cataloged ``breaker_open`` event naming it, ``fleet_breaker_state``
  gauge = 1): its submissions are refused instantly with a structured
  ``retry_after_s`` hint instead of queueing doomed work, while every
  other tenant is untouched. After ``open_s`` the breaker half-opens —
  the next outcome decides whether it closes (``breaker_close``).
  :meth:`note_noisy` is the ``NoisyNeighborDetector`` feed: a flagged
  tenant's threshold tightens by ``noisy_factor``, so measured
  overconsumption trips its breaker sooner.

Import-light on purpose (stdlib + sanitizer + monitor spine, no
jax/serving/extensions): the router imports this at module level and
must stay a pure host-logic import — pinned by
``tests/monitor_tests/test_import_hygiene.py``.
"""

from __future__ import annotations

import time
from typing import Optional

from chainermn_tpu.analysis import sanitizer
from chainermn_tpu.monitor._state import get_event_log, get_registry


class RetryBudget:
    """Per-tenant token bucket over *retries*.

    ``allow(tenant)`` consumes one token when available (True) or
    denies the retry (False, ``fleet_retry_denied_total{tenant=}``
    incremented). First submissions never consult the budget — only
    explicitly-marked retries spend tokens, so the budget bounds
    amplification, not admission."""

    def __init__(self, *, rate_per_s: float = 1.0,
                 burst: float = 5.0) -> None:
        if burst < 1.0:
            raise ValueError(f"burst must be >= 1, got {burst}")
        self.rate_per_s = float(rate_per_s)
        self.burst = float(burst)
        self._lock = sanitizer.make_lock("RetryBudget._lock", leaf=True)
        self._registry = get_registry()
        with self._lock:
            self._tokens: dict = {}    # tenant -> (tokens, t_refill)
            self._denied: dict = {}    # tenant -> count (report mirror)

    def allow(self, tenant: str, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else float(now)
        tenant = str(tenant)
        with self._lock:
            tokens, t_last = self._tokens.get(tenant, (self.burst, now))
            tokens = min(self.burst,
                         tokens + (now - t_last) * self.rate_per_s)
            if tokens >= 1.0:
                self._tokens[tenant] = (tokens - 1.0, now)
                return True
            self._tokens[tenant] = (tokens, now)
            self._denied[tenant] = self._denied.get(tenant, 0) + 1
        self._registry.counter("fleet_retry_denied_total",
                               {"tenant": tenant}).inc()
        return False

    def to_json(self) -> dict:
        with self._lock:
            return {
                "rate_per_s": self.rate_per_s,
                "burst": self.burst,
                "tokens": {t: round(v[0], 3)
                           for t, v in self._tokens.items()},
                "denied": dict(self._denied),
            }


class TenantBreaker:
    """Per-tenant shed-rate circuit breaker (see module docstring)."""

    def __init__(self, *, window_s: float = 5.0,
                 shed_threshold: float = 0.5, min_samples: int = 4,
                 open_s: float = 2.0, noisy_factor: float = 0.5) -> None:
        if not 0.0 < shed_threshold <= 1.0:
            raise ValueError(
                f"shed_threshold must be in (0, 1], got {shed_threshold}")
        self.window_s = float(window_s)
        self.shed_threshold = float(shed_threshold)
        self.min_samples = int(min_samples)
        self.open_s = float(open_s)
        self.noisy_factor = float(noisy_factor)
        self._lock = sanitizer.make_lock("TenantBreaker._lock", leaf=True)
        self._events = get_event_log()
        self._registry = get_registry()
        with self._lock:
            self._outcomes: dict = {}   # tenant -> [(t, shed_bool), ...]
            self._open_until: dict = {}  # tenant -> monotonic deadline
            self._noisy: set = set()
            self._trips: dict = {}

    # -- outcome feed ---------------------------------------------------
    def record_shed(self, tenant: str,
                    now: Optional[float] = None) -> None:
        self._record(tenant, True, now)

    def record_ok(self, tenant: str, now: Optional[float] = None) -> None:
        self._record(tenant, False, now)

    def _record(self, tenant: str, shed: bool,
                now: Optional[float]) -> None:
        now = time.monotonic() if now is None else float(now)
        tenant = str(tenant)
        opened = False
        with self._lock:
            window = self._outcomes.setdefault(tenant, [])
            window.append((now, shed))
            self._prune_locked(tenant, now)
            if tenant not in self._open_until:
                window = self._outcomes[tenant]
                if len(window) >= self.min_samples:
                    rate = (sum(1 for _, s in window if s)
                            / len(window))
                    if rate >= self._threshold_locked(tenant):
                        self._open_until[tenant] = now + self.open_s
                        self._trips[tenant] = (
                            self._trips.get(tenant, 0) + 1)
                        opened = True
                        shed_rate = rate
        if opened:
            self._emit_open(tenant, shed_rate, reason="shed_rate")

    def _prune_locked(self, tenant: str, now: float) -> None:
        cutoff = now - self.window_s
        self._outcomes[tenant] = [
            (t, s) for t, s in self._outcomes[tenant] if t >= cutoff]

    def _threshold_locked(self, tenant: str) -> float:
        thr = self.shed_threshold
        if tenant in self._noisy:
            thr *= self.noisy_factor
        return thr

    # -- state reads ----------------------------------------------------
    def is_open(self, tenant: str, now: Optional[float] = None) -> bool:
        """True while ``tenant``'s breaker is open; an expired open
        window closes here (half-open: the caller's next real outcome
        re-arms or re-trips it)."""
        now = time.monotonic() if now is None else float(now)
        tenant = str(tenant)
        closed = False
        with self._lock:
            deadline = self._open_until.get(tenant)
            if deadline is None:
                return False
            if now < deadline:
                return True
            # half-open: clear the window so stale sheds can't re-trip
            # the breaker before fresh outcomes arrive
            del self._open_until[tenant]
            self._outcomes[tenant] = []
            closed = True
        if closed:
            self._emit_close(tenant)
        return False

    def retry_after(self, tenant: str,
                    now: Optional[float] = None) -> float:
        """Remaining open time — the structured hint a refused
        submission carries."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            deadline = self._open_until.get(str(tenant))
        if deadline is None:
            return 0.0
        return max(0.0, round(deadline - now, 3))

    # -- external controls ----------------------------------------------
    def force_open(self, tenant: str, open_s: Optional[float] = None,
                   now: Optional[float] = None) -> None:
        """Operator/chaos control: open ``tenant``'s breaker now."""
        now = time.monotonic() if now is None else float(now)
        tenant = str(tenant)
        with self._lock:
            self._open_until[tenant] = now + (
                self.open_s if open_s is None else float(open_s))
            self._trips[tenant] = self._trips.get(tenant, 0) + 1
        self._emit_open(tenant, 1.0, reason="forced")

    def note_noisy(self, tenant: str) -> None:
        """NoisyNeighborDetector feed: a flagged tenant's shed-rate
        threshold tightens by ``noisy_factor`` — measured
        overconsumption trips its breaker sooner."""
        with self._lock:
            self._noisy.add(str(tenant))

    def _emit_open(self, tenant: str, shed_rate: float,
                   reason: str) -> None:
        self._registry.gauge("fleet_breaker_state",
                             {"tenant": tenant}).set(1)
        self._events.emit("breaker_open", tenant=tenant,
                          shed_rate=round(shed_rate, 4), reason=reason,
                          open_s=self.open_s)

    def _emit_close(self, tenant: str) -> None:
        self._registry.gauge("fleet_breaker_state",
                             {"tenant": tenant}).set(0)
        self._events.emit("breaker_close", tenant=tenant)

    def to_json(self) -> dict:
        now = time.monotonic()
        with self._lock:
            return {
                "window_s": self.window_s,
                "shed_threshold": self.shed_threshold,
                "open": {t: round(d - now, 3)
                         for t, d in self._open_until.items()},
                "noisy": sorted(self._noisy),
                "trips": dict(self._trips),
            }


__all__ = ["RetryBudget", "TenantBreaker"]
