"""The fleet router: N serving replicas behind one submit/wait/stream
surface.

This is the layer ROADMAP item 1 asks for — the refactor that turns "an
engine" into "a service". Each :class:`~chainermn_tpu.fleet.replica.
EngineReplica` runs its own :class:`~chainermn_tpu.serving.engine.
ServingEngine` (own warmup'd compiled programs, slot pool, prefix/paged-
KV store) on its own thread; the :class:`FleetRouter` in front of them:

- **routes** each submission with the two-signal policy
  (:mod:`~chainermn_tpu.fleet.routing`): prefix affinity through a
  fleet-level trie (send a request sharing a cached prefix to the
  replica whose trie holds it), falling back to occupancy-aware
  least-loaded (queue depth + slot occupancy + EWMA TTFT from each
  replica's metrics);
- **admits at the edge**: a global ``max_queue`` sheds overload with
  :class:`~chainermn_tpu.serving.scheduler.QueueFullError` at submit
  (the PR 3 backpressure stance), and per-request deadlines ride through
  to the replica schedulers' shedding machinery unchanged;
- **fails over**: a replica that errors or trips its watchdog is
  drained, warm-restarted, or quarantined by its supervisor
  (:mod:`~chainermn_tpu.fleet.replica`); the router then re-routes the
  drained QUEUED work — and any in-flight request the failure errored —
  to a healthy replica. Re-routing REPLAYS the request (same prompt,
  same rng), which reproduces the identical token stream (the PR 7
  preemption argument, lifted across replicas); tokens already streamed
  before the failure are de-duplicated, so a streaming consumer sees a
  seamless continuation. A request whose deadline expired instead
  finishes cleanly ERRORED (``DeadlineExceededError``) — re-routed or
  cleanly shed, never lost, never stranded.

**Disaggregated prefill/decode tiers (PR 19).** ``FleetRouter(...,
prefill_replicas=P, decode_replicas=D)`` splits the fleet: new requests
route to the first ``P`` replicas (the prefill tier — typically running
chunked prefill, ``chunk_tokens_per_step=``), and when a request's
prefill completes its KV blocks are read out host-side and handed to a
decode-tier replica (:meth:`FleetRouter._migrate`, installed as each
prefill scheduler's ``migrate_cb``). The handover moves the SAME
scheduler ``Request`` object — rng state, position, stream relay and
waiter all ride along, so the token stream is byte-identical to an
unmigrated decode. Every failure mode decays to something safe: no
decode replica can take the payload → the source keeps decoding in
place; the destination dies before importing → the drain hands the
request back and it replays elsewhere; the destination dies mid-decode →
the normal re-route replay. Never a lost request.

The consumer surface is a :class:`FleetRequest` mirroring
:class:`~chainermn_tpu.serving.scheduler.Request` (``wait`` / ``stream``
/ ``output`` / ``state``), so :meth:`FleetRouter.submit` and
:meth:`FleetRouter.generate` drop in where
:class:`~chainermn_tpu.serving.client.ServingClient` was.

Observability rides the existing monitor spine: ``fleet_replica_state``
gauges and per-replica restart counters (the replica module),
``fleet_requests_total`` / ``fleet_reroutes_total`` / ``fleet_shed_total``
/ ``fleet_affinity_{hits,misses}_total`` / ``fleet_route_fallbacks_total``
counters, a ``route`` span (replica id + affinity hit/miss) on every
request trace so a slow request's *placement* shows up in its PR 6
critical path, and :meth:`FleetRouter.fleet_report` pooling the
replicas' TTFT/TPOT/occupancy reservoirs with
:func:`~chainermn_tpu.monitor.registry.merge_rank_payloads` — the same
merge ``MetricsRegistry.aggregate(comm)`` applies across ranks, here
applied across replica registries. ``monitor.http.serve(fleet=router)``
exposes the whole report at ``/fleet``.

Fault cut-points (PR 3's injection surface, extended): ``fleet.route``
fires inside the routing decision — an injected raise falls back to the
lowest-id accepting replica (the request still lands, on the fallback);
``fleet.replica`` fires in each replica's drive loop — an injected raise
exercises the whole supervisor path (drain, restart/quarantine,
re-route).

This module must not import ``chainermn_tpu.extensions`` (or jax, or the
serving package) at module level — serving types are imported lazily;
pinned by ``tests/monitor_tests/test_import_hygiene.py``.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np

from chainermn_tpu.analysis import sanitizer
from chainermn_tpu.fleet.overload import RetryBudget, TenantBreaker
from chainermn_tpu.fleet.replica import (
    EngineReplica,
    ReplicaKilled,
    ReplicaState,
)
from chainermn_tpu.fleet.routing import (
    FleetTrie,
    RouteDecision,
    RoutingPolicy,
)
from chainermn_tpu.fleet.share import SharePayloadCache
from chainermn_tpu.monitor._state import get_event_log, get_registry
from chainermn_tpu.monitor.costs import merge_cost_payloads
from chainermn_tpu.monitor.registry import merge_rank_payloads


def _inject(point: str, **ctx) -> None:
    from chainermn_tpu.resilience.faults import inject  # lazy: hygiene

    inject(point, **ctx)


_fleet_ids = itertools.count()


class FleetRequest:
    """One request's fleet-level handle: stable across re-routes.

    The underlying scheduler :class:`Request` may be replaced when a
    replica fails (the replay binds a fresh one on a healthy replica);
    this handle's ``tokens`` / ``wait`` / ``stream`` / ``output`` present
    one continuous request regardless. Terminal state is owned by the
    router (:meth:`FleetRouter._resolve`) — consumers block on the
    fleet-level event, never on a dead replica's scheduler."""

    def __init__(self, router: "FleetRouter", fid: int, prompt,
                 max_new_tokens: int, rng, stream_cb, deadline_s,
                 tenant: str = "default",
                 priority: str = "interactive") -> None:
        self._router = router
        self.id = fid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.rng = rng
        self.stream_cb = stream_cb
        self.deadline_s = deadline_s
        # cost-attribution label: survives re-routes with the handle, so
        # a replayed binding bills the same tenant on the new replica
        self.tenant = str(tenant)
        # admission class: survives re-routes the same way, so a
        # replayed batch request stays batch on the new replica
        self.priority = str(priority)
        self.t_submit = time.perf_counter()
        self.t_deadline = (self.t_submit + float(deadline_s)
                           if deadline_s is not None else None)
        self.tokens: list = []           # delivered to THIS handle (deduped)
        self.error: Optional[BaseException] = None
        self.replica_id: Optional[int] = None
        self.reroutes = 0
        self.affinity_hit = False
        self._inner = None               # current scheduler Request binding
        self._terminal = threading.Event()
        self._final_state = None

    @property
    def finished(self) -> bool:
        return self._terminal.is_set()

    @property
    def retry_after_s(self) -> Optional[float]:
        """The structured backpressure hint riding a shed/rejected
        request's stored error (``QueueFullError`` / deadline shed), or
        None — a well-behaved client waits this long before retrying."""
        return getattr(self.error, "retry_after_s", None)

    @property
    def state(self):
        """Fleet-level request state (the serving ``RequestState``
        enum). Before a terminal decision this mirrors the current
        binding; after, the router's verdict."""
        if self._final_state is not None:
            return self._final_state
        inner = self._inner
        if inner is not None:
            return inner.state
        from chainermn_tpu.serving.scheduler import RequestState

        return RequestState.QUEUED

    @property
    def weight_version(self):
        """Weight version the current binding decoded under (stamped at
        admission; the fence guarantees it never changes mid-decode).
        None while still queued."""
        inner = self._inner
        return (getattr(inner, "weight_version", None)
                if inner is not None else None)

    @property
    def output(self) -> np.ndarray:
        """``prompt + generated`` tokens; an ERRORED request re-raises its
        stored exception (never a silent partial)."""
        if self.error is not None:
            raise self.error
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the router settles this request (DONE / CANCELLED
        / ERRORED — re-routes are transparent); True when finished. An
        ERRORED request re-raises its stored exception here."""
        return self._router._await(self, timeout)

    def stream(self, poll_s: float = 0.01):
        """Yield generated tokens as they arrive, across re-routes
        (replayed tokens are de-duplicated); re-raises the stored
        exception at the end of an ERRORED request's stream."""
        i = 0
        while True:
            while i < len(self.tokens):
                yield self.tokens[i]
                i += 1
            if self._terminal.is_set():
                while i < len(self.tokens):
                    yield self.tokens[i]
                    i += 1
                if self.error is not None:
                    raise self.error
                return
            self._router._await(self, poll_s, _raise=False)


class FleetRouter:
    """N engine replicas behind one serving surface (module docstring).

    Parameters
    ----------
    engines : sequence of ServingEngine
        One per replica, built by the caller (identical model/params/
        sampler config is the caller's contract — routing assumes any
        replica can serve any request). Warmup runs on each replica's
        own thread; :meth:`wait_ready` blocks until the fleet is warm.
    eos_id / retry : forwarded to every replica's scheduler.
    affinity : bool
        Prefix-affinity routing (auto-disabled when the engines have no
        prefix cache — there is nothing to be affine to).
    max_queue : int, optional
        GLOBAL queued-request bound: submissions beyond it are shed at
        the fleet edge with ``QueueFullError``.
    default_deadline_s : float, optional
        Default per-request deadline (PR 3 semantics, applied through
        the replica schedulers; also bounds how long a re-route keeps
        retrying a request).
    max_restarts : int
        Per-replica warm-restart budget before quarantine.
    max_reroutes : int, optional
        Re-route budget per request (default: the replica count).
    prefill_replicas / decode_replicas : int, optional
        Disaggregated tiers (give both or neither): the first
        ``prefill_replicas`` engines form the prefill tier, the rest the
        decode tier; ``prefill + decode`` must cover every engine.
    chunk_tokens_per_step : int, optional
        Forwarded to every replica's scheduler: long prompts prefill in
        bounded chunks interleaved with decode.
    share_prefixes : bool
        Cross-replica prefix sharing: when the affinity trie knows a
        holder but the policy routes elsewhere (holder overloaded/
        degraded), export the holder's cached prefix KV and import it
        into the chosen replica instead of re-prefilling it there.
        Auto-disabled unless every engine supports block migration
        (paged, single-device) and affinity is on.
    prefix_share_min_blocks : int
        Smallest resident prefix worth shipping (below it the import
        round-trip costs more than the prefill it saves — PERF.md
        derives the crossover).
    share_timeout_s : float
        Bound on the holder-export wait; a slow holder just means the
        destination prefills.
    share_cache_entries : int
        Host-side payload LRU size: a hot prefix is exported once and
        imported everywhere.
    """

    def __init__(self, engines: Sequence, *, eos_id: Optional[int] = None,
                 affinity: bool = True,
                 affinity_block_size: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 default_deadline_s: Optional[float] = None,
                 max_restarts: int = 2,
                 max_reroutes: Optional[int] = None,
                 policy: Optional[RoutingPolicy] = None,
                 retry=None, idle_wait_s: float = 0.02,
                 autostart: bool = True,
                 retry_budget: Optional[RetryBudget] = None,
                 breaker: Optional[TenantBreaker] = None,
                 fair=None, tenant_weights=None, brownout=None,
                 prefill_replicas: Optional[int] = None,
                 decode_replicas: Optional[int] = None,
                 chunk_tokens_per_step: Optional[int] = None,
                 share_prefixes: bool = False,
                 prefix_share_min_blocks: int = 2,
                 share_timeout_s: float = 5.0,
                 share_cache_entries: int = 8) -> None:
        if not engines:
            raise ValueError("a fleet needs at least one engine")
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if (prefill_replicas is None) != (decode_replicas is None):
            raise ValueError("prefill_replicas and decode_replicas must "
                             "be given together (or neither)")
        self._prefill_tier: Optional[frozenset] = None
        if prefill_replicas is not None:
            p, d = int(prefill_replicas), int(decode_replicas)
            if p < 1 or d < 1:
                raise ValueError(
                    f"both tiers need at least one replica, got "
                    f"prefill={p} decode={d}")
            if p + d != len(engines):
                raise ValueError(
                    f"prefill_replicas + decode_replicas must cover the "
                    f"fleet: {p}+{d} != {len(engines)} engines")
            self._prefill_tier = frozenset(range(p))
        prefix_on = all(getattr(e, "prefix_enabled", False) for e in engines)
        self.affinity = bool(affinity) and prefix_on
        # cross-replica prefix sharing (ISSUE 20) needs the affinity trie
        # to find holders AND every engine able to export/import block
        # rows (paged, single-device). Anything less degrades to plain
        # affinity routing — never an error (the TP-fleet stance).
        self.share_prefixes = (bool(share_prefixes) and self.affinity
                               and all(getattr(e, "migration_supported",
                                               False) for e in engines))
        self.prefix_share_min_blocks = max(1, int(prefix_share_min_blocks))
        self.share_timeout_s = float(share_timeout_s)
        if affinity_block_size is None:
            affinity_block_size = (engines[0].prefix_cache.block_size
                                   if prefix_on else 16)
        self.max_queue = max_queue
        self.default_deadline_s = default_deadline_s
        self._policy = policy if policy is not None else RoutingPolicy(
            affinity=self.affinity)
        self._trie = FleetTrie(affinity_block_size)
        self._lock = sanitizer.make_rlock("FleetRouter._lock")
        self._ids = itertools.count()
        # sanitizer-guarded: mutation without _lock held raises when the
        # runtime sanitizer is on
        self._requests: dict[int, FleetRequest] = sanitizer.guarded(
            {}, lock=self._lock, name="FleetRouter._requests")
        self._closed = False
        self._events = get_event_log()
        reg = get_registry()
        # per-router instance label (the ServingMetrics convention):
        # successive/concurrent fleets in one process never mix series
        labels = {"fleet": str(next(_fleet_ids))}
        self._c_requests = reg.counter("fleet_requests_total", labels)
        self._c_reroutes = reg.counter("fleet_reroutes_total", labels)
        self._c_shed = reg.counter("fleet_shed_total", labels)
        self._c_aff_hits = reg.counter("fleet_affinity_hits_total", labels)
        self._c_aff_miss = reg.counter("fleet_affinity_misses_total", labels)
        self._c_fallbacks = reg.counter("fleet_route_fallbacks_total",
                                        labels)
        self._c_shares = reg.counter("kv_shares_total", labels)
        self._c_rebalances = reg.counter("kv_rebalances_total", labels)
        # one export serves every later importer of the same prefix
        self._share_cache = (SharePayloadCache(share_cache_entries,
                                               labels=labels)
                             if self.share_prefixes else None)
        self.max_reroutes = (int(max_reroutes) if max_reroutes is not None
                             else len(engines))
        # replicas added later (spawn_replica) are built with the same
        # configuration as the constructor's set
        self._replica_cfg = dict(eos_id=eos_id, max_restarts=max_restarts,
                                 retry=retry, idle_wait_s=idle_wait_s,
                                 fair=fair, tenant_weights=tenant_weights,
                                 brownout=brownout,
                                 chunk_tokens_per_step=chunk_tokens_per_step)
        # fleet-edge overload guards (None = feature off, zero overhead)
        self.retry_budget = retry_budget
        self.breaker = breaker
        self._labels = labels
        # replicas currently inside a publish fence: routing steers new
        # work away from them (unless nothing else is healthy)
        self._publishing: set[int] = sanitizer.guarded(
            set(), lock=self._lock, name="FleetRouter._publishing")
        # optional HealthMonitor (attach_health): routing reads its
        # per-replica verdict as the leading sort key — degraded
        # replicas are deprioritized before the supervisor would
        # quarantine them
        self._health = None
        # optional FleetController (attach_controller): fleet_report
        # grows a "control" block; the controller itself only ever calls
        # INTO the router (never the other way), so no call cycle exists
        self._controller = None
        # per-replica admission weights (the control plane's rebalance
        # actuator): missing key = full weight 1.0
        self._weights: dict[int, float] = sanitizer.guarded(
            {}, lock=self._lock, name="FleetRouter._weights")
        self.replicas = [
            EngineReplica(i, eng, on_failure=self._on_replica_failure,
                          labels=labels, autostart=autostart,
                          **self._replica_cfg)
            for i, eng in enumerate(engines)
        ]
        if self._prefill_tier is not None:
            # the handover hook: each prefill-tier scheduler offers its
            # prefill-complete requests back to the router for placement
            # on a decode replica (replicas spawned later join the
            # decode tier implicitly — they are never in _prefill_tier)
            for rid in self._prefill_tier:
                self.replicas[rid].scheduler.migrate_cb = self._migrate

    # ------------------------------------------------------------------ #
    # lifecycle                                                           #
    # ------------------------------------------------------------------ #

    def start(self) -> None:
        """Start replica threads (only needed after ``autostart=False``,
        the deterministic-tests configuration)."""
        for r in self.replicas:
            r.start()

    def wait_ready(self, timeout: float = 300.0) -> bool:
        """Block until every replica still accepting work finished warmup
        (compiled programs built); True when all are ready within the
        timeout. Retired/quarantined replicas are skipped — a replica
        retired before it ever started will never signal ready."""
        deadline = time.perf_counter() + timeout
        for r in self.replicas:
            if not r.accepting:
                continue
            if not r.ready.wait(max(0.0, deadline - time.perf_counter())):
                return False
        return True

    @property
    def capacity(self) -> int:
        """Replicas currently accepting work (shrinks on quarantine)."""
        return sum(1 for r in self.replicas if r.accepting)

    def kill_replica(self, replica_id: int) -> None:
        """Hard-kill one replica (poison -> quarantine; its work is
        re-routed) — the continuity probe's entry point."""
        self.replicas[replica_id].kill()

    def attach_health(self, monitor) -> None:
        """Attach a :class:`~chainermn_tpu.monitor.health.HealthMonitor`
        (usually via :func:`~chainermn_tpu.monitor.health.fleet_health`):
        every routing decision then carries the monitor's per-replica
        verdict as the leading sort key, and :meth:`fleet_report` gains a
        ``health`` block. Detach with ``attach_health(None)``."""
        with self._lock:
            self._health = monitor

    def attach_controller(self, controller) -> None:
        """Attach a :class:`~chainermn_tpu.fleet.control.FleetController`
        so :meth:`fleet_report` carries its decision state under
        ``"control"``. Detach with ``attach_controller(None)``."""
        with self._lock:
            self._controller = controller

    def set_admission_weight(self, replica_id: int, weight: float) -> None:
        """Scale how much new traffic ``replica_id`` attracts (0 < w <=
        1; 1.0 resets). The routing policy divides the replica's
        normalized load by its weight, so a shed replica looks
        proportionally busier and loses placements it would otherwise
        win — without ever becoming unroutable (pre-quarantine
        rebalancing, driven by the control plane)."""
        w = float(weight)
        if not 0.0 < w <= 1.0:
            raise ValueError(f"admission weight must be in (0, 1], got {w}")
        with self._lock:
            if w == 1.0:
                self._weights.pop(int(replica_id), None)
            else:
                self._weights[int(replica_id)] = w

    def admission_weight(self, replica_id: int) -> float:
        with self._lock:
            return self._weights.get(int(replica_id), 1.0)

    def close(self, timeout: float = 10.0) -> None:
        """Stop every replica thread and settle every outstanding request
        (CANCELLED) so no waiter hangs."""
        with self._lock:
            self._closed = True
        for r in self.replicas:
            r.stop(timeout)
        from chainermn_tpu.serving.scheduler import RequestState

        with self._lock:
            pending = [fr for fr in self._requests.values()
                       if not fr.finished]
            for fr in pending:
                self._finalize_locked(fr, RequestState.CANCELLED, None)

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------ #
    # submission surface (any thread)                                     #
    # ------------------------------------------------------------------ #

    def submit(self, prompt, max_new_tokens: int, *, rng=None,
               stream_cb: Optional[Callable[[int], None]] = None,
               deadline_s: Optional[float] = None,
               tenant: str = "default", priority: str = "interactive",
               retrying: bool = False) -> FleetRequest:
        """Route and enqueue one request; returns immediately. Raises
        ``QueueFullError`` when the fleet-wide queue bound is hit
        (counted as a fleet shed, ``retry_after_s`` hint attached), when
        ``tenant``'s circuit breaker is open, or when ``retrying=True``
        and the tenant's retry budget is dry; ``RuntimeError`` when no
        replica is accepting work. ``retrying`` is the client's honesty
        bit — mark resubmissions of previously-shed work so the budget
        can bound retry-storm amplification at the edge."""
        from chainermn_tpu.resilience.cutpoints import FLEET_BREAKER
        from chainermn_tpu.resilience.faults import inject
        from chainermn_tpu.serving.scheduler import QueueFullError

        tenant = str(tenant)
        if self.breaker is not None or self.retry_budget is not None:
            # chaos boundary: a fault armed here fails CLOSED — the one
            # probed submission is refused, the fleet itself unharmed
            try:
                inject(FLEET_BREAKER, tenant=tenant, retrying=retrying)
            except Exception as e:
                self._c_shed.inc()
                self._events.emit("fleet_shed", reason="breaker_fault",
                                  tenant=tenant)
                raise QueueFullError(
                    f"tenant {tenant} refused at breaker cut-point: {e}",
                    retry_after_s=0.1) from e
        if self.breaker is not None and self.breaker.is_open(tenant):
            hint = self.breaker.retry_after(tenant) or self.breaker.open_s
            self._c_shed.inc()
            self._events.emit("fleet_shed", reason="breaker_open",
                              tenant=tenant, retry_after_s=hint)
            raise QueueFullError(
                f"tenant {tenant} circuit breaker is open "
                f"(sustained shed rate); retry after {hint}s",
                retry_after_s=hint)
        if (retrying and self.retry_budget is not None
                and not self.retry_budget.allow(tenant)):
            self._c_shed.inc()
            self._events.emit("fleet_shed", reason="retry_budget",
                              tenant=tenant)
            raise QueueFullError(
                f"tenant {tenant} retry budget exhausted; back off",
                retry_after_s=round(1.0 / max(
                    self.retry_budget.rate_per_s, 1e-6), 3))
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        with self._lock:
            if self._closed:
                raise RuntimeError("fleet router is closed")
            snaps = self._snapshots_locked()
            if not any(s.healthy for s in snaps):
                raise RuntimeError(
                    "no replica accepting work (all quarantined/stopped)")
            if self._policy.overloaded(snaps, self.max_queue):
                depth = sum(s.queue_depth for s in snaps)
                hint = round(0.05 + 0.01 * depth, 3)
                self._c_shed.inc()
                self._events.emit(
                    "fleet_shed", reason="queue_full",
                    queue_depth=depth, tenant=tenant)
                if self.breaker is not None:
                    self.breaker.record_shed(tenant)
                raise QueueFullError(
                    f"fleet admission queue full ({self.max_queue} queued "
                    f"across {self.capacity} replicas); retry later",
                    retry_after_s=hint,
                )
            fid = next(self._ids)
            fr = FleetRequest(self, fid, prompt, max_new_tokens, rng,
                              stream_cb, deadline_s, tenant=tenant,
                              priority=priority)
            t0 = time.perf_counter()
            decision = self._route_locked(fr.prompt, snaps)
            share = (self._plan_share_locked(fr, decision)
                     if self.share_prefixes else None)
            if share is None:
                self._bind_locked(fr, decision, t0)
                self._requests[fid] = fr
                self._c_requests.inc()
                return fr
        # cross-replica share handshake OUTSIDE the router lock: both
        # halves are bounded waits on other replicas' drive threads
        # (export on the holder, adoption on the destination — never
        # under the router lock), and the destination serves pending
        # imports at step() start BEFORE fresh admissions, so by the
        # time the bind below enqueues the request its prompt's shared
        # blocks are already trie-resident there. Every failure or
        # timeout decays to a plain prefill on the destination — the
        # request lands either way.
        self._execute_share(fr, share)
        with self._lock:
            if self._closed:
                raise RuntimeError("fleet router is closed")
            self._bind_locked(fr, decision, t0)
            self._requests[fid] = fr
            self._c_requests.inc()
        return fr

    def generate(self, prompt, max_new_tokens: int, *, rng=None,
                 timeout: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 tenant: str = "default",
                 priority: str = "interactive") -> np.ndarray:
        """Blocking single-request decode through the fleet — the
        ``ServingClient.generate`` shape."""
        fr = self.submit(prompt, max_new_tokens, rng=rng,
                         deadline_s=deadline_s, tenant=tenant,
                         priority=priority)
        if not fr.wait(timeout):
            self.cancel(fr)
            raise TimeoutError(
                f"fleet request {fr.id} did not finish within {timeout}s")
        return fr.output

    def cancel(self, fr: FleetRequest) -> bool:
        from chainermn_tpu.serving.scheduler import RequestState

        with self._lock:
            if fr.finished:
                return False
            inner = fr._inner
            self._finalize_locked(fr, RequestState.CANCELLED, None)
        if inner is not None and fr.replica_id is not None:
            self.replicas[fr.replica_id].scheduler.cancel(inner)
        return True

    # ------------------------------------------------------------------ #
    # routing internals                                                   #
    # ------------------------------------------------------------------ #

    def _snapshots_locked(self) -> list:
        """Occupancy snapshots of every replica, annotated with the
        attached health monitor's verdict (0 when none is attached).
        ``HealthMonitor._lock`` is a sanitizer leaf lock — reading the
        cached level while holding the router lock acquires nothing
        further, so no lock-order edge exists here."""
        snaps = [r.snapshot() for r in self.replicas]
        if self._health is not None:
            for s in snaps:
                s.health = self._health.level(str(s.replica_id))
        if self._weights:
            for s in snaps:
                s.admission_weight = self._weights.get(s.replica_id, 1.0)
        return snaps

    def _route_locked(self, prompt, snaps, exclude: Optional[int] = None
               ) -> RouteDecision:
        """The two-signal decision, with the ``fleet.route`` fault
        cut-point inside: an injected (or real) routing failure falls
        back to the lowest-id accepting replica — placement degrades,
        the request still lands."""
        candidates = [s for s in snaps if s.healthy
                      and s.replica_id != exclude
                      and s.replica_id not in self._publishing]
        if not candidates:
            # every healthy replica is mid-publish (or excluded): landing
            # on a fenced replica just queues through its swap window —
            # better than shedding
            candidates = [s for s in snaps if s.healthy
                          and s.replica_id != exclude]
        if not candidates:
            candidates = [s for s in snaps if s.healthy]
        if self._prefill_tier is not None:
            # disaggregated mode: new work lands on the prefill tier —
            # unless none of it survived the filters above, in which
            # case the whole fleet serves (degraded but never shedding
            # for tier purity)
            tiered = [s for s in candidates
                      if s.replica_id in self._prefill_tier]
            if tiered:
                candidates = tiered
        from chainermn_tpu.resilience.cutpoints import FLEET_ROUTE

        try:
            _inject(FLEET_ROUTE, candidates=len(candidates))
            rid, blocks = ((None, 0) if not self.affinity
                           else self._trie.lookup(prompt))
            decision = self._policy.route(candidates, rid, blocks)
            if decision is None:
                raise RuntimeError("no healthy replica")
            return decision
        except Exception as e:  # noqa: BLE001 — routing must not lose work
            fallback = min(s.replica_id for s in candidates)
            self._c_fallbacks.inc()
            self._events.emit("fleet_route_fallback",
                              error=type(e).__name__, replica=fallback)
            return RouteDecision(fallback, affinity_hit=False,
                                 reason=f"fallback:{type(e).__name__}")

    def _bind_locked(self, fr: FleetRequest, decision: RouteDecision,
                     t0: float, rerouted: bool = False) -> None:
        """Submit ``fr`` to the decided replica (holding the router
        lock): install the de-duplicating token relay, attach the
        ``route`` span to the new binding's trace, stamp the fleet trie,
        and count the affinity outcome."""
        replica = self.replicas[decision.replica_id]
        replayed = len(fr.tokens)
        seen = 0

        def relay(tok: int, fr=fr) -> None:
            # engine-thread callback: skip the replayed prefix (identical
            # by the prompt+rng replay argument), append the rest
            nonlocal seen
            seen += 1
            if seen > replayed:
                fr.tokens.append(int(tok))
                if fr.stream_cb is not None:
                    try:
                        fr.stream_cb(int(tok))
                    except Exception:  # noqa: BLE001 — consumer's problem
                        pass

        remaining = None
        if fr.t_deadline is not None:
            remaining = fr.t_deadline - time.perf_counter()
        inner = replica.submit(fr.prompt, fr.max_new_tokens, rng=fr.rng,
                               stream_cb=relay, deadline_s=remaining,
                               tenant=fr.tenant, priority=fr.priority)
        t1 = time.perf_counter()
        inner.trace.add_span("route", t0, t1, replica=decision.replica_id,
                             affinity="hit" if decision.affinity_hit
                             else "miss", reason=decision.reason,
                             rerouted=rerouted)
        fr._inner = inner
        fr.replica_id = decision.replica_id
        fr.affinity_hit = decision.affinity_hit
        (self._c_aff_hits if decision.affinity_hit
         else self._c_aff_miss).inc()
        if self.affinity:
            self._trie.note(fr.prompt, decision.replica_id)
        self._events.emit("fleet_route", req=fr.id,
                          replica=decision.replica_id,
                          affinity=decision.affinity_hit,
                          reason=decision.reason, rerouted=rerouted)

    def _migrate(self, req, payload: dict) -> bool:
        """A prefill-tier scheduler's handover hook (called on that
        replica's driving thread with a prefill-complete request and its
        exported KV payload). Picks the best decode-tier replica that can
        take the payload and enqueues the SAME request object there; True
        transfers ownership. Any failure — chaos at the ``fleet.migrate``
        cut-point, no candidate with capacity, a dying destination —
        returns False and the source decodes in place. The fleet-level
        handle needs no rebinding: ``fr._inner`` is unchanged, only
        ``fr.replica_id`` moves so failure attribution follows the KV."""
        from chainermn_tpu.resilience.cutpoints import FLEET_MIGRATE

        with self._lock:
            if self._closed or self._prefill_tier is None:
                return False
            fr = next((f for f in self._requests.values()
                       if f._inner is req), None)
            if fr is None or fr.finished:
                return False
            try:
                _inject(FLEET_MIGRATE, req=fr.id,
                        replica=fr.replica_id)
            except Exception as e:  # noqa: BLE001 — chaos: stay local
                self._events.emit("fleet_route_fallback",
                                  error=type(e).__name__,
                                  replica=fr.replica_id)
                return False
            snaps = self._snapshots_locked()
            cands = [s for s in snaps
                     if s.replica_id not in self._prefill_tier
                     and s.replica_id not in self._publishing]
            remaining = max(1, fr.max_new_tokens - len(fr.tokens))
            for snap in self._policy.migration_targets(cands):
                dest = self.replicas[snap.replica_id]
                try:
                    if not dest.engine.can_import(payload,
                                                  max_new=remaining):
                        continue
                    dest.submit_migrated(req, payload)
                except Exception:  # noqa: BLE001 — next candidate
                    continue
                src_rid = fr.replica_id
                fr.replica_id = dest.replica_id
                if self.affinity:
                    # the blocks MOVED: the importer now holds the
                    # prompt's KV, the exporter released it — keeping the
                    # exporter's stamps routes affinity traffic at KV
                    # that no longer exists (the disagg staleness bug)
                    self._trie.note(fr.prompt, dest.replica_id)
                    if src_rid is not None:
                        self._trie.forget(fr.prompt, src_rid)
                self._events.emit("fleet_route", req=fr.id,
                                  replica=dest.replica_id,
                                  affinity=False, reason="kv_migrate",
                                  rerouted=False)
                return True
            return False

    # ------------------------------------------------------------------ #
    # fleet-wide KV reuse (cross-replica prefix sharing + rebalancing)    #
    # ------------------------------------------------------------------ #

    def _plan_share_locked(self, fr: FleetRequest,
                           decision: RouteDecision) -> Optional[dict]:
        """Decide whether the routed request should import a shared
        prefix (router-locked, host-only). The trigger is exactly the
        affinity-policy's rejection: the trie knows a holder, but the
        policy sent the request elsewhere (holder overloaded, degraded,
        or out of blocks) — the miss that cross-replica sharing turns
        back into a hit."""
        if self._closed or decision.affinity_hit:
            return None            # routed TO the holder: nothing to move
        holder, blocks = self._trie.lookup(fr.prompt)
        if (holder is None or holder == decision.replica_id
                or blocks < self.prefix_share_min_blocks):
            return None
        if not self.replicas[holder].accepting:
            # a dying holder can't export — but a cached payload from an
            # earlier export still can serve (checked in _execute_share)
            holder = None
        return {"holder": holder, "blocks": blocks,
                "dest": decision.replica_id}

    def _execute_share(self, fr: FleetRequest, plan: dict) -> bool:
        """Run one share handshake (NO router lock held): payload-cache
        hit, else a bounded-wait export on the holder's drive thread;
        then a fire-and-forget import enqueue on the destination. The
        ``fleet.share`` cut-point covers the whole handshake — chaos (or
        any real failure) decays to the destination prefilling the
        prefix itself."""
        from chainermn_tpu.resilience.cutpoints import FLEET_SHARE

        dest_rid = plan["dest"]
        try:
            _inject(FLEET_SHARE, req=fr.id, holder=plan["holder"],
                    dest=dest_rid)
        except Exception as e:  # noqa: BLE001 — chaos: re-prefill
            self._events.emit("fleet_route_fallback",
                              error=type(e).__name__, replica=dest_rid)
            return False
        entry = self._share_cache.match(fr.prompt)
        if entry is None:
            holder = plan["holder"]
            if holder is None:
                return False
            try:
                ticket = self.replicas[holder].request_prefix_export(
                    fr.prompt, min_blocks=self.prefix_share_min_blocks)
            except Exception:  # noqa: BLE001 — holder dying: re-prefill
                return False
            payload = ticket.wait(self.share_timeout_s)
            if payload is None:
                return False
            entry = self._share_cache.put(payload)

        def _adopted(n: int, entry=entry) -> None:
            # destination drive thread: adoption outcome (0 = the blocks
            # were already cached there, or the import failed — either
            # way the request just prefills what's missing)
            self._share_cache.release(entry, imported=bool(n))
            if n:
                self._c_shares.inc()

        try:
            ticket = self.replicas[dest_rid].enqueue_prefix_import(
                entry.payload, on_done=_adopted)
        except Exception:  # noqa: BLE001 — dest dying: re-route handles it
            self._share_cache.release(entry)
            return False
        # bounded wait for the adoption so the bind that follows admits
        # against the populated trie; a timeout (wedged destination)
        # just means this request prefills — the import still lands for
        # the next one
        ticket.wait(self.share_timeout_s)
        return True

    def rebalance_decode(self, src_rid: int,
                         dest_rid: Optional[int] = None):
        """Ask replica ``src_rid`` to hand its cheapest live decode slot
        to a peer mid-stream (thread-safe, fire-and-forget; the control
        plane's pre-quarantine actuator — see
        :meth:`FleetController._rebalance_tick`). Returns the
        scheduler's ticket, or None when the source can't participate.
        The source picks the victim (batch class first, fewest live
        blocks — least payload to move); this router callback places it
        on the least-loaded peer that can import, ``dest_rid`` pinning
        the destination when given. Chaos at ``fleet.rebalance`` — or
        any placement failure — leaves the victim decoding in place."""

        def place(req, payload, src_rid=int(src_rid)) -> bool:
            # source drive thread (outside its scheduler lock): the same
            # lock pattern as _migrate — router-locked candidate walk,
            # host-only capacity checks
            from chainermn_tpu.resilience.cutpoints import FLEET_REBALANCE

            with self._lock:
                if self._closed:
                    return False
                fr = next((f for f in self._requests.values()
                           if f._inner is req), None)
                if fr is None or fr.finished:
                    return False
                try:
                    _inject(FLEET_REBALANCE, req=fr.id, replica=src_rid)
                except Exception as e:  # noqa: BLE001 — decode in place
                    self._events.emit("fleet_route_fallback",
                                      error=type(e).__name__,
                                      replica=src_rid)
                    return False
                snaps = self._snapshots_locked()
                cands = [s for s in snaps
                         if s.replica_id != src_rid
                         and s.replica_id not in self._publishing
                         and (dest_rid is None
                              or s.replica_id == int(dest_rid))
                         and (self._prefill_tier is None
                              or s.replica_id not in self._prefill_tier)]
                remaining = max(1, fr.max_new_tokens - len(fr.tokens))
                for snap in self._policy.migration_targets(cands):
                    dest = self.replicas[snap.replica_id]
                    try:
                        if not dest.engine.can_import(payload,
                                                      max_new=remaining):
                            continue
                        dest.submit_migrated(req, payload)
                    except Exception:  # noqa: BLE001 — next candidate
                        continue
                    fr.replica_id = dest.replica_id
                    if self.affinity:
                        self._trie.note(fr.prompt, dest.replica_id)
                        self._trie.forget(fr.prompt, src_rid)
                    self._c_rebalances.inc()
                    self._events.emit(
                        "rebalance", req=fr.id, src=src_rid,
                        dest=dest.replica_id,
                        blocks=int(payload["n_blocks"]),
                        tokens=len(fr.tokens))
                    return True
                return False

        try:
            return self.replicas[int(src_rid)].request_rebalance(place)
        except Exception:  # noqa: BLE001 — source dying/not accepting
            return None

    # ------------------------------------------------------------------ #
    # settlement (consumer waits + failover)                              #
    # ------------------------------------------------------------------ #

    def _await(self, fr: FleetRequest, timeout: Optional[float],
               _raise: bool = True) -> bool:
        end = (None if timeout is None
               else time.perf_counter() + float(timeout))
        while True:
            if fr._terminal.is_set():
                if _raise and fr.error is not None:
                    raise fr.error
                return True
            slice_s = 0.05 if end is None else min(
                0.05, end - time.perf_counter())
            if slice_s <= 0:
                return False
            inner = fr._inner
            if inner is None:
                time.sleep(min(slice_s, 0.002))   # mid-rebind blink
                continue
            inner._done.wait(slice_s)
            if inner.finished:
                self._resolve(fr, inner)

    def _resolve(self, fr: FleetRequest, inner) -> None:
        """One finished binding's verdict (idempotent, router-locked):
        DONE/CANCELLED settle the fleet request; an engine-failure error
        re-routes (replay on a healthy replica) within the deadline and
        re-route budgets, anything else settles ERRORED."""
        from chainermn_tpu.serving.scheduler import (
            DeadlineExceededError,
            EngineFailed,
            RequestState,
        )

        with self._lock:
            if fr.finished or fr._inner is not inner:
                return
            st = inner.state
            if st is RequestState.DONE:
                if self.breaker is not None:
                    self.breaker.record_ok(fr.tenant)
                self._finalize_locked(fr, st, None)
                return
            if st is RequestState.CANCELLED:
                self._finalize_locked(fr, st, None)
                return
            if st is not RequestState.ERRORED:
                return   # spurious wake: binding not actually terminal
            err = inner.error
            if not isinstance(err, EngineFailed):
                # deadline shed, validation, ... — the replica's verdict
                # IS the fleet verdict (PR 3 semantics pass through)
                if isinstance(err, DeadlineExceededError):
                    self._c_shed.inc()
                    if self.breaker is not None:
                        self.breaker.record_shed(fr.tenant)
                self._finalize_locked(fr, st, err)
                return
            # engine failure: replay on a healthy replica if budgets allow
            if (fr.t_deadline is not None
                    and time.perf_counter() >= fr.t_deadline):
                self._c_shed.inc()
                self._finalize_locked(fr, st, DeadlineExceededError(
                    f"fleet request {fr.id} hit its {fr.deadline_s}s "
                    "deadline during replica failover"))
                return
            snaps = self._snapshots_locked()
            if (fr.reroutes >= self.max_reroutes
                    or not any(s.healthy for s in snaps)):
                self._finalize_locked(fr, st, err)
                return
            t0 = time.perf_counter()
            decision = self._route_locked(fr.prompt, snaps,
                                          exclude=fr.replica_id)
            fr.reroutes += 1
            self._c_reroutes.inc()
            try:
                self._bind_locked(fr, decision, t0, rerouted=True)
            except Exception as bind_exc:  # noqa: BLE001 — target died too
                failure = EngineFailed(
                    f"fleet re-route of request {fr.id} failed: "
                    f"{type(bind_exc).__name__}: {bind_exc}")
                failure.__cause__ = bind_exc
                self._finalize_locked(fr, RequestState.ERRORED, failure)

    def _finalize_locked(self, fr: FleetRequest, state,
                         error: Optional[BaseException]) -> None:
        fr.error = error
        fr._final_state = state
        fr._terminal.set()
        self._requests.pop(fr.id, None)

    def _on_replica_failure(self, replica: EngineReplica, drained: list,
                            exc: BaseException, restarted: bool) -> None:
        """The supervisor's callback (replica thread): forget the failed
        replica's prefix beliefs, then proactively settle every fleet
        request it owned — drained QUEUED work re-binds immediately
        (nothing ever started, nothing lost); errored in-flight work goes
        through the normal :meth:`_resolve` replay path."""
        rid = replica.replica_id
        with self._lock:
            self._trie.drop_replica(rid)
            drained_ids = {id(req) for req in drained}
            affected = [fr for fr in list(self._requests.values())
                        if fr.replica_id == rid and not fr.finished]
        for fr in affected:
            inner = fr._inner
            if inner is None:
                continue
            if id(inner) in drained_ids:
                self._rebind_drained(fr, inner)
            elif inner.finished:
                self._resolve(fr, inner)

    def _rebind_drained(self, fr: FleetRequest, inner) -> None:
        from chainermn_tpu.serving.scheduler import (
            DeadlineExceededError,
            EngineFailed,
            RequestState,
        )

        with self._lock:
            if fr.finished or fr._inner is not inner:
                return
            if (fr.t_deadline is not None
                    and time.perf_counter() >= fr.t_deadline):
                self._c_shed.inc()
                self._finalize_locked(
                    fr, RequestState.ERRORED, DeadlineExceededError(
                        f"fleet request {fr.id} hit its {fr.deadline_s}s "
                        "deadline during replica failover"))
                return
            snaps = self._snapshots_locked()
            if (fr.reroutes >= self.max_reroutes
                    or not any(s.healthy for s in snaps)):
                failure = EngineFailed(
                    f"request {fr.id} drained from failed replica "
                    f"{fr.replica_id} with no healthy replica to take it")
                self._finalize_locked(fr, RequestState.ERRORED, failure)
                return
            t0 = time.perf_counter()
            decision = self._route_locked(fr.prompt, snaps,
                                          exclude=fr.replica_id)
            fr.reroutes += 1
            self._c_reroutes.inc()
            try:
                self._bind_locked(fr, decision, t0, rerouted=True)
            except Exception as bind_exc:  # noqa: BLE001
                failure = EngineFailed(
                    f"fleet re-route of request {fr.id} failed: "
                    f"{type(bind_exc).__name__}: {bind_exc}")
                failure.__cause__ = bind_exc
                self._finalize_locked(fr, RequestState.ERRORED, failure)

    # ------------------------------------------------------------------ #
    # weight lifecycle (the deploy layer's fleet surface)                 #
    # ------------------------------------------------------------------ #

    def publish(self, params, *, step: Optional[int] = None,
                timeout: float = 60.0, canary: Optional[int] = None,
                exclude: Sequence = ()) -> dict:
        """Rolling weight publish: swap ``params`` into every replica,
        ONE at a time. While a replica is fenced (draining its in-flight
        work before the swap), routing steers new submissions to its
        peers — the fleet keeps serving at N-1 capacity through each
        window, and every accepted request completes on the weights it
        started with. A replica that fails its swap (or is quarantined)
        is recorded and skipped; the roll continues, so one bad replica
        never wedges the deployment. Returns a per-replica outcome dict;
        ``ok`` is True only when every targeted accepting replica took
        the new version.

        ``canary=rid`` swaps EXACTLY that one replica (the control
        plane's canary path: blast radius 1/N for one bake window);
        ``exclude=(rid, ...)`` rolls everyone else (the promote path —
        the canary already carries the new version). The two are
        mutually exclusive."""
        from chainermn_tpu.deploy.publish import WeightPublisher

        if canary is not None and exclude:
            raise ValueError("publish: canary= and exclude= are mutually "
                             "exclusive")
        if canary is not None:
            targets = [self.replicas[int(canary)]]
        else:
            skip = {int(i) for i in exclude}
            targets = [r for r in list(self.replicas)
                       if r.replica_id not in skip]
        results: dict[str, dict] = {}
        for replica in targets:
            rid = replica.replica_id
            if not replica.accepting:
                results[str(rid)] = {"ok": False,
                                     "skipped": replica.state.value}
                continue
            with self._lock:
                self._publishing.add(rid)
            try:
                publisher = WeightPublisher(replica.engine,
                                            replica.scheduler)
                # the replica's own drive loop keeps stepping through the
                # fence (has_work includes the pending swap), so blocking
                # here is safe — this thread never drives that scheduler
                handle = publisher.publish_async(params, step=step)
                version = handle.wait(timeout)
                results[str(rid)] = {
                    "ok": True, "version": version,
                    "commit_s": round(handle.commit_s, 6),
                    "fence_s": round(handle.fence_s or 0.0, 6),
                }
            except Exception as e:  # noqa: BLE001 — roll past one failure
                results[str(rid)] = {"ok": False,
                                     "error": f"{type(e).__name__}: {e}"}
            finally:
                with self._lock:
                    self._publishing.discard(rid)
        ok = all(r.get("ok") for r in results.values()
                 if "skipped" not in r) and bool(results)
        self._events.emit("fleet_publish", ok=ok, canary=canary,
                          replicas={k: v.get("version", None)
                                    for k, v in results.items()})
        return {"ok": ok, "replicas": results}

    def spawn_replica(self, engine=None, *, checkpoint=None,
                      engine_factory=None, params_template=None,
                      comm=None, model=None,
                      wait_ready: bool = True,
                      timeout: float = 300.0) -> EngineReplica:
        """Bring one MORE replica into the fleet without stopping
        traffic — elastic scale-up and deployment in one mechanism.

        Either pass a constructed ``engine``, or a ``checkpoint``
        (:class:`~chainermn_tpu.extensions.sharded_checkpoint
        .ShardedCheckpointer`) plus ``engine_factory(params) ->
        ServingEngine`` and a like-sharded ``params_template``: the new
        replica's params come from :func:`~chainermn_tpu.deploy.reshard
        .elastic_restore` onto the template's mesh — which may be a
        DIFFERENT shape from both the snapshot's and the existing
        replicas' meshes. The replica warms up on its own thread and
        starts taking routed traffic once healthy; existing replicas
        never pause."""
        if engine is None:
            if checkpoint is None or engine_factory is None \
                    or params_template is None:
                raise ValueError(
                    "spawn_replica needs either engine= or all of "
                    "checkpoint=/engine_factory=/params_template=")
            from chainermn_tpu.deploy.reshard import elastic_restore

            state, ckpt_step = elastic_restore(
                checkpoint, {"params": params_template},
                comm=comm, model=model)
            if state is None:
                raise RuntimeError(
                    "spawn_replica: checkpoint has no snapshot to "
                    "restore from")
            engine = engine_factory(state["params"])
            self._events.emit("fleet_spawn_restore", step=ckpt_step)
        with self._lock:
            if self._closed:
                raise RuntimeError("fleet router is closed")
            rid = len(self.replicas)
            replica = EngineReplica(
                rid, engine, on_failure=self._on_replica_failure,
                labels=self._labels, autostart=True, **self._replica_cfg)
            self.replicas.append(replica)
        self._events.emit("fleet_spawn", replica=rid)
        if wait_ready:
            replica.ready.wait(timeout)
        return replica

    def retire_replica(self, replica_id: int, *,
                       timeout: float = 60.0) -> dict:
        """Gracefully take one replica OUT of the fleet — the clean
        scale-down actuator (quarantine is the failure-driven one).

        Sequence: the replica enters DRAINING (no longer accepting, its
        drive loop keeps stepping), its QUEUED work is drained and
        re-routed to peers (nothing ever started, nothing lost), in-
        flight requests finish on the weights they started with, then
        the thread stops and the replica lands RETIRED. If in-flight
        work outlives ``timeout`` the replica is hard-killed instead —
        the supervisor's drain-failure path re-routes the stragglers and
        quarantines (``forced=True`` in the result)."""
        replica = self.replicas[replica_id]
        rid = replica.replica_id
        replica.begin_retire()          # raises unless accepting
        with self._lock:
            # its prefix beliefs die with it: stop routing affinity
            # traffic at KV that is about to be released
            self._trie.drop_replica(rid)
        drained = replica.scheduler.drain_queued()
        drained_ids = {id(req) for req in drained}
        with self._lock:
            affected = [fr for fr in list(self._requests.values())
                        if fr.replica_id == rid and not fr.finished]
        for fr in affected:
            inner = fr._inner
            if inner is not None and id(inner) in drained_ids:
                self._rebind_drained(fr, inner)
        deadline = time.perf_counter() + timeout
        while replica.scheduler.has_work \
                and time.perf_counter() < deadline:
            time.sleep(0.002)
        forced = bool(replica.scheduler.has_work)
        if forced:
            # stragglers past the drain budget: the supervisor path
            # fails them over to peers and quarantines the replica
            replica.kill(ReplicaKilled(
                f"replica {rid} retire drain exceeded {timeout}s"))
        else:
            replica.finish_retire()
        self._events.emit("fleet_retire", replica=rid,
                          drained=len(drained), forced=forced)
        return {"replica": rid, "drained": len(drained), "forced": forced,
                "state": replica.state.value}

    # ------------------------------------------------------------------ #
    # observability                                                       #
    # ------------------------------------------------------------------ #

    def fleet_report(self) -> dict:
        """One JSON-able fleet view: per-replica state/occupancy/restarts,
        router counters (reroutes, sheds, affinity outcomes), and the
        replicas' latency/occupancy series POOLED with the same merge
        ``MetricsRegistry.aggregate(comm)`` uses across ranks — so
        ``pooled.histograms["serving_ttft_seconds"]`` carries the
        fleet-wide p50/p99, not one replica's."""
        replicas = {}
        for r in self.replicas:
            occ = r.engine.occupancy()
            replicas[str(r.replica_id)] = {
                "state": r.state.value,
                "restarts": r.restarts,
                "queue_depth": r.scheduler.queue_depth,
                "active_slots": occ["active_slots"],
                "n_slots": occ["n_slots"],
                "kv_free_frac": occ["kv_free_frac"],
                "recompiles_after_warmup":
                    sum(r.engine.recompiles.values()),
                "weight_version": occ.get("weight_version", 0),
                "requests_completed": r.metrics.requests_completed,
                "requests_errored": r.metrics.requests_errored,
            }
        pooled = merge_rank_payloads(
            [r.metrics.payload() for r in self.replicas])
        # per-tenant cost view pooled across replicas: a tenant's bill
        # is fleet-wide, not per-replica (conservation still holds —
        # the merge sums measured and attributed alike)
        cost_payloads = [r.metrics.costs.payload() for r in self.replicas
                         if getattr(r.metrics, "costs", None) is not None]
        costs = (merge_cost_payloads(cost_payloads)
                 if cost_payloads else None)
        hits = int(self._c_aff_hits.value)
        misses = int(self._c_aff_miss.value)
        with self._lock:
            hm = self._health
            ctrl = self._controller
            weights = dict(self._weights)
        for rid, w in weights.items():
            replicas.get(str(rid), {})["admission_weight"] = w
        health = hm.report() if hm is not None else None
        control = ctrl.report() if ctrl is not None else None
        overload = None
        if self.retry_budget is not None or self.breaker is not None:
            overload = {
                "retry_budget": (self.retry_budget.to_json()
                                 if self.retry_budget is not None else None),
                "breaker": (self.breaker.to_json()
                            if self.breaker is not None else None),
            }
        return {
            "health": health,
            "control": control,
            "costs": costs,
            "overload": overload,
            "replicas": replicas,
            "capacity": self.capacity,
            "n_replicas": len(self.replicas),
            "affinity": {
                "enabled": self.affinity,
                "hits": hits,
                "misses": misses,
                "hit_rate": round(hits / max(hits + misses, 1), 4),
                "trie_nodes": self._trie.n_nodes,
            },
            "kv_reuse": {
                "share_enabled": self.share_prefixes,
                "shares": int(self._c_shares.value),
                "rebalances": int(self._c_rebalances.value),
                "payload_cache": (self._share_cache.to_json()
                                  if self._share_cache is not None
                                  else None),
            },
            "tiers": (None if self._prefill_tier is None else {
                "prefill": sorted(self._prefill_tier),
                "decode": [r.replica_id for r in self.replicas
                           if r.replica_id not in self._prefill_tier],
            }),
            "requests_total": int(self._c_requests.value),
            "reroutes_total": int(self._c_reroutes.value),
            "shed_total": int(self._c_shed.value),
            "route_fallbacks_total": int(self._c_fallbacks.value),
            "pooled": pooled,
        }


__all__ = ["FleetRequest", "FleetRouter"]
