"""Pure host-side routing policy for the serving fleet.

The router's placement decision is two-signal (ISSUE 8 / ROADMAP item 1):

- **Prefix affinity** — a fleet-level trie (:class:`FleetTrie`) maps
  token-block prefixes to the replica whose engine-side
  ``PrefixCacheIndex``/``BlockPool`` holds them. A request whose prompt
  shares a cached prefix is worth routing to that replica: the hit is a
  spliced/shared admission that prefills only the uncached suffix
  (PR 5/7), which beats an idle-but-cold replica up to a point.
- **Occupancy-aware least-loaded** — per-replica queue depth, slot
  occupancy, and EWMA TTFT (:class:`ReplicaSnapshot`, read from each
  replica's scheduler/metrics/engine) rank the healthy replicas;
  affinity wins only while the holder's load stays within
  ``max_imbalance`` of the least-loaded candidate — a hot replica's
  cached prefix is NOT worth queueing behind (PERF.md "Fleet routing
  cost model" derives the crossover).

Everything here is deterministic, lock-free, engine-free host logic:
snapshots in, a :class:`RouteDecision` out, with ties broken by replica
id — so the policy is unit-testable against synthetic occupancy
snapshots (``tests/fleet_tests/test_routing.py``) without ever building
a device program.

This module must not import ``chainermn_tpu.extensions`` (or jax, or the
serving package) at module level — the fleet package obeys the monitor
subsystem's import-hygiene rule, pinned by
``tests/monitor_tests/test_import_hygiene.py``.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Optional, Sequence


@dataclass
class ReplicaSnapshot:
    """One replica's occupancy at routing time (host counters only).

    ``load`` is the admission-relevant pressure: requests queued or
    decoding, normalized by the slot pool so differently-sized replicas
    compare fairly. ``ttft_ewma_s`` breaks load ties toward the replica
    that has recently been fast; ``kv_free_frac`` lets a paged replica
    running low on blocks shed affinity traffic before it starts
    preempting. ``health`` is the telemetry verdict (0 healthy /
    1 degraded / 2 critical, from :class:`~chainermn_tpu.monitor.health.
    HealthMonitor` when the router has one attached): it outranks load,
    so a degraded replica is deprioritized while it can still serve —
    the step *before* the supervisor would quarantine it.
    ``admission_weight`` (0 < w <= 1) is the control plane's rebalance
    knob: shedding a replica's weight inflates its apparent load, so the
    policy sends it proportionally less traffic without ever making it
    unroutable — the step before even the health penalty."""

    replica_id: int
    healthy: bool = True
    queue_depth: int = 0
    active_slots: int = 0
    n_slots: int = 1
    ttft_ewma_s: float = 0.0
    kv_free_frac: float = 1.0
    health: int = 0
    admission_weight: float = 1.0

    @property
    def load(self) -> float:
        # the epsilon keeps the weight effective at zero occupancy (an
        # idle shed replica still loses ties to an idle full-weight
        # peer); at weight 1.0 it is a constant offset and cancels out
        # of every comparison the policy makes
        raw = (self.queue_depth + self.active_slots) / max(self.n_slots, 1)
        return (raw + 1e-3) / max(self.admission_weight, 1e-6)


@dataclass
class RouteDecision:
    """Where one request goes and why (the ``route`` span's labels)."""

    replica_id: int
    affinity_hit: bool = False
    affinity_blocks: int = 0
    reason: str = "least_loaded"


class RoutingPolicy:
    """Two-signal placement over healthy-replica snapshots.

    Parameters
    ----------
    affinity : bool
        Consult the fleet trie at all. Off = pure least-loaded.
    max_imbalance : float
        How much MORE normalized load the affinity holder may carry than
        the least-loaded healthy replica before the cached prefix stops
        being worth it (in ``load`` units: queued+active per slot).
    min_affinity_blocks : int
        Minimum resident prefix blocks for affinity to outrank load —
        a one-block match rarely pays for imbalance.
    min_kv_free_frac : float
        A paged replica below this free-block fraction is skipped by
        affinity (admission there would likely defer or preempt).
    """

    def __init__(self, *, affinity: bool = True, max_imbalance: float = 1.0,
                 min_affinity_blocks: int = 1,
                 min_kv_free_frac: float = 0.05) -> None:
        self.affinity = bool(affinity)
        self.max_imbalance = float(max_imbalance)
        self.min_affinity_blocks = int(min_affinity_blocks)
        self.min_kv_free_frac = float(min_kv_free_frac)

    @staticmethod
    def _key(snap: ReplicaSnapshot) -> tuple:
        # deterministic total order: health verdict first (a degraded
        # replica loses to ANY healthy one regardless of load), then
        # load, then recent speed, then id — equal replicas always
        # resolve to the lowest id
        return (snap.health, snap.load, snap.ttft_ewma_s, snap.replica_id)

    def least_loaded(self, snapshots: Sequence[ReplicaSnapshot]
                     ) -> Optional[ReplicaSnapshot]:
        healthy = [s for s in snapshots if s.healthy]
        if not healthy:
            return None
        return min(healthy, key=self._key)

    def route(self, snapshots: Sequence[ReplicaSnapshot],
              affinity_replica: Optional[int] = None,
              affinity_blocks: int = 0) -> Optional[RouteDecision]:
        """Pick a replica; ``None`` when no healthy replica exists.
        ``affinity_replica``/``affinity_blocks`` come from the fleet
        trie's longest-holder lookup (``None``/0 on a miss)."""
        base = self.least_loaded(snapshots)
        if base is None:
            return None
        if (self.affinity and affinity_replica is not None
                and affinity_blocks >= self.min_affinity_blocks):
            holder = next((s for s in snapshots
                           if s.replica_id == affinity_replica and s.healthy),
                          None)
            if (holder is not None
                    and holder.health <= base.health
                    and holder.kv_free_frac >= self.min_kv_free_frac
                    and holder.load - base.load <= self.max_imbalance):
                return RouteDecision(holder.replica_id, affinity_hit=True,
                                     affinity_blocks=affinity_blocks,
                                     reason="affinity")
        return RouteDecision(base.replica_id, affinity_hit=False,
                             reason="least_loaded")

    def migration_targets(self, snapshots: Sequence[ReplicaSnapshot]
                          ) -> list[ReplicaSnapshot]:
        """Rank decode-tier candidates for a KV handover: healthy
        replicas with block headroom, best (least-loaded) first. The
        caller walks the list until one accepts the payload — a ranking,
        not a single pick, because import capacity (free slots, exact
        block budget) is only known engine-side at handover time.
        Affinity plays no part: the migrated request's prefix KV travels
        WITH it, so there is nothing cached to seek out."""
        fit = [s for s in snapshots
               if s.healthy and s.kv_free_frac >= self.min_kv_free_frac]
        return sorted(fit, key=self._key)

    @staticmethod
    def overloaded(snapshots: Sequence[ReplicaSnapshot],
                   max_queue: Optional[int]) -> bool:
        """Fleet-edge admission gate: total work queued across healthy
        replicas has reached the global bound — shed at the edge (the
        PR 3 backpressure stance: reject at submit, don't bury the
        request in a queue it will expire in)."""
        if max_queue is None:
            return False
        depth = sum(s.queue_depth for s in snapshots if s.healthy)
        return depth >= max_queue


class _TrieNode:
    __slots__ = ("key", "parent", "children", "replicas", "last_use")

    def __init__(self, key, parent):
        self.key = key
        self.parent = parent
        self.children: dict = {}
        self.replicas: dict[int, int] = {}   # replica_id -> last_use clock
        self.last_use = 0


class FleetTrie:
    """The router's belief of which replica caches which prompt prefix.

    A host-only trie over ``block_size``-token keys (the same granularity
    as the engines' :class:`~chainermn_tpu.serving.prefix_cache.
    PrefixCacheIndex`, so a fleet hit corresponds to a real engine-side
    block match). Each node records the replicas believed to hold that
    block; :meth:`note` is called at routing time (the chosen replica
    will cache the prompt on admission), :meth:`drop_replica` when a
    replica restarts or quarantines (its engine trie was cleared with its
    store — believing otherwise would route traffic at KV that no longer
    exists). It is a belief, not ground truth: an engine-side LRU
    eviction the router missed just downgrades a would-be hit to a plain
    suffix prefill — correctness never depends on this index.

    ``max_nodes`` bounds memory: inserts past the cap evict the
    least-recently-used leaves first (same stance as the engine trie).
    Single-threaded by design — the router serializes all calls under its
    own lock.
    """

    def __init__(self, block_size: int, max_nodes: int = 8192) -> None:
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.block_size = int(block_size)
        self.max_nodes = int(max_nodes)
        self._root = _TrieNode(None, None)
        self._n_nodes = 0
        self._clock = itertools.count(1)

    def _key(self, tokens, i: int) -> tuple:
        bs = self.block_size
        return tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])

    def note(self, tokens, replica_id: int) -> int:
        """Record that ``replica_id`` (now) holds every full block of
        ``tokens``; returns blocks noted. Walks/extends the path,
        stamping the replica on each node."""
        tokens = list(tokens)
        total = len(tokens) // self.block_size
        t = next(self._clock)
        node = self._root
        for i in range(total):
            key = self._key(tokens, i)
            child = node.children.get(key)
            if child is None:
                self._evict_to_fit(protect=node)
                child = _TrieNode(key, node)
                node.children[key] = child
                self._n_nodes += 1
            child.replicas[int(replica_id)] = t
            child.last_use = t
            node = child
        return total

    def lookup(self, tokens) -> tuple[Optional[int], int]:
        """``(replica_id, blocks)`` of the longest believed-resident
        prefix — the replica covering the DEEPEST consecutive path from
        the root (ties: most recently stamped, then lowest id). ``(None,
        0)`` on a miss."""
        tokens = list(tokens)
        total = len(tokens) // self.block_size
        depth_by: dict[int, int] = {}
        stamp_by: dict[int, int] = {}
        alive: Optional[set] = None
        node = self._root
        t = next(self._clock)
        for i in range(total):
            child = node.children.get(self._key(tokens, i))
            if child is None:
                break
            present = set(child.replicas)
            alive = present if alive is None else (alive & present)
            if not alive:
                break
            child.last_use = t
            for rid in alive:
                depth_by[rid] = i + 1
                stamp_by[rid] = child.replicas[rid]
            node = child
        if not depth_by:
            return None, 0
        best = max(depth_by,
                   key=lambda r: (depth_by[r], stamp_by[r], -r))
        return best, depth_by[best]

    def forget(self, tokens, replica_id: int) -> int:
        """Drop ``replica_id``'s stamps along the full-block path of
        ``tokens`` — the surgical inverse of :meth:`note`, for when ONE
        prompt's blocks left a replica (KV migration / decode rebalance
        handed them to a peer) while the rest of its cache stayed put.
        Without this the trie keeps routing affinity traffic at the
        exporter for KV that now lives elsewhere (the disaggregation
        staleness bug). Prunes holder-less childless tail nodes; returns
        blocks forgotten."""
        rid = int(replica_id)
        tokens = list(tokens)
        total = len(tokens) // self.block_size
        path = []
        node = self._root
        for i in range(total):
            child = node.children.get(self._key(tokens, i))
            if child is None:
                break
            path.append(child)
            node = child
        forgotten = 0
        for node in reversed(path):
            if node.replicas.pop(rid, None) is not None:
                forgotten += 1
            if not node.replicas and not node.children:
                del node.parent.children[node.key]
                self._n_nodes -= 1
        return forgotten

    def drop_replica(self, replica_id: int) -> int:
        """Forget everything attributed to ``replica_id`` (its engine's
        trie/store was just rebuilt); prunes nodes left holder-less.
        Returns nodes pruned."""
        rid = int(replica_id)
        pruned = 0
        stack = [self._root]
        order = []
        while stack:
            node = stack.pop()
            order.append(node)
            stack.extend(node.children.values())
        # leaves first, so emptied chains unzip bottom-up
        for node in reversed(order):
            if node is self._root:
                continue
            node.replicas.pop(rid, None)
            if not node.replicas and not node.children:
                del node.parent.children[node.key]
                self._n_nodes -= 1
                pruned += 1
        return pruned

    def _evict_to_fit(self, protect=None) -> None:
        while self._n_nodes >= self.max_nodes:
            leaves = []
            stack = [self._root]
            while stack:
                node = stack.pop()
                if (node is not self._root and not node.children
                        and node is not protect):  # never unzip the path
                    leaves.append(node)            # being extended
                stack.extend(node.children.values())
            if not leaves:
                return
            victim = min(leaves, key=lambda nd: nd.last_use)
            del victim.parent.children[victim.key]
            self._n_nodes -= 1

    @property
    def n_nodes(self) -> int:
        return self._n_nodes


__all__ = [
    "FleetTrie",
    "ReplicaSnapshot",
    "RouteDecision",
    "RoutingPolicy",
]
