"""``chainermn_tpu.fleet`` — multi-replica serving: N engines, one
service.

The serving package (PR 1-7) ends at ONE
:class:`~chainermn_tpu.serving.engine.ServingEngine`: one slot pool, one
mesh, one failure domain. This package is the coordination tier above it
— the paper's thesis (many identical workers behind a thin coordination
layer) applied to the serving side:

- :mod:`~chainermn_tpu.fleet.routing` — pure host policy:
  :class:`RoutingPolicy` (prefix affinity vs occupancy-aware
  least-loaded, deterministic tie-breaks, fleet-edge admission math) and
  :class:`FleetTrie` (the router's belief of which replica caches which
  prompt prefix);
- :mod:`~chainermn_tpu.fleet.replica` — :class:`EngineReplica`: one
  engine + scheduler on its own thread, under a supervisor that drains,
  warm-restarts, or quarantines a failed replica (PR 3's exception
  boundary, one level up);
- :mod:`~chainermn_tpu.fleet.router` — :class:`FleetRouter`: the
  ``submit``/``wait``/``stream`` front with prefix-affinity routing,
  global ``max_queue`` shedding, replica failover with replayed
  re-routes (stream-dedup'd — a consumer sees a seamless continuation),
  and fleet-pooled observability (``/fleet`` via
  ``monitor.http.serve(fleet=router)``);
- :mod:`~chainermn_tpu.fleet.control` — :class:`FleetController`: the
  closed control loop over the telemetry pipeline (ISSUE 16) —
  autoscaling with hysteresis, SLO-guarded canary deploys with
  auto-rollback, and pre-quarantine admission rebalancing
  (``/control`` via ``monitor.http.serve(controller=...)``).

Correctness invariants (pinned in ``tests/fleet_tests``): a fleet serves
a mixed prefix-heavy workload token-for-token equal to solo
``generate()``; killing one replica mid-stream loses zero accepted
requests (re-routed or cleanly ERRORED per deadline policy); and
``recompiles_after_warmup == 0`` holds on every surviving replica.

Import hygiene: fleet modules import the serving/resilience/extensions
stack lazily (inside functions), never at module level — the same rule
as ``chainermn_tpu.monitor``, pinned by
``tests/monitor_tests/test_import_hygiene.py``.
"""

from chainermn_tpu.fleet.control import (
    AutoscalePolicy,
    CanaryPolicy,
    FleetController,
    RebalancePolicy,
)
from chainermn_tpu.fleet.overload import (
    RetryBudget,
    TenantBreaker,
)
from chainermn_tpu.fleet.replica import (
    EngineReplica,
    ReplicaHang,
    ReplicaKilled,
    ReplicaState,
)
from chainermn_tpu.fleet.router import FleetRequest, FleetRouter
from chainermn_tpu.fleet.routing import (
    FleetTrie,
    ReplicaSnapshot,
    RouteDecision,
    RoutingPolicy,
)
from chainermn_tpu.fleet.share import SharePayloadCache

__all__ = [
    "AutoscalePolicy",
    "CanaryPolicy",
    "EngineReplica",
    "FleetController",
    "FleetRequest",
    "FleetRouter",
    "FleetTrie",
    "RebalancePolicy",
    "ReplicaHang",
    "ReplicaKilled",
    "ReplicaSnapshot",
    "ReplicaState",
    "RetryBudget",
    "RouteDecision",
    "RoutingPolicy",
    "SharePayloadCache",
    "TenantBreaker",
]
