"""Host-side cache of exported prefix-KV payloads (fleet KV reuse).

When cross-replica prefix sharing is on, the router exports a hot
prefix's KV blocks from the replica that holds them and imports them
into whichever replica the load balancer actually picked. The export is
the expensive half (a device gather + host bounce on the holder's drive
thread); the import is cheap and local. This cache closes the loop: the
payload from ONE export is kept host-side, ref-count pinned while a
submission is importing from it, and served to every later request that
shares the prefix — a fleet-popular system prompt is exported once and
imported everywhere, so the holder pays the gather once no matter how
many peers adopt the blocks.

Entries are keyed by the exact covered-prefix token tuple;
:meth:`match` returns the LONGEST entry whose tokens are a prefix of
the query (same longest-match stance as the engine trie). Eviction is
LRU over unpinned entries, bounded by ``max_entries`` — payloads are
the largest host objects the fleet holds (``2 * layers * blocks *
block_size * heads * d_head`` elements each), so the bound is small and
deliberate.

Host-only, numpy-only: this module must not import jax, the serving
package, or ``chainermn_tpu.extensions`` at module level (the fleet
package import-hygiene rule, pinned by
``tests/monitor_tests/test_import_hygiene.py``).
"""

from __future__ import annotations

import itertools
from typing import Optional

from chainermn_tpu.analysis import sanitizer
from chainermn_tpu.monitor._state import get_registry


class ShareEntry:
    """One cached export: the payload plus its pin count. Pinned entries
    (a submission is mid-import from them) never evict."""

    __slots__ = ("payload", "pins", "last_use", "imports")

    def __init__(self, payload: dict) -> None:
        self.payload = payload
        self.pins = 0
        self.last_use = 0
        self.imports = 0

    @property
    def tokens(self) -> tuple:
        return tuple(int(t) for t in self.payload["tokens"])

    @property
    def n_blocks(self) -> int:
        return int(self.payload["n_blocks"])


class SharePayloadCache:
    """Ref-counted LRU over exported prefix payloads (module docstring).

    Thread-safe under its own leaf lock — callers hold NO router lock
    across these calls (the share handshake runs outside it)."""

    def __init__(self, max_entries: int = 8,
                 labels: Optional[dict] = None) -> None:
        if max_entries < 1:
            raise ValueError(
                f"max_entries must be >= 1, got {max_entries}")
        self.max_entries = int(max_entries)
        self._lock = sanitizer.make_lock(
            "SharePayloadCache._lock", leaf=True)
        self._entries: dict[tuple, ShareEntry] = sanitizer.guarded(
            {}, lock=self._lock, name="SharePayloadCache._entries")
        self._clock = itertools.count(1)
        reg = get_registry()
        labels = dict(labels or {})
        self._c_hits = reg.counter(
            "share_payload_cache_hits_total", labels)
        self._c_evict = reg.counter(
            "share_payload_cache_evictions_total", labels)

    def put(self, payload: dict) -> ShareEntry:
        """Cache one exported payload (idempotent per covered prefix —
        a racing second export just refreshes recency) and return its
        entry PINNED; the caller imports from it then :meth:`release`\\s.
        """
        entry = ShareEntry(payload)
        key = entry.tokens
        with self._lock:
            existing = self._entries.get(key)
            if existing is not None:
                entry = existing
                evicted = 0
            else:
                evicted = self._evict_to_fit_locked()
                self._entries[key] = entry
            entry.pins += 1
            entry.last_use = next(self._clock)
        # counter locks are never taken under the cache's leaf lock
        for _ in range(evicted):
            self._c_evict.inc()
        return entry

    def match(self, tokens) -> Optional[ShareEntry]:
        """Longest cached entry whose covered prefix is a prefix of
        ``tokens``, PINNED (counted as a cache hit), or None."""
        query = tuple(int(t) for t in tokens)
        with self._lock:
            best = None
            for key, entry in self._entries.items():
                if len(key) <= len(query) and query[:len(key)] == key:
                    if best is None or len(key) > len(best.tokens):
                        best = entry
            if best is None:
                return None
            best.pins += 1
            best.last_use = next(self._clock)
        self._c_hits.inc()
        return best

    def release(self, entry: ShareEntry, *, imported: bool = False) -> None:
        """Unpin one :meth:`put`/:meth:`match` reference; ``imported``
        marks a completed adoption (reported per entry)."""
        with self._lock:
            entry.pins = max(0, entry.pins - 1)
            if imported:
                entry.imports += 1

    def _evict_to_fit_locked(self) -> int:
        evicted = 0
        while len(self._entries) >= self.max_entries:
            victims = [(e.last_use, k) for k, e in self._entries.items()
                       if e.pins == 0]
            if not victims:
                break           # everything pinned: grow past the bound
            _, key = min(victims)
            del self._entries[key]
            evicted += 1
        return evicted

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def to_json(self) -> dict:
        with self._lock:
            out = {
                "entries": len(self._entries),
                "max_entries": self.max_entries,
                "pinned": sum(1 for e in self._entries.values()
                              if e.pins > 0),
                "blocks_cached": sum(e.n_blocks
                                     for e in self._entries.values()),
                "imports": sum(e.imports
                               for e in self._entries.values()),
            }
        out["hits"] = int(self._c_hits.value)
        out["evictions"] = int(self._c_evict.value)
        return out


__all__ = ["ShareEntry", "SharePayloadCache"]
