"""The fleet control plane: sensors -> policies -> actuators, closed.

PR 15 built the fleet's nervous system (time-series store, detectors,
per-replica health scores, SLO burn rates) and earlier PRs built the
actuators (``spawn_replica``, quarantine, drain, ``FleetRouter.
publish``); nothing connected them. :class:`FleetController` is that
connection — a deterministic control loop (same daemon-thread +
``tick(now=)`` design as the monitor's :class:`~chainermn_tpu.monitor.
timeseries.Collector`) that reads the telemetry pipeline and drives
three policies:

- **Autoscaling** (:class:`AutoscalePolicy`): sustained queue-depth
  breach or SLO burn scales UP via ``spawn_replica`` (the new replica
  warms in parallel and is synced to the fleet's current weight
  version); sustained idleness scales DOWN via the graceful
  ``retire_replica`` drain. Hysteresis (``up_after_s`` /
  ``down_after_s``), a post-action ``cooldown_s``, and hard
  ``min_replicas``/``max_replicas`` bounds keep a noisy signal from
  flapping the fleet.
- **SLO-guarded canary deploys** (:class:`CanaryPolicy`):
  :meth:`FleetController.deploy` swaps EXACTLY ONE replica
  (``FleetRouter.publish(canary=rid)`` — blast radius 1/N for one bake
  window), compares its health score and the SLO verdict against the
  fleet baseline over ``bake_s``, then either PROMOTES (rolling swap of
  the rest, the canary excluded — it already carries the new version)
  or AUTO-ROLLBACKS: every accepting replica is re-published onto the
  pre-canary weights and the controller's :class:`~chainermn_tpu.
  deploy.versions.VersionLog` records the reversal at
  ``rollback_target()``. A canary that dies mid-bake aborts cleanly
  (peers never saw the new weights — nothing to undo); a commit fault
  during the promote roll triggers the same rollback, so a
  partially-rolled fleet converges back to one version.
- **Pre-quarantine rebalancing** (:class:`RebalancePolicy`): a replica
  scoring DEGRADED (not critical — the supervisor owns that) has its
  admission weight shed, so routing sends it proportionally less
  traffic while it recovers; the weight is restored the tick it scores
  healthy again.

Every decision is an edge-triggered, cataloged flight-recorder event
(``controller_scale_up`` / ``controller_scale_down`` /
``controller_rebalance`` / ``canary_start`` / ``canary_promote`` /
``canary_rollback``) that NAMES the triggering signals, mirrored into
counters/gauges, and surfaced through :meth:`report` — which
``FleetRouter.fleet_report`` embeds under ``"control"`` and
``monitor.http.serve(controller=...)`` exposes at ``/control``.

Locking: the controller's own lock is a ``sanitizer.make_lock`` LEAF
guarding only the report-visible state (canary record, decision ring,
pending deploy). Policy work runs on the tick thread (single ticker by
contract, like the Collector) and every router/collector call happens
OUTSIDE the lock — the controller calls into the router, never the
reverse, so no lock-order cycle can exist.

This module must not import ``chainermn_tpu.extensions`` (or jax, or
the serving package) at module level — pinned by
``tests/monitor_tests/test_import_hygiene.py``.
"""

from __future__ import annotations

import itertools
import sys
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass
from typing import Callable, Optional

from chainermn_tpu.analysis import sanitizer
from chainermn_tpu.deploy.versions import VersionLog
from chainermn_tpu.monitor._state import get_event_log, get_registry


@dataclass
class AutoscalePolicy:
    """When and how far to scale (all thresholds in sensor units).

    Pressure = queued work per accepting replica above ``queue_high``,
    or (``burn_gate``) the SLO engine reporting non-compliance. Pressure
    sustained for ``up_after_s`` spawns one replica; NO pressure and
    fleet load at/below ``idle_low`` sustained for ``down_after_s``
    retires one. ``cooldown_s`` separates consecutive scale actions so
    the previous action's effect is observed before the next."""

    min_replicas: int = 1
    max_replicas: int = 4
    queue_high: float = 4.0
    idle_low: float = 0.25
    up_after_s: float = 1.0
    down_after_s: float = 5.0
    cooldown_s: float = 2.0
    burn_gate: bool = True


@dataclass
class CanaryPolicy:
    """Bake-window guard for one-replica canary deploys: during
    ``bake_s`` the canary must not score WORSE than the healthiest
    interpretation of the fleet baseline (its peers' worst level at
    evaluation time, or the baseline captured at canary start —
    whichever is higher), and (``slo_gate``) the SLO engine must not
    newly breach while it bakes."""

    bake_s: float = 5.0
    slo_gate: bool = True


@dataclass
class RebalancePolicy:
    """Admission weight applied to DEGRADED (level 1) replicas — shed
    before the supervisor would ever consider quarantine — plus the
    mid-stream decode-migration actuator (ISSUE 20): weight shedding
    only steers NEW traffic, so a replica already full of long decodes
    stays hot for minutes; ``migrate_decode`` moves one live decode
    slot per tick off the most-pressured replica (degraded outranks
    loaded; normalized load gap at least ``migrate_load_gap``) onto the
    least-loaded peer, token-exactly, through
    ``FleetRouter.rebalance_decode``."""

    degraded_weight: float = 0.25
    migrate_decode: bool = False
    migrate_load_gap: float = 1.0
    migrate_cooldown_s: float = 2.0


class _Canary:
    """One in-flight canary deploy (tick-thread state, report-copied)."""

    __slots__ = ("replica_id", "new_params", "old_params", "step",
                 "started_at", "version", "baseline_level",
                 "baseline_compliant")

    def __init__(self, replica_id, new_params, old_params, step,
                 started_at, version, baseline_level,
                 baseline_compliant) -> None:
        self.replica_id = replica_id
        self.new_params = new_params
        self.old_params = old_params
        self.step = step
        self.started_at = started_at
        self.version = version
        self.baseline_level = baseline_level
        self.baseline_compliant = baseline_compliant

    def to_json(self) -> dict:
        return {"replica": self.replica_id, "version": self.version,
                "started_at": self.started_at, "step": self.step,
                "baseline_level": self.baseline_level,
                "baseline_compliant": self.baseline_compliant}


_controller_ids = itertools.count()


class FleetController:
    """Closed-loop controller over one fleet (module docstring).

    Parameters
    ----------
    router : FleetRouter
        The fleet under control.
    collector : Collector
        The telemetry pipeline (normally from :func:`~chainermn_tpu.
        monitor.health.fleet_health`) — its store feeds the queue-depth
        sensor and its attached :class:`~chainermn_tpu.monitor.health.
        HealthMonitor` feeds the canary/rebalance verdicts.
    slo : SLOEngine, optional
        Burn-rate gate for both scale-up pressure and the canary bake.
    engine_factory : callable() -> ServingEngine, optional
        Builds the engine for each scale-up (an ``autoscale`` policy
        needs this, ``snapshot``, or both).
    snapshot : dict, optional
        Durable spawn source for scale-ups, forwarded verbatim to
        ``router.spawn_replica`` (keys ``checkpoint`` /
        ``engine_factory`` / ``params_template``, optionally ``comm`` /
        ``model``). When set, scale-up restores the new replica from
        the fleet's persisted snapshot — the weights every survivor of
        a crash would converge to — instead of whatever params the live
        factory closure captured at construction time. A failed
        snapshot load (corrupt file, injected fault) falls back to
        ``engine_factory`` when one is available, recorded as
        ``source="factory_fallback"`` on the decision.
    brownout : BrownoutPolicy, optional
        The serving tier's degradation ladder (shared with the replica
        schedulers). When present, sustained pressure steps brownout UP
        *before* spending a replica on scale-up — shedding load is
        cheap and instant, capacity is slow and finite — and a spawned
        replica turning ready steps it fully back DOWN
        (``relieve("capacity_arrived")``).
    autoscale / canary / rebalance : policy dataclasses or None
        ``None`` disables that policy entirely.
    cadence_s / clock : like the Collector — ``start()`` runs
        :meth:`tick` on a daemon thread; tests drive ``tick(now=)``.
    sensor_kw : dict, optional
        Forwarded to :func:`~chainermn_tpu.monitor.health.wire_replica`
        when wiring spawned replicas into the health pipeline (use the
        same values ``fleet_health`` was called with).
    """

    def __init__(self, router, collector, *, slo=None,
                 engine_factory: Optional[Callable] = None,
                 snapshot: Optional[dict] = None,
                 autoscale: Optional[AutoscalePolicy] = None,
                 canary: Optional[CanaryPolicy] = None,
                 rebalance: Optional[RebalancePolicy] = None,
                 brownout=None,
                 cadence_s: float = 0.5, clock=None,
                 sensor_kw: Optional[dict] = None,
                 publish_timeout_s: float = 60.0,
                 retire_timeout_s: float = 60.0,
                 registry=None, events=None) -> None:
        if cadence_s <= 0:
            raise ValueError(f"cadence_s must be > 0, got {cadence_s}")
        if (autoscale is not None and engine_factory is None
                and snapshot is None):
            raise ValueError(
                "an autoscale policy needs engine_factory= or snapshot= "
                "to build scale-up replicas")
        self.router = router
        self.collector = collector
        self.slo = slo
        self.autoscale = autoscale
        self.canary = canary
        self.rebalance = rebalance
        self.brownout = brownout
        self.cadence_s = float(cadence_s)
        self.log = VersionLog()          # fleet-level deploy audit trail
        self._engine_factory = engine_factory
        self._snapshot = dict(snapshot) if snapshot is not None else None
        self._sensor_kw = dict(sensor_kw or {})
        self._publish_timeout_s = float(publish_timeout_s)
        self._retire_timeout_s = float(retire_timeout_s)
        self._clock = clock if clock is not None else time.monotonic
        self._events = events if events is not None else get_event_log()
        self._registry = registry if registry is not None else get_registry()
        labels = {"controller": str(next(_controller_ids))}
        self._labels = labels
        reg = self._registry
        self._c_ticks = reg.counter("controller_ticks_total", labels)
        self._c_ups = reg.counter("controller_scale_ups_total", labels)
        self._c_downs = reg.counter("controller_scale_downs_total", labels)
        self._c_deploys = reg.counter("canary_deploys_total", labels)
        self._c_promotes = reg.counter("canary_promotes_total", labels)
        self._c_rollbacks = reg.counter("canary_rollbacks_total", labels)
        self._g_target = reg.gauge("controller_target_replicas", labels)
        self._g_phase = reg.gauge("controller_canary_phase", labels)
        # leaf: guards ONLY report-visible state; no call made under it
        # ever acquires another lock (enforced at runtime by leaf=True)
        self._lock = sanitizer.make_lock("FleetController._lock", leaf=True)
        self._canary: Optional[_Canary] = None
        self._pending_deploy: Optional[tuple] = None
        self._decisions: deque = deque(maxlen=32)
        self._last_outcome: Optional[dict] = None
        # tick-thread-private policy state (single ticker by contract)
        self._pressure_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_scale: Optional[float] = None
        self._last_decode_rebalance: Optional[float] = None
        self._target: Optional[int] = None
        self._fleet_version = 0
        self._params_current = None      # last PROMOTED params (sync src)
        self._pending_sync: list = []    # spawned replicas awaiting sync
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        router.attach_controller(self)

    # ------------------------------------------------------------------ #
    # the deploy entry point (any thread)                                 #
    # ------------------------------------------------------------------ #

    def deploy(self, params, *, step: Optional[int] = None) -> None:
        """Queue ``params`` for a canary deploy; the next tick starts
        the bake. One deploy in flight at a time — a second call while
        one is pending or baking raises."""
        if self.canary is None:
            raise RuntimeError(
                "controller has no canary policy (pass canary=)")
        with self._lock:
            if self._canary is not None or self._pending_deploy is not None:
                raise RuntimeError(
                    "a canary deploy is already in flight; wait for its "
                    "promote/rollback before deploying again")
            self._pending_deploy = (params, step)

    # ------------------------------------------------------------------ #
    # the control loop                                                    #
    # ------------------------------------------------------------------ #

    def tick(self, now: Optional[float] = None) -> dict:
        """One full sense -> decide -> act pass, deterministic under an
        injected ``now``. Returns a summary of the signals read and the
        actions taken (also kept in the decision ring for reports)."""
        now = self._clock() if now is None else float(now)
        summary = {"now": now, "actions": []}
        if getattr(self.router, "_closed", False):
            return summary
        sensors = self._read_sensors(now)
        summary["signals"] = sensors
        self._canary_tick(now, sensors, summary)
        self._autoscale_tick(now, sensors, summary)
        self._rebalance_tick(sensors, summary)
        self._sync_spawned(summary)
        self._c_ticks.inc()
        if self._target is not None:
            self._g_target.set(self._target)
        # graftlint: unguarded-ok — atomic reference read (writers lock)
        self._g_phase.set(0 if self._canary is None else 1)
        if summary["actions"]:
            with self._lock:
                self._decisions.extend(summary["actions"])
        return summary

    def start(self) -> "FleetController":
        """Run :meth:`tick` every ``cadence_s`` on a daemon thread
        (idempotent while running)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="chainermn-fleet-controller",
            daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)

    def __enter__(self) -> "FleetController":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — controller must not die
                print(f"chainermn_tpu.fleet: controller tick failed: "
                      f"{type(e).__name__}: {e}", file=sys.stderr,
                      flush=True)
            self._stop.wait(self.cadence_s)

    # ------------------------------------------------------------------ #
    # sensors                                                             #
    # ------------------------------------------------------------------ #

    def _read_sensors(self, now: float) -> dict:
        """The controller's inputs, in one dict: sampled queue depth
        (from the collector's store, cross-checked against the live
        scheduler depth — the gauge only moves when the replica's drive
        loop steps, so a stalled replica's sample freezes while its real
        queue grows; the max sees the growth), fleet load, SLO verdict,
        and the derived pressure signals. Reading the store/snapshots
        takes no controller lock."""
        store = self.collector.store
        accepting = [r for r in self.router.replicas if r.accepting]
        queued = active = slots = 0.0
        for r in accepting:
            key = (f'serving_queue_depth_now'
                   f'{{instance="{r.metrics.instance}"}}')
            last = store.last(key)
            live = float(r.scheduler.queue_depth)
            queued += (max(float(last[1]), live) if last is not None
                       else live)
            snap = r.snapshot()
            active += snap.active_slots
            slots += snap.n_slots
        n = max(len(accepting), 1)
        compliant, max_burn = True, 0.0
        if self.slo is not None:
            for entry in self.slo.evaluate(now).values():
                compliant = compliant and bool(entry.get("compliant", True))
                max_burn = max(max_burn,
                               float(entry.get("max_burn_rate", 0.0)))
        sensors = {
            "accepting": len(accepting),
            "queue_total": queued,
            "queue_per_replica": queued / n,
            "load": (queued + active) / max(slots, 1.0),
            "slo_compliant": compliant,
            "max_burn_rate": max_burn,
            "pressure": [],
        }
        p = self.autoscale
        if p is not None:
            if sensors["queue_per_replica"] > p.queue_high:
                sensors["pressure"].append("queue_depth")
            if p.burn_gate and not compliant:
                sensors["pressure"].append("slo_burn")
        return sensors

    @property
    def health(self):
        return self.collector.health

    def _level(self, replica_id) -> int:
        hm = self.health
        return hm.level(str(replica_id)) if hm is not None else 0

    # ------------------------------------------------------------------ #
    # policy 1: autoscaling                                               #
    # ------------------------------------------------------------------ #

    def _autoscale_tick(self, now: float, s: dict, summary: dict) -> None:
        p = self.autoscale
        if p is None:
            return
        # graftlint: unguarded-ok — atomic reference read (writers lock)
        if self._canary is not None:
            # a bake window compares the canary against a STABLE
            # baseline — resizing the fleet mid-bake would move it
            self._pressure_since = self._idle_since = None
            return
        capacity = s["accepting"]
        if self._target is None:
            self._target = capacity
        in_cooldown = (self._last_scale is not None
                       and now - self._last_scale < p.cooldown_s)
        pressure = bool(s["pressure"]) and capacity < p.max_replicas
        idle = (not s["pressure"] and s["load"] <= p.idle_low
                and capacity > p.min_replicas)
        if pressure:
            self._idle_since = None
            if self._pressure_since is None:
                self._pressure_since = now
            elif (now - self._pressure_since >= p.up_after_s
                  and not in_cooldown):
                if (self.brownout is not None
                        and not self.brownout.saturated):
                    self._brownout_up(now, s, summary)
                else:
                    self._scale_up(now, s, summary)
        elif idle:
            self._pressure_since = None
            if self._idle_since is None:
                self._idle_since = now
            elif (now - self._idle_since >= p.down_after_s
                  and not in_cooldown):
                self._scale_down(now, s, summary)
        else:
            self._pressure_since = self._idle_since = None

    def _top_tenant(self) -> Optional[str]:
        """Name the tenant consuming the most device time fleet-wide
        (the cost ledgers' view) — the 'who is driving this pressure'
        annotation on scale/rebalance decisions. None when no replica
        carries a ledger or nothing has been attributed yet."""
        totals: dict = {}
        for r in self.router.replicas:
            led = getattr(r.metrics, "costs", None)
            if led is None:
                continue
            for tenant, secs in led.tenant_device_seconds().items():
                totals[tenant] = totals.get(tenant, 0.0) + secs
        if not totals:
            return None
        return max(totals.items(), key=lambda kv: (kv[1], kv[0]))[0]

    def _brownout_up(self, now: float, s: dict, summary: dict) -> None:
        """Degrade before scaling: a brownout step is instant and free,
        a replica is slow and finite. The step counts as a scale action
        for cooldown purposes, so pressure must persist THROUGH the
        shed before real capacity is spent (the ``brownout_step`` event
        is emitted by the policy itself)."""
        prev = self.brownout.level
        self.brownout.step_up(
            "controller:" + "+".join(s["pressure"]), now=now)
        self._last_scale = now
        self._pressure_since = None
        action = {"action": "brownout", "direction": "up", "t": now,
                  "level": self.brownout.level, "prev": prev,
                  "signals": list(s["pressure"]),
                  "queue_per_replica": round(s["queue_per_replica"], 3)}
        summary["actions"].append(action)

    def _spawn_scaled_replica(self) -> tuple:
        """Scale-up spawn, snapshot-first: restore the new replica from
        the fleet's durable snapshot when one is configured — the
        crash-consistent weights — falling back to the live factory if
        the restore fails (corrupt/injected fault) and a factory
        exists. Returns ``(replica, source)``."""
        if self._snapshot is not None:
            try:
                return (self.router.spawn_replica(
                    wait_ready=False, **self._snapshot), "snapshot")
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                if self._engine_factory is None:
                    raise
                print("chainermn_tpu.fleet: snapshot spawn failed "
                      f"({type(e).__name__}: {e}); falling back to "
                      "engine_factory", file=sys.stderr, flush=True)
                return (self.router.spawn_replica(
                    engine=self._engine_factory(), wait_ready=False),
                    "factory_fallback")
        return (self.router.spawn_replica(
            engine=self._engine_factory(), wait_ready=False), "factory")

    def _scale_up(self, now: float, s: dict, summary: dict) -> None:
        replica, source = self._spawn_scaled_replica()
        self._last_scale = now
        self._pressure_since = None
        self._target = min(s["accepting"] + 1,
                           self.autoscale.max_replicas)
        hm = self.health
        if hm is not None:
            from chainermn_tpu.monitor.health import wire_replica

            wire_replica(self.collector, hm, replica, **self._sensor_kw)
        self._pending_sync.append(replica)
        self._c_ups.inc()
        # name the heaviest tenant in the decision record: "we scaled
        # up, and THIS workload is why" — the noisy-neighbor join key
        tt = self._top_tenant()
        tenant_kw = {} if tt is None else {"top_tenant": tt}
        action = {"action": "scale_up", "t": now,
                  "replica": replica.replica_id, "source": source,
                  "signals": list(s["pressure"]),
                  "queue_per_replica": round(s["queue_per_replica"], 3),
                  "capacity": s["accepting"], **tenant_kw}
        summary["actions"].append(action)
        self._events.emit("controller_scale_up",
                          replica=replica.replica_id, source=source,
                          signals=list(s["pressure"]),
                          queue_per_replica=round(
                              s["queue_per_replica"], 3),
                          capacity=s["accepting"], **tenant_kw)

    def _scale_down(self, now: float, s: dict, summary: dict) -> None:
        candidates = [r for r in self.router.replicas if r.accepting]
        if len(candidates) <= self.autoscale.min_replicas:
            return
        # least-loaded victim; ties retire the youngest replica
        victim = min(candidates,
                     key=lambda r: (r.snapshot().load, -r.replica_id))
        rid = victim.replica_id
        out = self.router.retire_replica(rid,
                                         timeout=self._retire_timeout_s)
        self._last_scale = now
        self._idle_since = None
        self._target = max(s["accepting"] - 1,
                           self.autoscale.min_replicas)
        self._pending_sync = [r for r in self._pending_sync
                              if r.replica_id != rid]
        hm = self.health
        if hm is not None:
            hm.unwatch(str(rid))
        self._c_downs.inc()
        action = {"action": "scale_down", "t": now, "replica": rid,
                  "signals": ["idle"], "load": round(s["load"], 3),
                  "forced": out["forced"], "capacity": s["accepting"]}
        summary["actions"].append(action)
        self._events.emit("controller_scale_down", replica=rid,
                          signals=["idle"], load=round(s["load"], 3),
                          forced=out["forced"], capacity=s["accepting"])

    def _sync_spawned(self, summary: dict) -> None:
        """Bring freshly-warm spawned replicas onto the fleet's current
        PROMOTED weights (their factory built them from the original
        params; after any promote those are stale)."""
        for replica in list(self._pending_sync):
            if not replica.ready.is_set():
                continue
            self._pending_sync.remove(replica)
            if (self.brownout is not None and self.brownout.level > 0):
                # the capacity brownout was standing in for has arrived:
                # unwind the whole ladder, not one step at a time
                prev = self.brownout.level
                self.brownout.relieve(now=summary["now"])
                summary["actions"].append(
                    {"action": "brownout", "direction": "relieve",
                     "t": summary["now"], "level": self.brownout.level,
                     "prev": prev, "replica": replica.replica_id})
            if not replica.accepting or self._params_current is None:
                continue
            self.router.publish(self._params_current,
                                canary=replica.replica_id,
                                timeout=self._publish_timeout_s)

    # ------------------------------------------------------------------ #
    # policy 2: SLO-guarded canary deploys                                #
    # ------------------------------------------------------------------ #

    def _canary_tick(self, now: float, s: dict, summary: dict) -> None:
        with self._lock:
            c = self._canary
            pending = None
            if c is None and self._pending_deploy is not None:
                pending, self._pending_deploy = self._pending_deploy, None
        if c is None:
            if pending is not None:
                self._start_canary(now, pending[0], pending[1], s, summary)
            return
        replica = self.router.replicas[c.replica_id]
        if not replica.accepting:
            # canary died mid-bake: its weights died with it, peers
            # never saw the new version — abort, nothing to republish
            self._rollback(now, c, summary, reason="canary_lost",
                           signals=[f"replica_state@{c.replica_id}"],
                           dirty=False)
            return
        signals = self._regression_signals(c, s)
        if signals:
            self._rollback(now, c, summary, reason="regression",
                           signals=signals, dirty=True)
            return
        if now - c.started_at >= self.canary.bake_s:
            self._promote(now, c, summary)

    def _start_canary(self, now: float, params, step, s: dict,
                      summary: dict) -> None:
        candidates = [r for r in self.router.replicas if r.accepting]
        if not candidates:
            self._events.emit("canary_rollback", replica=None,
                              reason="no_accepting_replica", signals=[])
            self._c_rollbacks.inc()
            return
        replica = min(candidates,
                      key=lambda r: (r.snapshot().load, r.replica_id))
        rid = replica.replica_id
        old_params = replica.engine.params
        out = self.router.publish(params, canary=rid, step=step,
                                  timeout=self._publish_timeout_s)
        if not out["ok"]:
            # the canary itself refused the new weights: the fleet never
            # left the old version — record the aborted attempt
            self._c_rollbacks.inc()
            action = {"action": "canary_rollback", "t": now,
                      "replica": rid, "reason": "canary_publish_failed",
                      "signals": []}
            summary["actions"].append(action)
            self._events.emit("canary_rollback", replica=rid,
                              reason="canary_publish_failed", signals=[])
            with self._lock:
                self._last_outcome = action
            return
        peers = [self._level(r.replica_id) for r in candidates
                 if r.replica_id != rid]
        self._fleet_version += 1
        version = self._fleet_version
        self.log.record(version, source="canary", step=step)
        c = _Canary(rid, params, old_params, step, now, version,
                    baseline_level=max(peers, default=0),
                    baseline_compliant=bool(s["slo_compliant"]))
        with self._lock:
            self._canary = c
        self._c_deploys.inc()
        action = {"action": "canary_start", "t": now, "replica": rid,
                  "version": version, "bake_s": self.canary.bake_s}
        summary["actions"].append(action)
        self._events.emit("canary_start", replica=rid, version=version,
                          bake_s=self.canary.bake_s, step=step)

    def _regression_signals(self, c: _Canary, s: dict) -> list:
        """Signals that damn the canary: its health level rose above
        both the live peer baseline and the start-of-bake baseline, or
        the SLO newly breached during the bake."""
        signals = []
        level = self._level(c.replica_id)
        peers = [self._level(r.replica_id) for r in self.router.replicas
                 if r.accepting and r.replica_id != c.replica_id]
        baseline = max(max(peers, default=0), c.baseline_level)
        if level >= 1 and level > baseline:
            signals.append(f"health@{c.replica_id}")
        if (self.canary.slo_gate and c.baseline_compliant
                and not s["slo_compliant"]):
            signals.append("slo_burn")
        return signals

    def _promote(self, now: float, c: _Canary, summary: dict) -> None:
        peers = [r for r in self.router.replicas
                 if r.accepting and r.replica_id != c.replica_id]
        ok = True
        if peers:
            out = self.router.publish(c.new_params,
                                      exclude=(c.replica_id,),
                                      step=c.step,
                                      timeout=self._publish_timeout_s)
            ok = out["ok"]
        if not ok:
            self._rollback(now, c, summary, reason="promote_failed",
                           signals=["publish_error"], dirty=True)
            return
        self.log.record(c.version, source="publish", step=c.step)
        self._params_current = c.new_params
        self._c_promotes.inc()
        action = {"action": "canary_promote", "t": now,
                  "replica": c.replica_id, "version": c.version,
                  "baked_s": round(now - c.started_at, 3)}
        summary["actions"].append(action)
        with self._lock:
            self._canary = None
            self._last_outcome = action
        self._events.emit("canary_promote", replica=c.replica_id,
                          version=c.version,
                          baked_s=round(now - c.started_at, 3))

    def _rollback(self, now: float, c: _Canary, summary: dict, *,
                  reason: str, signals: list, dirty: bool) -> None:
        """Converge every accepting replica back onto the pre-canary
        weights. ``dirty=False`` (canary lost) skips the republish — no
        surviving replica ever held the new version."""
        target = self.log.rollback_target()
        if dirty:
            # republish the OLD params fleet-wide: the canary (and any
            # peers a failed promote already rolled) step back; replicas
            # still on the old content take a same-content swap (a
            # pointer exchange — zero recompiles, nothing dropped)
            self.router.publish(c.old_params,
                                timeout=self._publish_timeout_s)
        self._fleet_version = target.version if target is not None else 0
        self.log.record(self._fleet_version, source="rollback")
        self._c_rollbacks.inc()
        action = {"action": "canary_rollback", "t": now,
                  "replica": c.replica_id, "reason": reason,
                  "signals": list(signals), "version": c.version,
                  "rolled_back_to": self._fleet_version}
        summary["actions"].append(action)
        with self._lock:
            self._canary = None
            self._last_outcome = action
        self._events.emit("canary_rollback", replica=c.replica_id,
                          reason=reason, signals=list(signals),
                          version=c.version,
                          rolled_back_to=self._fleet_version)

    # ------------------------------------------------------------------ #
    # policy 3: pre-quarantine rebalancing                                #
    # ------------------------------------------------------------------ #

    def _rebalance_tick(self, s: dict, summary: dict) -> None:
        p = self.rebalance
        if p is None:
            return
        if self.health is None:
            # weight shedding keys on the health verdict; the decode-
            # migration branch below is load-based and works without one
            self._migrate_tick(summary)
            return
        for r in self.router.replicas:
            if not r.accepting:
                continue
            rid = r.replica_id
            level = self._level(rid)
            want = p.degraded_weight if level == 1 else 1.0
            have = self.router.admission_weight(rid)
            if have == want:
                continue
            self.router.set_admission_weight(rid, want)
            self._registry.gauge(
                "fleet_admission_weight",
                dict(self._labels, replica=str(rid))).set(want)
            tt = self._top_tenant()
            tenant_kw = {} if tt is None else {"top_tenant": tt}
            action = {"action": "rebalance", "replica": rid,
                      "weight": want, "level": level, **tenant_kw}
            summary["actions"].append(action)
            self._events.emit("controller_rebalance", replica=rid,
                              weight=want, level=level, **tenant_kw)
        self._migrate_tick(summary)

    def _migrate_tick(self, summary: dict) -> None:
        """Decode-migration branch of the rebalance policy: weight
        shedding only steers NEW traffic, so this moves one LIVE decode
        slot per tick (cooldown-bounded) off the most-pressured replica
        — degraded verdict first, then normalized load — onto the
        least-loaded healthy peer. Fire-and-forget: the source's drive
        thread picks the cheapest victim and the router places it; every
        failure leaves the victim decoding where it is."""
        p = self.rebalance
        if p is None or not p.migrate_decode:
            return
        now = summary["now"]
        if (self._last_decode_rebalance is not None
                and now - self._last_decode_rebalance
                < p.migrate_cooldown_s):
            return
        snaps = [r.snapshot() for r in self.router.replicas if r.accepting]
        for s in snaps:
            s.health = self._level(s.replica_id)
        busy = [s for s in snaps if s.active_slots > 0]
        if len(snaps) < 2 or not busy:
            return
        src = max(busy, key=lambda s: (s.health, s.load, s.replica_id))
        peers = [s for s in snaps
                 if s.replica_id != src.replica_id and s.health == 0]
        if not peers:
            return
        dest = min(peers, key=lambda s: (s.load, s.replica_id))
        if src.health == 0 and src.load - dest.load < p.migrate_load_gap:
            return            # a healthy source must be LOPSIDED to move
        ticket = self.router.rebalance_decode(src.replica_id,
                                              dest.replica_id)
        if ticket is None:
            return
        self._last_decode_rebalance = now
        summary["actions"].append(
            {"action": "rebalance_decode", "t": now,
             "src": src.replica_id, "dest": dest.replica_id,
             "level": src.health})

    # ------------------------------------------------------------------ #
    # observability                                                       #
    # ------------------------------------------------------------------ #

    def report(self) -> dict:
        """The ``/control`` payload: policies, live phase, canary state,
        version history, admission weights, and the decision ring."""
        with self._lock:
            canary = self._canary
            pending = self._pending_deploy is not None
            decisions = list(self._decisions)
            last_outcome = self._last_outcome
        weights = {
            str(r.replica_id): self.router.admission_weight(r.replica_id)
            for r in self.router.replicas if r.accepting}
        cur = self.log.current
        return {
            "ticks": int(self._c_ticks.value),
            "phase": ("baking" if canary is not None
                      else "pending" if pending else "idle"),
            "target_replicas": self._target,
            "capacity": self.router.capacity,
            "autoscale": (dict(asdict(self.autoscale),
                               scale_ups=int(self._c_ups.value),
                               scale_downs=int(self._c_downs.value))
                          if self.autoscale is not None else None),
            "canary": ({"policy": asdict(self.canary),
                        "active": (canary.to_json()
                                   if canary is not None else None),
                        "last_outcome": last_outcome,
                        "deploys": int(self._c_deploys.value),
                        "promotes": int(self._c_promotes.value),
                        "rollbacks": int(self._c_rollbacks.value)}
                       if self.canary is not None else None),
            "rebalance": (dict(asdict(self.rebalance), weights=weights)
                          if self.rebalance is not None else None),
            "brownout": (self.brownout.to_json()
                         if self.brownout is not None else None),
            "versions": {
                "current": {"version": cur.version, "source": cur.source,
                            "step": cur.step},
                "history": [{"version": e.version, "source": e.source,
                             "step": e.step}
                            for e in self.log.history()],
            },
            "decisions": decisions,
        }


__all__ = [
    "AutoscalePolicy",
    "CanaryPolicy",
    "FleetController",
    "RebalancePolicy",
]
