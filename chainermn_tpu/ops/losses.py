"""Memory-lean losses: token-chunked softmax cross entropy.

The reference delegates losses to its host framework (SURVEY.md S0 — no
loss ops of its own); this op exists for the rebuild's long-context LM
flagship, where the LOSS — not the model — sets the memory ceiling: the
``[B*T, vocab]`` f32 logits and their gradient are the two largest
tensors in the whole train step (scripts/lm_roofline_aot.jsonl: at
T=2048 B=32, d=1024, V=32k the pair is ~17 GB — past a 16 GB v5e even
with block remat; full attention at B=8 cannot compile at all).

:func:`chunked_softmax_cross_entropy` fuses the LM head matmul with the
cross entropy under a custom VJP that processes tokens in chunks:

- forward: one ``[chunk, V]`` logits tile at a time -> per-token
  ``lse`` and target logit; the tile dies inside the ``lax.map`` body,
  so live memory is O(chunk * V) instead of O(B*T * V);
- backward: recomputes each tile from the saved ``lse`` (flash
  attention's trick applied to the vocabulary axis), forms
  ``dlogits = (softmax - onehot) * g`` tile-locally, and accumulates
  ``dhidden`` / ``dkernel`` / ``dbias`` in f32 — the full dlogits never
  exists either.

Numerics: matches ``optax.softmax_cross_entropy_with_integer_labels``
on the materialized logits to fp tolerance (pinned in tests, values and
grads); the matmul accumulates in f32 via ``preferred_element_type``
from storage-dtype operands, the same contract as the flash kernels.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_DEFAULT_CHUNK = 4096


def _pad_to_multiple(x, n, axis=0):
    pad = (-x.shape[axis]) % n
    if pad == 0:
        return x, 0
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths), pad


def _tile_logits(h_c, kernel, bias):
    """One chunk's f32 logits tile from storage-dtype operands."""
    lg = jax.lax.dot_general(
        h_c, kernel, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    if bias is not None:
        lg = lg + bias.astype(jnp.float32)
    return lg


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _chunked_ce(hidden, kernel, bias, targets, chunk):
    losses, _ = _ce_fwd_core(hidden, kernel, bias, targets, chunk)
    return losses


def _ce_fwd_core(hidden, kernel, bias, targets, chunk):
    n = hidden.shape[0]
    h_p, _ = _pad_to_multiple(hidden, chunk)
    t_p, _ = _pad_to_multiple(targets, chunk)
    n_chunks = h_p.shape[0] // chunk
    h_c = h_p.reshape(n_chunks, chunk, hidden.shape[1])
    t_c = t_p.reshape(n_chunks, chunk)

    def body(args):
        h_i, t_i = args
        lg = _tile_logits(h_i, kernel, bias)
        m = jnp.max(lg, axis=-1)
        lse = m + jnp.log(jnp.sum(jnp.exp(lg - m[:, None]), axis=-1))
        t_logit = jnp.take_along_axis(lg, t_i[:, None], axis=-1)[:, 0]
        return lse - t_logit, lse

    losses, lse = jax.lax.map(body, (h_c, t_c))
    return losses.reshape(-1)[:n], lse.reshape(-1)[:n]


def _ce_fwd(hidden, kernel, bias, targets, chunk):
    losses, lse = _ce_fwd_core(hidden, kernel, bias, targets, chunk)
    return losses, (hidden, kernel, bias, targets, lse)


def _ce_bwd(chunk, res, g):
    hidden, kernel, bias, targets, lse = res
    n, d = hidden.shape
    v = kernel.shape[1]
    h_p, _ = _pad_to_multiple(hidden, chunk)
    t_p, _ = _pad_to_multiple(targets, chunk)
    lse_p, _ = _pad_to_multiple(lse, chunk)
    # padded tokens carry zero cotangent -> contribute nothing anywhere
    g_p, _ = _pad_to_multiple(g.astype(jnp.float32), chunk)
    n_chunks = h_p.shape[0] // chunk
    h_c = h_p.reshape(n_chunks, chunk, d)
    t_c = t_p.reshape(n_chunks, chunk)
    lse_c = lse_p.reshape(n_chunks, chunk)
    g_c = g_p.reshape(n_chunks, chunk)

    def body(carry, args):
        dk_acc, db_acc = carry
        h_i, t_i, lse_i, g_i = args
        lg = _tile_logits(h_i, kernel, bias)
        p = jnp.exp(lg - lse_i[:, None])
        onehot = jax.nn.one_hot(t_i, v, dtype=jnp.float32)
        dlg = (p - onehot) * g_i[:, None]
        dh_i = jax.lax.dot_general(
            dlg.astype(kernel.dtype), kernel, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc = dk_acc + jax.lax.dot_general(
            h_i, dlg.astype(h_i.dtype), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        db_acc = db_acc + jnp.sum(dlg, axis=0)
        return (dk_acc, db_acc), dh_i

    # the zero init must carry the same varying-manner annotation as the
    # per-chunk updates or lax.scan rejects the carry under shard_map
    # (the train step pcasts params to varying); adding a data-derived
    # zero scalar transfers the vma without knowing the axes
    vma_zero = (g_c.ravel()[0] * 0.0 + h_c.ravel()[0].astype(jnp.float32)
                * 0.0)
    (dk, db), dh = jax.lax.scan(
        body,
        (jnp.zeros((d, v), jnp.float32) + vma_zero,
         jnp.zeros((v,), jnp.float32) + vma_zero),
        (h_c, t_c, lse_c, g_c))
    dh = dh.reshape(-1, d)[:n].astype(hidden.dtype)
    dbias = None if bias is None else db.astype(bias.dtype)
    return dh, dk.astype(kernel.dtype), dbias, None


_chunked_ce.defvjp(_ce_fwd, _ce_bwd)


def chunked_softmax_cross_entropy(hidden, kernel, bias, targets, *,
                                  chunk_size: int = _DEFAULT_CHUNK):
    """Per-token cross entropy of ``softmax(hidden @ kernel + bias)``
    against integer ``targets`` without materializing the logits.

    Args:
      hidden: ``[..., d]`` final hidden states (any float dtype; the
        logits tile accumulates in f32 from the storage dtype).
      kernel: ``[d, vocab]`` LM head weight (the flax ``Dense`` kernel).
      bias: ``[vocab]`` or None.
      targets: ``[...]`` integer ids, same leading shape as ``hidden``.
      chunk_size: tokens per logits tile; live memory is
        O(chunk_size * vocab) f32. The default (4096) costs a 0.5 GB
        tile at vocab 32k.

    Returns per-token f32 losses shaped like ``targets`` (the same
    contract as ``optax.softmax_cross_entropy_with_integer_labels``).
    Differentiable wrt hidden/kernel/bias via the chunked custom VJP.
    """
    lead = targets.shape
    d = hidden.shape[-1]
    losses = _chunked_ce(hidden.reshape(-1, d), kernel, bias,
                         targets.reshape(-1), int(chunk_size))
    return losses.reshape(lead)
