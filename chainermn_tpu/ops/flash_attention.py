"""Pallas TPU flash attention (forward + backward kernels).

The reference has no attention ops at all (SURVEY.md S2.16: it predates
them); this kernel is the TPU-native hot-op for the long-context extension
(:mod:`chainermn_tpu.parallel.sequence`). Design per the Pallas TPU guide:

- every kernel grids over ``(batch*heads, outer-seq-block,
  reduction-chunk)`` with the reduction chunk innermost; the running state
  (online-softmax (m, l, acc) forward; dq / (dk, dv) accumulators
  backward) lives in f32 VMEM scratch across the sweep and flushes to the
  output block once at the last chunk — attention scores are never
  materialized in HBM, so memory is O(T) instead of O(T^2);
- causal masking is computed from *global* positions: ``q_offset`` /
  ``k_offset`` arrive as SMEM scalars so sequence-sharded callers (ring
  attention shards, ``pos_offset`` in the LM) can pass traced offsets;
- fully-masked (future) chunks skip their COMPUTE via ``pl.when`` — the
  standard ~2x causal FLOP saving — and, when the offsets are static
  (the plain ``flash_attention`` LM path), their DMAs too: the
  streaming-side index maps clamp masked chunks to the previous chunk's
  block index, which Mosaic's pipeline elides (see ``_static_delta``).
  Ring shards pass traced offsets, where the ring layer's block-level
  masking decides which whole blocks to visit instead;
- backward is the standard two-kernel flash backward: ``dq`` gridded over
  q-blocks and ``(dk, dv)`` gridded over k-blocks, both recomputing scores
  from the saved row logsumexp (``lse``) instead of storing P;
- contractions accumulate in f32 (``preferred_element_type``) from bf16 or
  f32 inputs.

Numerical contract: identical to
:func:`chainermn_tpu.parallel.sequence.full_attention` (tested to fp
tolerance, values and grads). Off TPU the kernels run in Pallas interpret
mode, so the same code path is unit-testable on the CPU mesh.

All three kernels grid over BOTH sequence dims with the reduction dim
innermost and f32 VMEM scratch carrying the running state (online-softmax
m/l/acc forward; dq / dk+dv accumulators backward) — per-cell VMEM is
O(block_q + block_k) regardless of T. This structure is load-bearing:
the earlier form held full-length [T, d] K/V (or q/do) blocks per grid
cell, and XLA's scoped-VMEM accounting killed fwd+bwd compilation at
T >= 16384 on v5e; chunked, the same program AOT-compiles to T = 131072
(AOT-verified round 5, 8 heads, d=64 — HBM, not VMEM, is then the
binding limit, and beyond it the ring in
:mod:`chainermn_tpu.parallel.sequence` shards T across devices).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -1e30
# Row statistics (lse, delta) are stored lane-broadcast to this width so
# their blocks satisfy Mosaic's (8, 128) tiling rule — the same layout the
# reference jax.experimental.pallas TPU flash kernel uses for l/m.
_LANE = 128


def _smem_spec():
    """Spec for the (1, 1) int32 offset scalars (SMEM on TPU; the guide's
    'scalars must be 2D in SMEM' rule)."""
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _pick_block(t: int, preferred: int = 1024) -> int:
    """Largest hardware-legal divisor of ``t`` near ``preferred`` (kernel
    blocks must tile the sequence exactly; callers fall back to XLA
    otherwise). "Near": sub-8 requests on t > 8 round UP to the 8-row
    hardware minimum, so the result can exceed ``preferred``.

    The 1024 default is measured, not guessed: the round-5 on-chip sweep
    (scripts/flash_tune.py -> scripts/flash_tune.jsonl, v5e, bf16 fwd+bwd,
    causal) is monotonic in block size at both T=4096 and T=8192 —
    28.3 TFLOP/s at block 1024 vs 18.0 (512) / 6.7 (128) at T=8192.
    Per-cell fixed work (mask iota, scratch flush, grid bookkeeping)
    amortizes over more MXU work, and VMEM per cell stays O(block) —
    ~3 MB at block 1024, d=64, far under the ~128 MB budget. The
    T = 131072 single-call ceiling is AOT-verified at every block in
    {128, 256, 512, 1024} with the post-round-5 kernels (clamped causal
    maps, storage-dtype MXU inputs): 3.25 GB peak at each
    (scripts/aot_flash_ceiling.jsonl).

    Blocks respect the 8-row sublane granularity (Mosaic's (8, 128)
    tiling rule): candidates step down in multiples of 8, and a length
    with no such divisor returns 1, which is below every caller's
    usable-block floor — flash_attention falls back to XLA, ring callers
    raise their pad-the-shard error. (The pre-round-5 picker accepted any
    divisor, so e.g. t=251 with a >=251 preferred would have produced one
    251-row block that only works in interpret mode.) A sub-8 ``preferred``
    on a t > 8 sequence rounds UP to the hardware-minimum 8-row block
    (a 4-row block cannot tile on the MXU regardless of the request);
    t <= 8 keeps the plain largest-divisor-<=-preferred search (tiny test
    shapes, where interpret mode has no tiling rule)."""
    step = 1 if t <= 8 else 8
    m = min(preferred, t)
    b = max(step, m - m % step)
    while b >= step and t % b:
        b -= step
    return b if b >= step else 1


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _default_block(t: int) -> int:
    """Default preferred block: 1024 at every length. On-chip sweep
    coverage (T <= 8192) shows 1024 is 1.6x faster than 512 and the gain
    GROWS with T (the mechanism — fewer K/V re-streams per q-block —
    scales with n_blocks); the T = 131072 fwd+bwd ceiling is AOT-verified
    at block 1024 with the clamped causal maps active (3.25 GB peak,
    scripts/aot_flash_ceiling.jsonl), so long-T compilability is proven,
    not assumed. Kept as a function: the tuning boundary lives in one
    place if on-chip long-T data ever disagrees."""
    return 1024


def _sds(shape, dtype, vma):
    """``jax.ShapeDtypeStruct`` with a vma annotation where supported;
    legacy JAX has no vma field (and no tracking to need it)."""
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:
        return jax.ShapeDtypeStruct(shape, dtype)


def _compiler_params(**kw):
    """``pltpu.CompilerParams`` (new) / ``pltpu.TPUCompilerParams``
    (legacy 0.4.x) — same fields, pre-rename."""
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)


def _out_vma(*xs) -> frozenset:
    """Varying-manner annotation for kernel outputs: the union of the
    inputs' vma sets. pallas_call does not infer vma, so under
    ``shard_map(check_vma=True)`` — the default on real TPU — out_shapes
    with ``vma=None`` fail at trace time. Caught by the round-5 AOT
    schedule analysis (scripts/aot_ring_overlap.py); the CPU suite never
    sees it because interpret-mode tests run with check_vma=False."""
    vma = frozenset()
    if not hasattr(jax, "typeof"):  # legacy JAX: no vma tracking at all
        return vma
    for x in xs:
        v = getattr(jax.typeof(x), "vma", None)
        if v:
            vma |= v
    return vma


def _prec(*xs):
    """HIGHEST precision for f32 MXU operands: Mosaic's default f32 dot
    (like XLA's) may round operands through bf16 passes; flash in f32 is a
    correctness surface (the CPU oracle path), not a perf path, so pay for
    exactness. bf16 operands are single-pass exact either way -> None keeps
    the fast path untouched."""
    return (jax.lax.Precision.HIGHEST
            if any(x.dtype == jnp.float32 for x in xs) else None)


def _fold_args(b, h, d, *xs):
    """Model layout ``[B, T, H, D]`` -> kernel layout ``[B*H, T, D]``."""
    return tuple(x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)
                 for x in xs)


def _static_delta(causal, q_offset, k_offset):
    """``q_offset - k_offset`` when both offsets are static Python ints and
    the call is causal, else None. A static delta lets the kernels CLAMP
    their streaming-side index maps so fully-masked chunks alias the
    previous chunk's block index — Mosaic's pipeline emitter skips the
    copy when consecutive grid steps map to the same block, so the ~2x
    causal FLOP saving (pl.when compute skip) gains the matching ~2x DMA
    saving. This matters more than it sounds: the reduction-chunk grids
    re-stream K/V once per q-block (and q/do once per k-block in the dkv
    kernel), so attention bytes, not attention FLOPs, are the LM step's
    roofline term (scripts/lm_roofline_aot.jsonl: ~1% of FLOPs, over half
    the bytes). Traced offsets (ring shards) return None — the ring layer
    already skips wholly-invisible blocks at the block level."""
    if (causal and isinstance(q_offset, (int, np.integer))
            and isinstance(k_offset, (int, np.integer))):
        return int(q_offset) - int(k_offset)
    return None


# --------------------------------------------------------------------------- #
# Forward                                                                     #
# --------------------------------------------------------------------------- #

def _fwd_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_acc, l_acc, o_acc, *, scale: float, causal: bool,
                n_k: int):
    """Grid ``(bh, q-block, k-chunk)``, k-chunk INNERMOST: the online-
    softmax state (m, l, acc) lives in f32 VMEM scratch across the k sweep
    and the o/lse output blocks flush once at the last chunk — per-cell
    VMEM is O(block_q + block_k) regardless of T (the previous form held
    the full [tk, d] K/V blocks per cell). Fully-masked chunks skip their
    compute via pl.when (the former dynamic trip-count clamp)."""
    bq, d = q_ref.shape[1], q_ref.shape[2]
    bk = k_ref.shape[1]
    j = pl.program_id(2)
    q_off = qo_ref[0, 0] + pl.program_id(1) * bq
    k_off = ko_ref[0, 0] + j * bk

    @pl.when(j == 0)
    def _init():
        m_acc[...] = jnp.full_like(m_acc, _NEG_BIG)
        l_acc[...] = jnp.zeros_like(l_acc)
        o_acc[...] = jnp.zeros_like(o_acc)

    def compute():
        # MXU inputs stay in their storage dtype: bf16 x bf16 -> f32 is the
        # MXU's native full-rate mode, while a pre-cast to f32 forces the
        # multi-pass f32 path (~3-6x slower; measured round 5 — the kernel
        # sat at ~6.5 TFLOP/s with the casts). preferred_element_type keeps
        # the ACCUMULATION in f32 either way, which is all flash needs.
        q = q_ref[0]
        kb = k_ref[0]
        vb = v_ref[0]
        m = m_acc[:, 0]
        l = l_acc[:, 0]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_prec(q, kb),
        ) * scale
        if causal:
            q_pos = q_off + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = k_off + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_BIG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        corr = jnp.exp(m - m_new)
        # explicit zero for masked entries: when a row is fully masked within
        # a VISITED block, s == m_new == the sentinel and exp(s - m_new)
        # would be 1, polluting l/acc with mean-of-V garbage
        p = jnp.where(s <= _NEG_BIG / 2, 0.0, jnp.exp(s - m_new[:, None]))
        # p rides the MXU in v's dtype (f32 p x bf16 v would hit the slow
        # path); the f32->bf16 rounding of p is the same concession every
        # production TPU flash kernel makes, and the accumulator stays f32
        pv = jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_prec(vb),
        )
        m_acc[...] = jnp.broadcast_to(m_new[:, None], m_acc.shape)
        l_acc[...] = jnp.broadcast_to(
            (l * corr + jnp.sum(p, axis=-1))[:, None], l_acc.shape)
        o_acc[...] = o_acc[...] * corr[:, None] + pv

    if causal:
        # chunks whose first position is beyond the last q position never
        # contribute — skip the math (the DMA still streams; same traffic
        # as the old full-block fetch)
        pl.when(q_off + bq - 1 >= k_off)(compute)
    else:
        compute()

    @pl.when(j == n_k - 1)
    def _flush():
        m = m_acc[:, 0]
        l = l_acc[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (o_acc[...] / l_safe[:, None]).astype(o_ref.dtype)
        # rows with no visible keys get lse = -inf-ish; backward masks them
        # out. lse rides a lane-broadcast [block_q, _LANE] tile (a
        # [1, block_q] block violates Mosaic's sublane rule), like the
        # reference TPU flash kernel's l/m.
        lse = jnp.where(l == 0.0, _NEG_BIG, m + jnp.log(l_safe))
        lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[1:])


def _kv_clamped_map(delta, block_q, block_k, n_k):
    """Streaming-side index map for grids ``(bh, q-block i, k-chunk j)``:
    chunks past q-block i's last visible chunk alias that chunk (same
    block index -> the pipeline skips the copy). The kernel's pl.when
    skips their compute by the true j, so values are unchanged.
    ``delta=None`` (traced offsets / non-causal) -> plain streaming map."""
    if delta is None:
        return lambda b, i, j: (b, j, 0)

    def kv_map(b, i, j):
        vis = (delta + (i + 1) * block_q - 1) // block_k
        return (b, jnp.clip(jnp.minimum(j, vis), 0, n_k - 1), 0)
    return kv_map


def _q_clamped_map(delta, block_q, block_k, n_q):
    """Streaming-side index map for grids ``(bh, k-block j, q-chunk i)``:
    q-chunks wholly before k-block j's first visible chunk alias it.
    ``delta=None`` -> plain streaming map."""
    if delta is None:
        return lambda b, j, i: (b, i, 0)

    def q_map(b, j, i):
        first = (j * block_k - delta) // block_q
        return (b, jnp.clip(jnp.maximum(i, first), 0, n_q - 1), 0)
    return q_map


def _fwd(q, k, v, q_offset, k_offset, *, scale, causal, block_q, block_k,
         interpret, out_dtype=None, static_delta=None):
    bh, tq, d = q.shape
    tk = k.shape[1]
    n_k = tk // block_k
    # k-chunk INNERMOST (sequential: the online-softmax scratch accumulates
    # over it); o/lse blocks are indexed by (b, i) only and flush once
    grid = (bh, tq // block_q, n_k)
    qo = jnp.asarray(q_offset, jnp.int32).reshape(1, 1)
    ko = jnp.asarray(k_offset, jnp.int32).reshape(1, 1)
    smem = _smem_spec()
    kv_map = _kv_clamped_map(static_delta, block_q, block_k, n_k)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          n_k=n_k),
        grid=grid,
        in_specs=[
            smem,
            smem,
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_k, d), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANE), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            # out_dtype=f32 lets ring callers merge partial block outputs
            # without a bf16 round-trip (q/k/v still feed the MXU in their
            # input dtype; the kernel accumulates f32 regardless)
            _sds((bh, tq, d), out_dtype or q.dtype,
                                 _out_vma(qo, ko, q, k, v)),
            _sds((bh, tq, _LANE), jnp.float32,
                                 _out_vma(qo, ko, q, k, v)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANE), jnp.float32),   # running max m
            pltpu.VMEM((block_q, _LANE), jnp.float32),   # running denom l
            pltpu.VMEM((block_q, d), jnp.float32),       # unnormalized acc
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qo, ko, q, k, v)
    return out, lse[..., 0]


# --------------------------------------------------------------------------- #
# Backward                                                                    #
# --------------------------------------------------------------------------- #

def _bwd_dq_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, dq_acc, *, scale: float, causal: bool,
                   n_k: int):
    """Grid ``(bh, q-block, k-chunk)``, k-chunk INNERMOST: dq accumulates
    in f32 VMEM scratch across the k sweep and flushes once — per-cell
    VMEM is O(block) regardless of T (see _fwd_kernel / _bwd_dkv_kernel;
    all three kernels share the structure)."""
    bq, d = q_ref.shape[1], q_ref.shape[2]
    bk = k_ref.shape[1]
    j = pl.program_id(2)
    q_off = qo_ref[0, 0] + pl.program_id(1) * bq
    k_off = ko_ref[0, 0] + j * bk

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def compute():
        # storage-dtype MXU inputs, f32 accumulation — see _fwd_kernel
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, :, 0]     # lane-broadcast [block_q, _LANE]
        delta = delta_ref[0, :, 0]
        kb = k_ref[0]
        vb = v_ref[0]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_prec(q, kb),
        ) * scale
        if causal:
            q_pos = q_off + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = k_off + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_BIG)
        # masked entries must not resurrect when lse is the -inf sentinel
        # (fully-masked row): exp(-1e30 - (-1e30)) == 1 otherwise
        p = jnp.where(s <= _NEG_BIG / 2, 0.0, jnp.exp(s - lse[:, None]))
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_prec(do, vb),
        )
        ds = p * (dp - delta[:, None])
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_prec(kb),
        )

    if causal:
        # chunks wholly after the last q position contribute nothing
        pl.when(q_off + bq - 1 >= k_off)(compute)
    else:
        compute()

    @pl.when(j == n_k - 1)
    def _flush():
        dq_ref[0] = (dq_acc[...] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                    scale: float, causal: bool, n_q: int):
    """Grid ``(bh, k-block, q-chunk)``, q-chunk INNERMOST: the dk/dv output
    block for (b, k-block) stays VMEM-resident across the whole q sweep,
    accumulating in the f32 scratch, and flushes once at the last chunk.

    The previous form held the FULL [tq, d] q/do and [tq, 128] lse/delta
    blocks per grid cell and streamed q inside a fori_loop — its VMEM
    footprint grew linearly with tq and OOM'd the v5e backward at
    T = 16384 (AOT-verified); chunked via the grid, per-cell VMEM is
    O(block_q + block_k) regardless of tq."""
    bk, d = k_ref.shape[1], k_ref.shape[2]
    bq = q_ref.shape[1]
    i = pl.program_id(2)
    q_off = qo_ref[0, 0] + i * bq
    k_off = ko_ref[0, 0] + pl.program_id(1) * bk

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def compute():
        # storage-dtype MXU inputs, f32 accumulation — see _fwd_kernel
        kb = k_ref[0]
        vb = v_ref[0]
        qb = q_ref[0]
        dob = do_ref[0]
        lse = lse_ref[0, :, 0]
        delta = delta_ref[0, :, 0]
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_prec(qb, kb),
        ) * scale
        if causal:
            q_pos = q_off + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = k_off + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_BIG)
        p = jnp.where(s <= _NEG_BIG / 2, 0.0, jnp.exp(s - lse[:, None]))
        dv_acc[...] += jax.lax.dot_general(
            p.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_prec(dob),
        )
        dp = jax.lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_prec(dob, vb),
        )
        ds = p * (dp - delta[:, None])
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(qb.dtype), qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_prec(qb),
        )

    if causal:
        # q chunks wholly before this k block see nothing of it
        pl.when(q_off + bq - 1 >= k_off)(compute)
    else:
        compute()

    @pl.when(i == n_q - 1)
    def _flush():
        dk_ref[0] = (dk_acc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _dq_call(q, k, v, do, lse, delta, qo2, ko2, *, scale, causal, block_q,
             block_k, interpret, grad_dtype=None, static_delta=None):
    """dq for one (q-range x k-range) pair, folded ``[B*H, T, D]`` layout —
    shared by the full backward and the ring backward's per-block calls
    (which pass ``grad_dtype=f32`` to accumulate across blocks losslessly)."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    # lane-broadcast the row stats to the Mosaic-tileable layout (see _fwd)
    lse = jnp.broadcast_to(lse[..., None], (*lse.shape, _LANE))
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, _LANE))
    smem = _smem_spec()
    n_k = tk // block_k
    kv_map = _kv_clamped_map(static_delta, block_q, block_k, n_k)
    return pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          n_k=n_k),
        grid=(bh, tq // block_q, n_k),
        in_specs=[
            smem, smem,
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANE), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANE), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=_sds((bh, tq, d), grad_dtype or q.dtype,
                                       _out_vma(qo2, ko2, q, k, v, do)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qo2, ko2, q, k, v, do, lse, delta)


def _dkv_call(q, k, v, do, lse, delta, qo2, ko2, *, scale, causal, block_q,
              block_k, interpret, grad_dtype=None, static_delta=None):
    """(dk, dv) for one (q-range x k-range) pair, folded layout — see
    :func:`_dq_call`."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    lse = jnp.broadcast_to(lse[..., None], (*lse.shape, _LANE))
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, _LANE))
    smem = _smem_spec()
    n_q = tq // block_q
    q_map = _q_clamped_map(static_delta, block_q, block_k, n_q)
    return pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          n_q=n_q),
        # q-chunk is the INNERMOST grid dim: the (b, j) output block stays
        # resident while the scratch accumulates over every q chunk
        grid=(bh, tk // block_k, n_q),
        in_specs=[
            smem, smem,
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, block_q, _LANE), q_map),
            pl.BlockSpec((1, block_q, _LANE), q_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            _sds((bh, tk, d), grad_dtype or k.dtype,
                                 _out_vma(qo2, ko2, q, k, v, do)),
            _sds((bh, tk, d), grad_dtype or v.dtype,
                                 _out_vma(qo2, ko2, q, k, v, do)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        # the q-chunk dim accumulates into the scratch -> sequential
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qo2, ko2, q, k, v, do, lse, delta)


def _bwd(scale, causal, block_q, block_k, interpret, static_delta, res, g):
    q, k, v, out, lse, qo, ko = res
    do, _ = g  # cotangent of (out, lse); lse cotangent unused
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    qo2 = jnp.asarray(qo, jnp.int32).reshape(1, 1)
    ko2 = jnp.asarray(ko, jnp.int32).reshape(1, 1)
    kw = dict(scale=scale, causal=causal, block_q=block_q, block_k=block_k,
              interpret=interpret, static_delta=static_delta)
    dq = _dq_call(q, k, v, do, lse, delta, qo2, ko2, **kw)
    dk, dv = _dkv_call(q, k, v, do, lse, delta, qo2, ko2, **kw)
    return dq, dk, dv, None, None


# --------------------------------------------------------------------------- #
# Public entry                                                                #
# --------------------------------------------------------------------------- #

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash(q, k, v, q_offset, k_offset, scale, causal, block_q, block_k,
           interpret, static_delta):
    out, _ = _fwd(q, k, v, q_offset, k_offset, scale=scale, causal=causal,
                  block_q=block_q, block_k=block_k, interpret=interpret,
                  static_delta=static_delta)
    return out


def _flash_fwd(q, k, v, q_offset, k_offset, scale, causal, block_q, block_k,
               interpret, static_delta):
    out, lse = _fwd(q, k, v, q_offset, k_offset, scale=scale, causal=causal,
                    block_q=block_q, block_k=block_k, interpret=interpret,
                    static_delta=static_delta)
    return out, (q, k, v, out, lse, q_offset, k_offset)


def _flash_bwd(scale, causal, block_q, block_k, interpret, static_delta,
               res, g):
    dq, dk, dv, _, _ = _bwd(scale, causal, block_q, block_k, interpret,
                            static_delta, res, (g, None))
    return dq, dk, dv, None, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset=0,
    k_offset=0,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """Blockwise (flash) attention, layout ``[B, T, H, D]`` like
    :func:`chainermn_tpu.parallel.sequence.full_attention`.

    ``q_offset``/``k_offset`` are the *global* positions of ``q[:, 0]`` /
    ``k[:, 0]`` for causal masking under sequence sharding (may be traced).
    Differentiable (custom VJP, flash backward kernels). Runs compiled on
    TPU, interpreted elsewhere (``interpret=None`` auto-detects).
    """
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if scale is None:
        scale = d ** -0.5
    if interpret is None:
        interpret = _interpret_default()
    bq = _pick_block(tq, block_q or _default_block(tq))
    bk = _pick_block(tk, block_k or _default_block(tk))
    if bq < min(8, tq) or bk < min(8, tk):
        # awkward lengths (no usable divisor): blockwise degenerates below
        # hardware tile minimums — use the XLA path, same semantics
        from chainermn_tpu.parallel.sequence import full_attention

        static_zero_offsets = (
            isinstance(q_offset, (int, np.integer)) and q_offset == 0
            and isinstance(k_offset, (int, np.integer)) and k_offset == 0
        )
        if not causal or (static_zero_offsets and tq == tk):
            return full_attention(q, k, v, causal=causal, scale=scale)
        raise ValueError(
            f"flash_attention: sequence lengths (tq={tq}, tk={tk}) have no "
            "usable block divisor and the offset-causal XLA fallback is not "
            "implemented — pad the sequence to a multiple of 8"
        )

    qf, kf, vf = _fold_args(b, h, d, q, k, v)
    out = _flash(qf, kf, vf,
                 jnp.asarray(q_offset, jnp.int32),
                 jnp.asarray(k_offset, jnp.int32),
                 float(scale), bool(causal), bq, bk, bool(interpret),
                 _static_delta(causal, q_offset, k_offset))
    return out.reshape(b, h, tq, d).transpose(0, 2, 1, 3)


# --------------------------------------------------------------------------- #
# Block-level entries for ring attention                                      #
# --------------------------------------------------------------------------- #
# Ring attention (parallel/sequence.py) computes attention against one K/V
# block per step and merges partials with the online-softmax recurrence; it
# owns its own custom VJP at the ring level, so these entries are PRIMAL
# only — the forward returns the (out, lse) pair the merge needs, and the
# backward pieces take the ring's final lse/delta and return one block's
# gradient contributions. All in model layout [B, T, H, D] (lse [B, H, T]).

def _check_blocks(bq, bk, tq, tk):
    """Ring callers have no XLA fallback (the custom VJP is built on the
    kernels), so reject un-tileable lengths loudly instead of letting
    Pallas fail with an obscure Mosaic error."""
    if bq < min(8, tq) or bk < min(8, tk):
        raise ValueError(
            f"ring flash attention: shard lengths (tq={tq}, tk={tk}) have "
            "no usable block divisor >= 8 — pad the per-shard sequence to a "
            "multiple of 8 (zigzag chunks: a multiple of 16)"
        )


def flash_fwd_with_lse(q, k, v, *, causal=False, scale=None, q_offset=0,
                       k_offset=0, block_q=None, block_k=None, interpret=None,
                       out_dtype=None):
    """Primal-only flash forward returning ``(out, lse)``.

    ``out [B, Tq, H, D]`` (in ``out_dtype``, default ``q.dtype`` — ring
    callers pass f32 to merge without a bf16 round-trip), ``lse [B, H, Tq]``
    (f32; fully-masked rows hold the -1e30 sentinel, which the lse-weighted
    merge turns into a zero contribution). Causal masking uses global
    positions via the (possibly traced) offsets; fully-masked chunks skip
    their compute (pl.when), and with STATIC int offsets their DMA too
    (clamped index maps, see _static_delta). Traced-offset callers still
    pay the masked chunks' DMA — ring callers that KNOW a whole block is
    invisible should skip the call, not lean on the kernel."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if scale is None:
        scale = d ** -0.5
    if interpret is None:
        interpret = _interpret_default()
    bq = _pick_block(tq, block_q or _default_block(tq))
    bk = _pick_block(tk, block_k or _default_block(tk))
    _check_blocks(bq, bk, tq, tk)
    qf, kf, vf = _fold_args(b, h, d, q, k, v)
    out, lse = _fwd(qf, kf, vf,
                    jnp.asarray(q_offset, jnp.int32),
                    jnp.asarray(k_offset, jnp.int32),
                    scale=float(scale), causal=bool(causal), block_q=bq,
                    block_k=bk, interpret=bool(interpret),
                    out_dtype=out_dtype,
                    static_delta=_static_delta(causal, q_offset, k_offset))
    return (out.reshape(b, h, tq, d).transpose(0, 2, 1, 3),
            lse.reshape(b, h, tq))


def flash_block_grads(q, k, v, do, lse, delta, *, causal=False, scale=None,
                      q_offset=0, k_offset=0, block_q=None, block_k=None,
                      interpret=None, grad_dtype=jnp.float32):
    """One block's gradient contributions ``(dq, dk, dv)`` given the FINAL
    (globally merged) ``lse [B, H, Tq]`` and ``delta = rowsum(do * out)
    [B, H, Tq]`` — the flash backward decomposes over K/V blocks once those
    are fixed, which is exactly what the ring backward's rotation needs.
    Layouts as :func:`flash_fwd_with_lse`. Gradients come back in
    ``grad_dtype`` (default f32) because the ring accumulates them across
    blocks; cast once at the end."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if scale is None:
        scale = d ** -0.5
    if interpret is None:
        interpret = _interpret_default()
    bq = _pick_block(tq, block_q or _default_block(tq))
    bk = _pick_block(tk, block_k or _default_block(tk))
    _check_blocks(bq, bk, tq, tk)
    qf, kf, vf, dof = _fold_args(b, h, d, q, k, v, do)
    lsef = lse.reshape(b * h, tq)
    deltaf = delta.reshape(b * h, tq)
    qo2 = jnp.asarray(q_offset, jnp.int32).reshape(1, 1)
    ko2 = jnp.asarray(k_offset, jnp.int32).reshape(1, 1)
    kw = dict(scale=float(scale), causal=bool(causal), block_q=bq,
              block_k=bk, interpret=bool(interpret), grad_dtype=grad_dtype,
              static_delta=_static_delta(causal, q_offset, k_offset))
    dq = _dq_call(qf, kf, vf, dof, lsef, deltaf, qo2, ko2, **kw)
    dk, dv = _dkv_call(qf, kf, vf, dof, lsef, deltaf, qo2, ko2, **kw)
    unfold = lambda x: x.reshape(b, h, x.shape[1], d).transpose(0, 2, 1, 3)
    return unfold(dq), unfold(dk), unfold(dv)
