"""Microbatched pipeline parallelism (GPipe-style fill-drain schedule).

TPU extension BEYOND the reference: upstream's ``MultiNodeChainList`` runs
whole batches sequentially through the stages — no microbatch pipelining
(SURVEY.md S2.16: "no GPipe/1F1B"). This op provides the schedule the
reference lacks, the SPMD way: every device runs the SAME traced program
(``lax.scan`` over ticks), holds ONE stage's parameters, and boundary
activations rotate with ``lax.ppermute``; autodiff of scan+ppermute yields
the reverse (backward) schedule with transposed transfers automatically.

Bubble fraction is the textbook ``(n_stages - 1) / (n_micro + n_stages - 1)``
— choose ``n_microbatches >> n_stages``. Stages must be shape-preserving
(input/output shapes equal across the boundary, e.g. transformer blocks):
the rotating buffer has one static shape.

Use inside ``comm.shard_map`` with stage parameters stacked on a leading
axis sharded over the pipeline mesh axis (``P(axis_name)``), e.g.::

    def body(stacked_params, x):
        local = jax.tree.map(lambda l: l[0], stacked_params)  # my stage
        return pipeline_apply(stage_fn, local, x, "ranks", n_micro)

    y = jax.jit(comm.shard_map(body, in_specs=(P("ranks"), P()),
                               out_specs=P()))(stacked, x)
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax

from chainermn_tpu.utils import axis_size as _axis_size
from chainermn_tpu.utils import pcast_varying
from jax.sharding import PartitionSpec as P


def pipeline_apply(
    stage_fn: Callable[[Any, Any], Any],
    stage_params: Any,
    x,
    axis_name: str,
    n_microbatches: int,
    remat: bool = False,
):
    """Run ``x`` through ``n_stages = axis_size`` pipeline stages.

    Args:
      stage_fn: ``(params, micro_in) -> micro_out``; applied by every rank to
        its resident stage. Shape-preserving.
      stage_params: THIS rank's stage parameters (the local shard).
      x: full batch, replicated across the axis; leading dim divisible by
        ``n_microbatches``.
      axis_name: the pipeline mesh axis (inside ``shard_map``).
      remat: rematerialize each stage in the backward pass
        (``jax.checkpoint``). Without it the scan stashes every stage's
        internal activations for all ``n_microbatches`` ticks; with it only
        the microbatch boundary tensors persist and stage internals are
        recomputed — the same live-activation bound 1F1B schedules buy with
        manual fwd/bwd interleaving, obtained here by trading one extra
        stage forward. (XLA owns the schedule either way; an explicit 1F1B
        tick order would not change what the compiler overlaps, only this
        memory profile, which remat already provides.)

    Returns the full-batch output of the last stage, replicated.
    """
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b = x.shape[0]
    if b % n_microbatches:
        raise ValueError(
            f"batch {b} not divisible by n_microbatches {n_microbatches}"
        )
    micro = x.reshape(n_microbatches, b // n_microbatches, *x.shape[1:])
    ticks = n_microbatches + n - 1
    perm = [(i, i + 1) for i in range(n - 1)]  # stage i -> i+1 (no wrap)

    def tick(state, t):
        # rank 0 injects microbatch t (clamped; masked after drain),
        # others consume what the previous stage sent last tick
        inj = jnp.take(micro, jnp.clip(t, 0, n_microbatches - 1), axis=0)
        inp = jnp.where(idx == 0, inj, state)
        out = stage_fn(stage_params, inp)
        return lax.ppermute(out, axis_name, perm), out

    # the carry is per-device state (varying over the pipeline axis); without
    # the cast the scan carry's replicated-ness differs between input/output
    state0 = pcast_varying(jnp.zeros_like(micro[0]), (axis_name,))
    _, outs = lax.scan(tick, state0, jnp.arange(ticks))
    # the last stage emits valid microbatch m at tick m + n - 1; everything
    # it produced earlier is fill garbage. Select the valid window and
    # broadcast it from the last rank (masked psum).
    valid = lax.dynamic_slice_in_dim(outs, n - 1, n_microbatches, axis=0)
    mine = jnp.where(idx == n - 1, valid, jnp.zeros_like(valid))
    full = lax.psum(mine, axis_name)
    return full.reshape(b, *x.shape[1:])


# --------------------------------------------------------------------------- #
# Pipelined TransformerLM (the end-to-end consumer)                           #
# --------------------------------------------------------------------------- #
# Round 3 shipped pipeline_apply with unit tests only — nothing end-to-end
# consumed it (VERDICT weak #6, the pattern that let round 1's fused path
# ship broken). This is the consumer: a decoder LM whose blocks are the
# pipeline stages — embed and head replicated (they are small next to the
# blocks), one transformer block per mesh rank, stage params stacked on a
# leading axis sharded P(axis).

class _PPEmbed(nn.Module):
    vocab_size: int
    d_model: int
    max_len: int
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens):
        x = nn.Embed(self.vocab_size, self.d_model,
                     dtype=self.compute_dtype, name="embed")(tokens)
        pos = jnp.arange(tokens.shape[1])
        return x + nn.Embed(self.max_len, self.d_model,
                            dtype=self.compute_dtype,
                            name="pos_embed")(pos)[None]


class _PPHead(nn.Module):
    vocab_size: int
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(dtype=self.compute_dtype)(x)
        logits = nn.Dense(self.vocab_size, dtype=self.compute_dtype,
                          name="lm_head")(x)
        return logits.astype(jnp.float32)


def make_pipeline_lm(vocab_size: int, d_model: int, n_heads: int,
                     n_stages: int, d_ff: int | None = None,
                     max_len: int = 512,
                     compute_dtype: jnp.dtype = jnp.float32):
    """The three module parts of a pipelined decoder LM: ``(embed, block,
    head)`` — ``block`` is one pipeline stage (a causal
    :class:`~chainermn_tpu.models.transformer.TransformerBlock`); the
    model has ``n_stages`` of them, one resident per mesh rank."""
    from chainermn_tpu.models.transformer import TransformerBlock

    embed = _PPEmbed(vocab_size, d_model, max_len, compute_dtype)
    block = TransformerBlock(d_model, n_heads, d_ff or 4 * d_model,
                             compute_dtype=compute_dtype)
    head = _PPHead(vocab_size, compute_dtype)
    return embed, block, head


def init_pipeline_lm(modules, rng, tokens, n_stages: int):
    """Init the pipelined LM: returns ``{'embed', 'blocks', 'head'}`` with
    ``blocks`` stacked ``[n_stages, ...]`` (shard it ``P(axis)``)."""
    embed, block, head = modules
    k_e, k_b, k_h = jax.random.split(rng, 3)
    ep = embed.init(k_e, tokens)
    x = embed.apply(ep, tokens)
    bp = jax.vmap(lambda k: block.init(k, x))(
        jax.random.split(k_b, n_stages))
    hp = head.init(k_h, x)
    return {"embed": ep, "blocks": bp, "head": hp}


def pp_lm_specs(params, optimizer, opt_state, axis: str):
    """(param_specs, opt_specs) for the pipelined LM: blocks ``P(axis)``
    on their stacked leading dim, everything else replicated; optimizer
    moments co-shard with their parameters."""
    param_specs = {
        "embed": jax.tree_util.tree_map(lambda _: P(), params["embed"]),
        "blocks": jax.tree_util.tree_map(lambda _: P(axis),
                                         params["blocks"]),
        "head": jax.tree_util.tree_map(lambda _: P(), params["head"]),
    }
    opt_specs = optax.tree_map_params(
        optimizer, lambda _, s: s, opt_state, param_specs,
        transform_non_params=lambda _: P(),
    )
    return param_specs, opt_specs


def jit_pp_lm_train_step(modules, optimizer, comm, n_microbatches: int,
                         remat: bool = True, donate: bool = True):
    """Jitted pipeline-parallel LM train step:
    ``step(params, opt_state, tokens, targets) -> (params, opt_state,
    loss)`` with ``params`` from :func:`init_pipeline_lm` (blocks sharded
    over the communicator's axis — ``n_stages`` must equal the axis size).

    Inside the shard_map body each rank holds ONE stage's params; the
    batch is replicated and microbatched through :func:`pipeline_apply`.
    Embed gradients psum (only rank 0's embed output enters the pipe),
    head gradients are identical on every rank already.
    """
    embed, block, head = modules
    axis = comm.axis_name
    if not isinstance(axis, str):
        raise ValueError(
            "pipeline LM needs a flat single-axis communicator "
            f"(got axes {axis!r})")

    def _map_blocks(fn, tree):
        """Apply ``fn`` to every leaf under a 'blocks' key (params AND
        optimizer moments mirror the same {'embed','blocks','head'} dict),
        leaving other leaves untouched — the strip/re-stack of the stacked
        stage dim on entry/exit of the per-rank body."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        out = [
            fn(leaf) if "'blocks'" in jax.tree_util.keystr(path) else leaf
            for path, leaf in flat
        ]
        return jax.tree_util.tree_unflatten(treedef, out)

    def body(params, opt_state, tokens, targets):
        local = _map_blocks(lambda l: l[0], params)
        opt_local = _map_blocks(lambda l: l[0], opt_state)

        def loss_fn(p):
            x = embed.apply(p["embed"], tokens)
            y = pipeline_apply(
                lambda bp, xi: block.apply(bp, xi), p["blocks"], x,
                axis, n_microbatches, remat=remat,
            )
            logits = head.apply(p["head"], y)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, targets).mean()

        loss, grads = jax.value_and_grad(loss_fn)(local)
        # embed feeds the pipeline on rank 0 only -> its grad lives there;
        # head grads are already identical everywhere (mean = identity)
        grads["embed"] = jax.tree_util.tree_map(
            lambda g: comm.allreduce(g, "sum"), grads["embed"])
        grads["head"] = jax.tree_util.tree_map(
            lambda g: comm.allreduce(g, "mean"), grads["head"])
        updates, opt_local = optimizer.update(grads, opt_local, local)
        new_local = optax.apply_updates(local, updates)
        new_params = _map_blocks(lambda l: l[None], new_local)
        new_opt = _map_blocks(lambda l: l[None], opt_local)
        return new_params, new_opt, comm.allreduce(loss, "mean")

    # spec trees need a state template; build it cheaply via eval_shape
    def _template(params):
        return jax.eval_shape(optimizer.init, {
            "embed": params["embed"],
            "blocks": jax.tree_util.tree_map(lambda l: l[0],
                                             params["blocks"]),
            "head": params["head"],
        })

    def make(params):
        n_stages = jax.tree_util.tree_leaves(params["blocks"])[0].shape[0]
        n_ranks = comm.mesh.shape[axis]
        if n_stages != n_ranks:
            # a divisible mismatch would SILENTLY train every n-th stage
            # (shard_map blocks [S] -> local [S/n], l[0] picks one) and a
            # non-divisible one fails with an opaque sharding error
            raise ValueError(
                f"blocks are stacked for {n_stages} stages but the "
                f"pipeline axis {axis!r} has {n_ranks} ranks — init with "
                f"n_stages={n_ranks}")
        opt_shape = _template(params)
        param_specs, opt_specs = pp_lm_specs(
            params, optimizer, opt_shape, axis)
        sm = comm.shard_map(
            body,
            in_specs=(param_specs, opt_specs, P(), P()),
            out_specs=(param_specs, opt_specs, P()),
        )
        return jax.jit(sm, donate_argnums=(0, 1) if donate else ())

    # the returned callable builds (and caches) the jitted program on first
    # use — spec trees depend on the param tree structure
    cache = {}

    def step(params, opt_state, tokens, targets):
        key = jax.tree_util.tree_structure(params)
        if key not in cache:
            cache[key] = make(params)
        return cache[key](params, opt_state, tokens, targets)

    return step


def pp_lm_opt_init(optimizer, params):
    """Optimizer state for the pipelined LM: block moments stacked
    ``[n_stages, ...]`` like the params (vmap of init over stages), so the
    step's ``P(axis)`` in_specs hand each rank its own stage's moments;
    embed/head moments and counters stay one replicated copy (selected by
    tree path from an unstacked template init)."""
    local_template = {
        "embed": params["embed"],
        "blocks": jax.tree_util.tree_map(lambda l: l[0], params["blocks"]),
        "head": params["head"],
    }
    stacked = jax.vmap(
        lambda sb: optimizer.init({**local_template, "blocks": sb})
    )(params["blocks"])
    # graftlint: recompile-ok — one-time init trace, never re-entered
    template = jax.jit(optimizer.init)(local_template)
    flat_s = jax.tree_util.tree_flatten_with_path(stacked)[0]
    flat_t = jax.tree_util.tree_flatten_with_path(template)[0]
    out = [
        leaf_s if "'blocks'" in jax.tree_util.keystr(path) else leaf_t
        for (path, leaf_s), (_, leaf_t) in zip(flat_s, flat_t)
    ]
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(template), out)
