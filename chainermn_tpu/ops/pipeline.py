"""Microbatched pipeline parallelism (GPipe-style fill-drain schedule).

TPU extension BEYOND the reference: upstream's ``MultiNodeChainList`` runs
whole batches sequentially through the stages — no microbatch pipelining
(SURVEY.md S2.16: "no GPipe/1F1B"). This op provides the schedule the
reference lacks, the SPMD way: every device runs the SAME traced program
(``lax.scan`` over ticks), holds ONE stage's parameters, and boundary
activations rotate with ``lax.ppermute``; autodiff of scan+ppermute yields
the reverse (backward) schedule with transposed transfers automatically.

Bubble fraction is the textbook ``(n_stages - 1) / (n_micro + n_stages - 1)``
— choose ``n_microbatches >> n_stages``. Stages must be shape-preserving
(input/output shapes equal across the boundary, e.g. transformer blocks):
the rotating buffer has one static shape.

Use inside ``comm.shard_map`` with stage parameters stacked on a leading
axis sharded over the pipeline mesh axis (``P(axis_name)``), e.g.::

    def body(stacked_params, x):
        local = jax.tree.map(lambda l: l[0], stacked_params)  # my stage
        return pipeline_apply(stage_fn, local, x, "ranks", n_micro)

    y = jax.jit(comm.shard_map(body, in_specs=(P("ranks"), P()),
                               out_specs=P()))(stacked, x)
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(
    stage_fn: Callable[[Any, Any], Any],
    stage_params: Any,
    x,
    axis_name: str,
    n_microbatches: int,
    remat: bool = False,
):
    """Run ``x`` through ``n_stages = axis_size`` pipeline stages.

    Args:
      stage_fn: ``(params, micro_in) -> micro_out``; applied by every rank to
        its resident stage. Shape-preserving.
      stage_params: THIS rank's stage parameters (the local shard).
      x: full batch, replicated across the axis; leading dim divisible by
        ``n_microbatches``.
      axis_name: the pipeline mesh axis (inside ``shard_map``).
      remat: rematerialize each stage in the backward pass
        (``jax.checkpoint``). Without it the scan stashes every stage's
        internal activations for all ``n_microbatches`` ticks; with it only
        the microbatch boundary tensors persist and stage internals are
        recomputed — the same live-activation bound 1F1B schedules buy with
        manual fwd/bwd interleaving, obtained here by trading one extra
        stage forward. (XLA owns the schedule either way; an explicit 1F1B
        tick order would not change what the compiler overlaps, only this
        memory profile, which remat already provides.)

    Returns the full-batch output of the last stage, replicated.
    """
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b = x.shape[0]
    if b % n_microbatches:
        raise ValueError(
            f"batch {b} not divisible by n_microbatches {n_microbatches}"
        )
    micro = x.reshape(n_microbatches, b // n_microbatches, *x.shape[1:])
    ticks = n_microbatches + n - 1
    perm = [(i, i + 1) for i in range(n - 1)]  # stage i -> i+1 (no wrap)

    def tick(state, t):
        # rank 0 injects microbatch t (clamped; masked after drain),
        # others consume what the previous stage sent last tick
        inj = jnp.take(micro, jnp.clip(t, 0, n_microbatches - 1), axis=0)
        inp = jnp.where(idx == 0, inj, state)
        out = stage_fn(stage_params, inp)
        return lax.ppermute(out, axis_name, perm), out

    # the carry is per-device state (varying over the pipeline axis); without
    # the cast the scan carry's replicated-ness differs between input/output
    state0 = lax.pcast(jnp.zeros_like(micro[0]), (axis_name,), to="varying")
    _, outs = lax.scan(tick, state0, jnp.arange(ticks))
    # the last stage emits valid microbatch m at tick m + n - 1; everything
    # it produced earlier is fill garbage. Select the valid window and
    # broadcast it from the last rank (masked psum).
    valid = lax.dynamic_slice_in_dim(outs, n - 1, n_microbatches, axis=0)
    mine = jnp.where(idx == n - 1, valid, jnp.zeros_like(valid))
    full = lax.psum(mine, axis_name)
    return full.reshape(b, *x.shape[1:])
