"""TPU-native compute ops: Pallas kernels and pipeline schedules.

No reference counterpart (``gshuichi/chainermn`` has no custom device
kernels beyond CuPy JIT pack/cast strings, SURVEY.md S2.9) — this package
holds the ops where hand-written kernels beat XLA's default lowering, plus
TPU-idiomatic extensions (microbatched pipeline schedule).
"""

from chainermn_tpu.ops.flash_attention import flash_attention
from chainermn_tpu.ops.pipeline import (
    init_pipeline_lm,
    jit_pp_lm_train_step,
    make_pipeline_lm,
    pipeline_apply,
    pp_lm_opt_init,
)

__all__ = [
    "flash_attention",
    "pipeline_apply",
    "make_pipeline_lm",
    "init_pipeline_lm",
    "pp_lm_opt_init",
    "jit_pp_lm_train_step",
]
