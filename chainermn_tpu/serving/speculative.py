"""Speculative decode for the paged serving engine: draft k tokens
cheaply, verify them in ONE target-model call, commit the accepted run.

``ServingEngine.decode_step`` advances every slot exactly one token per
device dispatch, so generation pays the per-dispatch overhead once per
token (PERF.md's dispatch-bound regime) and reads the whole KV working
set once per token (the bandwidth-bound regime). Speculative decode
attacks both at once: a cheap *drafter* proposes ``k`` continuation
tokens per slot, the target model scores the window ``[t0, d1..dk]`` at
positions ``[p..p+k]`` in ONE compiled call, and the engine commits the
longest prefix of drafts that match the target's own greedy choices plus
one correction token — between 1 and ``k+1`` tokens per dispatch, always
at least the one token the plain path would have produced.

Greedy only, and exactly: the verify program recomputes the target's
argmax at every drafted position, so the committed stream is
token-for-token identical to non-speculative greedy decode regardless of
what the drafter proposed (a bad drafter costs speed, never
correctness). That parity argument is causal induction: logits at window
row ``j`` depend only on committed tokens plus drafts ``d1..dj``, and a
row's output is only committed when every draft before it matched.

Two drafters ship behind one interface (:class:`SpeculativeConfig`):

- ``'ngram'`` — :class:`NgramDrafter`, model-free prompt-lookup
  decoding (PLD): the longest trailing n-gram of the request's own
  history (prompt + generated) that occurred earlier proposes the
  tokens that followed it, falling back to the shared prefix trie
  (:meth:`~chainermn_tpu.serving.prefix_cache.PrefixCacheIndex.
  ngram_continuation`) and finally to repeating the last token. Zero
  extra weights, zero extra device programs — strongest exactly on the
  repetitive / shared-system-prompt workloads ``bench.py`` models.
- ``'draft'`` — :class:`DraftModelDrafter`, a small ``TransformerLM``
  decoding ``k`` greedy tokens per window against its own dense slot
  caches (two extra compiled programs: one full-prompt prefill, one
  all-slots decode step). The draft caches stay consistent across
  partial acceptance by the same write-before-attend argument the
  engine's slot reuse rides on: every propose window rewrites the rows
  a rejected draft left behind before any query attends them.

The engine side (verify program, block-table scatter of up to ``k+1``
rows per slot, per-slot accept mask, position bookkeeping, block
rollback) lives in ``engine.py``; this module is the drafter state
machine plus its host/device programs.

Under ``ServingEngine(paged_kernel=True)`` the verify window's
attention reads ride the fused Pallas paged-decode kernel
(``parallel.paged_kernel.paged_attend``) like every other decode shape:
the S=k+1 window is just a wider query block, and the per-slot
``valid`` write caps redirect rejected rows before the kernel ever
reads them, so acceptance bookkeeping is unchanged and the committed
stream stays token-for-token identical to the XLA paged path (pinned
in ``tests/serving_tests/test_paged_kernel_engine.py``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

__all__ = [
    "DraftModelDrafter",
    "NgramDrafter",
    "SpeculativeConfig",
    "build_drafter",
]


@dataclass
class SpeculativeConfig:
    """Speculative-decode configuration for ``ServingEngine(speculative=)``.

    Parameters
    ----------
    k : int
        Drafted tokens per verify window. Each decode dispatch scores
        ``k + 1`` positions and commits ``1..k+1`` tokens; the block
        budget reserves ``ceil(k / kv_block_size)`` extra headroom per
        slot for the window's worst-case writes.
    drafter : {'ngram', 'draft'}
        ``'ngram'``: model-free prompt-lookup drafting from the
        request's own history and the shared prefix trie.
        ``'draft'``: a small ``TransformerLM`` draft model
        (``draft_model`` + ``draft_params`` required).
    draft_model / draft_params : the draft ``TransformerLM`` and its
        params (``drafter='draft'`` only). Must share the target's
        vocabulary, must not be tensor/sequence-sharded, and needs
        ``max_len >= cache_len``.
    ngram_max / ngram_min : longest/shortest trailing n-gram the
        prompt-lookup drafter tries to match (longest first).
    """

    k: int = 4
    drafter: str = "ngram"
    draft_model: object = None
    draft_params: object = None
    ngram_max: int = 3
    ngram_min: int = 1

    def validate(self) -> None:
        if self.k < 1:
            raise ValueError(f"speculative k must be >= 1, got {self.k}")
        if self.drafter not in ("ngram", "draft"):
            raise ValueError(
                f"drafter must be 'ngram' or 'draft', got {self.drafter!r}")
        if not 1 <= self.ngram_min <= self.ngram_max:
            raise ValueError(
                f"need 1 <= ngram_min <= ngram_max, got "
                f"({self.ngram_min}, {self.ngram_max})")
        if self.drafter == "draft" and (
                self.draft_model is None or self.draft_params is None):
            raise ValueError(
                "drafter='draft' needs draft_model= and draft_params=")


class NgramDrafter:
    """Model-free prompt-lookup drafter (PLD / lookahead-by-lookup).

    Per-slot host state only: the request's token history (prompt +
    committed tokens). ``propose`` finds the most recent earlier
    occurrence of the history's trailing n-gram (longest n first) and
    proposes the tokens that followed it; on a miss it probes the shared
    prefix trie (another request's cached prompt may extend ours), and
    as a last resort repeats the last committed token — which is the
    *optimal* draft whenever greedy decode has entered a fixed point.
    Wrong proposals cost nothing but speed: the verify step rejects
    them. No device programs, nothing to warm up or guard."""

    def __init__(self, config: SpeculativeConfig, engine) -> None:
        self.config = config
        self.engine = engine
        self._hist: list[list[int]] = [[] for _ in range(engine.n_slots)]

    # -- slot lifecycle (engine-driven) -------------------------------- #

    def on_admit(self, slot: int, prompt, first_token: int) -> None:
        self._hist[slot] = [int(t) for t in prompt] + [int(first_token)]

    def on_commit(self, slot: int, tokens) -> None:
        self._hist[slot].extend(int(t) for t in tokens)

    def on_release(self, slot: int) -> None:
        self._hist[slot] = []

    def reset(self) -> None:
        self._hist = [[] for _ in range(self.engine.n_slots)]

    # -- drafting ------------------------------------------------------- #

    def _lookup(self, hist: list[int], k: int) -> list[int]:
        """Most recent earlier occurrence of the trailing n-gram, longest
        n first; the tokens following it are the draft."""
        h = np.asarray(hist, np.int32)
        length = len(h)
        hi = min(self.config.ngram_max, length - 1)
        for n in range(hi, self.config.ngram_min - 1, -1):
            tail = h[length - n:]
            win = np.lib.stride_tricks.sliding_window_view(h, n)
            # windows starting before the tail itself (index < length-n)
            hits = np.flatnonzero((win[: length - n] == tail).all(axis=1))
            if hits.size:
                i = int(hits[-1])
                cont = h[i + n: i + n + k]
                if cont.size:
                    return [int(t) for t in cont]
        return []

    def propose(self, k: int) -> np.ndarray:
        """``[n_slots, k]`` int32 draft tokens; inactive slots are zeros
        (the verify program masks them anyway)."""
        eng = self.engine
        out = np.zeros((eng.n_slots, k), np.int32)
        trie = eng.prefix_cache
        for slot in np.flatnonzero(eng._active):
            slot = int(slot)
            hist = self._hist[slot]
            if not hist:
                # admitted outside the scheduler path (direct engine
                # use): behave as if history were just the last token
                hist = [int(eng._token[slot])]
            draft = self._lookup(hist, k)
            if len(draft) < k and trie is not None:
                cont = trie.ngram_continuation(hist + draft,
                                               k - len(draft))
                if cont:
                    draft.extend(cont)
            last = draft[-1] if draft else hist[-1]
            while len(draft) < k:
                draft.append(int(last))
            out[slot, :] = draft[:k]
        return out

    # -- engine integration stubs (no device programs) ------------------ #

    def warmup(self) -> None:
        pass

    def watched_fns(self) -> dict:
        return {}

    def compile_counts(self) -> dict:
        return {}


class DraftModelDrafter:
    """Small-``TransformerLM`` drafter: dense per-slot KV caches plus two
    compiled programs (a single-request full-prompt prefill and an
    all-slots one-token decode), both greedy-argmax — draft tokens are
    *proposals*, so the drafter never needs the engine's sampler keys.

    Cache consistency across partial acceptance: a propose window at
    base position ``p`` writes draft-cache rows ``p..p+k-1`` before any
    of its queries attend them; the next window starts at the commit
    frontier ``p' <= p+k+1`` and rewrites every row a rejected draft
    polluted (``p'..p'+k-1`` covers ``p+a+1..p+k-1`` for any accept
    length ``a``) — the same write-before-attend induction the engine's
    slot reuse rides on, so rejected drafts never leak into a later
    window's attention."""

    def __init__(self, config: SpeculativeConfig, engine) -> None:
        import jax
        import jax.numpy as jnp

        from chainermn_tpu.models.transformer import init_kv_caches

        config.validate()
        model = config.draft_model
        if model.vocab_size != engine.model.vocab_size:
            raise ValueError(
                f"draft model vocab {model.vocab_size} != target vocab "
                f"{engine.model.vocab_size} — drafted token ids must be "
                "target token ids")
        if model.tensor_axis is not None or model.sequence_axis is not None:
            raise ValueError(
                "the draft model runs un-sharded (plain jit) — rebuild it "
                "with tensor_axis=None, sequence_axis=None")
        if model.max_len < engine.cache_len:
            raise ValueError(
                f"draft model max_len {model.max_len} < engine cache_len "
                f"{engine.cache_len}")
        self.config = config
        self.engine = engine
        self.model = model
        self.params = config.draft_params
        self._jnp = jnp
        self._caches = init_kv_caches(model, engine.n_slots,
                                      engine.cache_len)
        self._prefill_len = engine.prefill_len
        self._prefill_fn = jax.jit(self._prefill_body(),
                                   donate_argnums=(1,))
        self._decode_fn = jax.jit(self._decode_body(), donate_argnums=(1,))

    def _prefill_body(self):
        """One request's FULL prompt (the drafter has no prefix cache to
        discount a suffix against) through the slot's dense cache rows —
        gather the slot, run the padded prompt at positions
        ``[0, prefill_len)``, scatter it back. No sampling: the first
        drafted token always conditions on the engine's committed one."""
        import jax.numpy as jnp
        from jax import lax

        model, plen = self.model, self._prefill_len

        def body(params, caches, tokens, slot):
            slot_c = [
                {kk: lax.dynamic_slice_in_dim(c[kk], slot, 1, 0)
                 for kk in ("k", "v")}
                for c in caches
            ]
            pos = jnp.arange(plen, dtype=jnp.int32)[None, :]
            _, slot_c = model.apply(params, tokens, pos, kv_caches=slot_c)
            out = []
            for c, s in zip(caches, slot_c):
                buf = dict(c)
                for kk in ("k", "v"):
                    buf[kk] = lax.dynamic_update_slice_in_dim(
                        buf[kk], s[kk], slot, 0)
                out.append(buf)
            return out

        return body

    def _decode_body(self):
        """All-slots one-token greedy step — the engine's dense decode
        body minus sampler keys (argmax; drafts are proposals)."""
        import jax.numpy as jnp

        model = self.model

        def body(params, caches, tokens, pos, active):
            lg, caches = model.apply(params, tokens[:, None], pos[:, None],
                                     kv_caches=caches)
            nxt = jnp.argmax(lg[:, 0], axis=-1).astype(jnp.int32)
            nxt = jnp.where(active, nxt, jnp.zeros_like(nxt))
            return caches, nxt

        return body

    # -- slot lifecycle -------------------------------------------------- #

    def on_admit(self, slot: int, prompt, first_token: int) -> None:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        tokens = np.zeros((1, self._prefill_len), np.int32)
        tokens[0, : len(prompt)] = prompt
        jnp = self._jnp
        self._caches = self._prefill_fn(self.params, self._caches,
                                        jnp.asarray(tokens),
                                        jnp.int32(slot))

    def on_commit(self, slot: int, tokens) -> None:
        pass   # the draft caches advance inside propose()

    def on_release(self, slot: int) -> None:
        pass   # stale rows are masked until the next tenant overwrites

    def reset(self) -> None:
        from chainermn_tpu.models.transformer import init_kv_caches

        self._caches = init_kv_caches(self.model, self.engine.n_slots,
                                      self.engine.cache_len)

    # -- drafting --------------------------------------------------------- #

    def propose(self, k: int) -> np.ndarray:
        """Run ``k`` chained draft decode steps from the engine's commit
        frontier (``_token`` at ``_pos`` per slot). Tokens stay on device
        between steps; ONE fetch at the end returns ``[n_slots, k]``."""
        from chainermn_tpu.dataflow.dispatch import device_fetch

        jnp = self._jnp
        eng = self.engine
        tok = jnp.asarray(eng._token)
        active = jnp.asarray(eng._active)
        pos = jnp.asarray(eng._pos)
        drafts = []
        for j in range(k):
            self._caches, tok = self._decode_fn(
                self.params, self._caches, tok, pos + j, active)
            drafts.append(tok)
        stacked = device_fetch(jnp.stack(drafts, axis=1))
        return np.asarray(stacked, np.int32)

    # -- engine integration ------------------------------------------------ #

    def warmup(self) -> None:
        jnp = self._jnp
        eng = self.engine
        self._caches = self._prefill_fn(
            self.params, self._caches,
            jnp.zeros((1, self._prefill_len), jnp.int32), jnp.int32(0))
        z = jnp.zeros((eng.n_slots,), jnp.int32)
        self._caches, _ = self._decode_fn(
            self.params, self._caches, z, z,
            jnp.zeros((eng.n_slots,), bool))

    def watched_fns(self) -> dict:
        return {"spec_draft_prefill": self._prefill_fn,
                "spec_draft_decode": self._decode_fn}

    def compile_counts(self) -> dict:
        return {"draft_prefill": int(self._prefill_fn._cache_size()),
                "draft_decode": int(self._decode_fn._cache_size())}


def build_drafter(config: SpeculativeConfig, engine):
    """Engine hook: validate the config and build its drafter."""
    config.validate()
    if config.drafter == "draft":
        return DraftModelDrafter(config, engine)
    return NgramDrafter(config, engine)
