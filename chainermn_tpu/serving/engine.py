"""Continuous-batching decode engine over the static KV-cache path.

The offline :func:`chainermn_tpu.models.generate` decodes ONE fixed batch
start-to-finish; a traffic-facing server cannot wait for the slowest
request before admitting the next. This engine owns a fixed pool of
``n_slots`` cache slots inside one persistent static-shape KV cache
(:func:`~chainermn_tpu.models.transformer.init_kv_caches`-backed) and a
small fixed family of compiled device programs:

- ``prefill`` (one program per **bucket**): run up to ``prefill_batch``
  requests' (padded) prompt suffixes through the model in ONE call, each
  batch row writing K/V into its OWN slot at its OWN start position (the
  per-row ``[B, T]`` position form of ``TransformerLM.__call__`` over the
  per-slot ``update_cache_and_attend``) and sampling its first token —
  admission cost is one batched suffix prefill, amortized over the group;
- ``decode_step``: advance ALL slots one token per call, each at its OWN
  sequence position; retired/free slots ride along masked by ``jnp.where``
  so shapes never change and nothing recompiles;
- ``prefix_insert`` (when the prefix cache is on): copy a freshly
  prefilled prompt's full KV blocks into the device block store backing
  :class:`~chainermn_tpu.serving.prefix_cache.PrefixCacheIndex`, deferred
  off the admission path. The matching *fetch* needs no program of its
  own: each bucket's prefill gathers the matched blocks INSIDE its single
  device call (a hit costs zero extra dispatches), then prefills only the
  uncached suffix.

Prompt padding is **bucketed**: instead of one ``prefill_len``-padded
program, ``prefill_buckets`` is a small ladder (e.g. ``(64, 256, 1024)``)
and each admission group runs the smallest bucket covering its (suffix)
lengths — padding waste shrinks from ``max_len - len`` to the bucket gap
at the cost of ``len(buckets)`` compiles, all performed once by
:meth:`warmup` (``RecompileGuard`` pins zero growth after).

Why this is correct without ever zeroing a slot between requests: the
causal position mask only admits cache rows at positions ``<= q_pos``, and
every such row was either written by THIS request's prefill (rows
``< prompt_len``) or overwritten by one of its decode steps (each step
writes its query row before attending). Stale K/V from a previous tenant
of the slot — the padding rows a short prompt leaves behind, warmup's
dummy rows, and the garbage tail of a copied prefix block span — sit at
positions the mask excludes until the exact step that overwrites them.
Prefix reuse adds one step to the argument: the copied rows ``[0, L)``
were computed from the SAME first ``L`` tokens at the SAME positions
(causality: K/V of a position depends only on tokens at or before it), so
the suffix attends exactly the rows its own full prefill would have
written. The engine-level parity tests (staggered admissions and shared-
prefix admissions vs solo ``generate()``, token-for-token) pin both.

Per-request sampling parity: each slot carries its own PRNG key and draws
through the SAME ``_sampler`` split sequence as a solo ``generate()`` call
(one split at prefill, one per decode step), via a per-slot vmap — so a
request's tokens are independent of which other requests share the batch.

Tensor-parallel decode reuses the ``_generate_tp_fn`` pattern: all
programs are traced inside ``comm.shard_map`` with the cache's (and block
store's) head axis sharded over the mesh (``P(None, None, axis)`` at
rest), and a vocab-parallel head's local logits are ``all_gather``-ed
before sampling — the scheduler drives TP decode through the identical
slot API.

**Paged mode** (``paged=True``) replaces the dense per-slot cache regions
with ONE shared block store — the same store the prefix cache runs on —
and per-slot **block tables** (host mirror + a ``[n_slots, max_blocks]``
int32 operand per decode call). Concurrency is then bound by *tokens
actually resident*, not ``n_slots x cache_len`` worst case: a slot
allocates blocks lazily as its sequence crosses block boundaries
(``append_block``, scheduler-driven), prefix hits become plain
ref-counted table entries (the PR-5 splice-copy collapses into sharing —
a hit costs zero copies, and caching a freshly prefilled prompt is pure
bookkeeping via ``insert_shared``), retirement decrefs the slot's blocks
back to the pool, and ``kv_quant='int8'`` halves resident bytes again
(per-row-per-head scales, dequantized inside the attention gather).
Shared blocks are never written: a match covers only *full* prompt
blocks, and every write position ``>= match.length`` lands in a block
the slot owns exclusively — copy-on-write reduces to "the first partial
block is always private". Still exactly TWO program families (bucketed
prefill + decode), compiled once at warmup: table *contents* change
per call, shapes never do, so the zero-recompile invariant carries over
unchanged. The legacy dense path is preserved byte-for-byte behind
``paged=False`` (the default).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from chainermn_tpu.extensions.profiling import Watchdog
from chainermn_tpu.models.transformer import (
    _sampler,
    init_kv_caches,
    init_paged_kv_caches,
)
from chainermn_tpu.dataflow.dispatch import device_fetch
from chainermn_tpu.monitor import RecompileGuard, annotate
from chainermn_tpu.monitor._state import get_event_log, get_registry
from chainermn_tpu.parallel.paged_kernel import kernel_supported
from chainermn_tpu.resilience.cutpoints import (
    SERVING_CHUNK_PREFILL,
    SERVING_DECODE,
    SERVING_KV_APPEND,
    SERVING_PREFILL,
    SERVING_PREFILL_BATCH,
    SERVING_PREFIX_COPY,
    SERVING_SPEC_VERIFY,
)
from chainermn_tpu.resilience.faults import inject
from chainermn_tpu.serving.prefix_cache import (
    BlockPool,
    PrefixCacheIndex,
    PrefixMatch,
)
from chainermn_tpu.serving.speculative import SpeculativeConfig, build_drafter


@dataclass
class AdmitPlan:
    """One request's admission decision: the pinned prefix match (if any),
    the suffix start position, and the prefill bucket its padded suffix
    runs in. Built by :meth:`ServingEngine.plan_admission`; consumed by
    :meth:`ServingEngine.admit_batch` (or discarded via
    :meth:`ServingEngine.cancel_plan`, which unpins the match)."""

    prompt: np.ndarray
    rng: object
    match: Optional[PrefixMatch]
    start: int          # cached tokens reused (0 on miss)
    bucket: int         # padded suffix length (one compiled program per)
    max_new: int = 1    # token budget (paged mode reserves growth blocks)

    @property
    def cached_frac(self) -> float:
        return self.start / len(self.prompt) if len(self.prompt) else 0.0


@dataclass
class ChunkedPrefill:
    """In-progress chunked prefill of ONE slot: the request's prompt and
    rng held host-side, the slot's privately-staged block ids (allocated
    up front, NOT yet visible in the engine's decode table — see
    :meth:`ServingEngine.begin_chunked`), and the precomputed chunk
    schedule ``[(frontier, chunk_len, bucket), ...]`` that
    :meth:`ServingEngine.prefill_chunk` walks one entry per call."""

    prompt: np.ndarray
    rng: object
    start: int                     # cached-prefix tokens (chunk 0 frontier)
    max_new: int
    ids: list = field(default_factory=list)
    chunks: list = field(default_factory=list)
    next_idx: int = 0
    t_begin: float = 0.0

    @property
    def done(self) -> bool:
        return self.next_idx >= len(self.chunks)

    @property
    def frontier(self) -> int:
        """Tokens prefilled so far (cached prefix included)."""
        if self.done:
            return len(self.prompt)
        return self.chunks[self.next_idx][0]


class EngineStateError(RuntimeError):
    """A device-program failure left the engine's donated buffers in an
    unknown state — containment is impossible; the scheduler must fail all
    in-flight work and warm-restart."""


class ServingEngine:
    """Slot-pool KV-cache decode engine (mechanism only — admission policy,
    EOS retirement, and per-request bookkeeping live in
    :class:`~chainermn_tpu.serving.scheduler.FCFSScheduler`).

    Parameters
    ----------
    model : TransformerLM
        Built for inference: ``sequence_axis=None``; MoE via
        ``moe_impl='gshard'``; ``tensor_axis`` set requires ``comm``.
    params : pytree
        Model parameters (the engine never mutates them).
    n_slots : int
        Cache slots == max concurrently-decoding requests. The decode
        program's batch dimension; fixed at construction.
    prefill_len : int, optional
        Maximum admitted prompt length (== the largest bucket). With the
        default single-bucket ladder every prompt is right-padded to this
        length, the PR-1 behavior; padding rows write K/V the causal mask
        hides until decode overwrites them (module docstring).
    prefill_buckets : sequence of int, optional
        Ascending ladder of padded prompt(-suffix) lengths, one compiled
        prefill program each; an admission runs the smallest bucket
        covering it. Default ``(prefill_len,)``. When both are given,
        ``max(prefill_buckets)`` must equal ``prefill_len``.
    prefill_batch : int
        Batch dimension of every bucket's prefill program: up to this many
        requests admit per device call (rows beyond the group ride along
        masked). Clamped to ``n_slots``. Default 1 (the PR-1 shape).
    prefix_cache_blocks / prefix_block_size : int
        ``prefix_cache_blocks > 0`` enables ref-counted prefix KV reuse: a
        device block store of that many ``prefix_block_size``-token blocks
        plus a host trie (:class:`PrefixCacheIndex`). On admission the
        longest cached prefix is copied slot-locally (compiled-once fetch
        program) and only the suffix prefills; after admission the
        prompt's full blocks are inserted back (compiled-once insert
        program). 0 disables (default).
    prefix_min_insert_blocks : int
        Cost/benefit gate on inserts: skip caching prompts contributing
        fewer than this many new full blocks (an insert is a device copy;
        a unique ragged tail is never re-hit). Default 1 (cache all).
    paged : bool
        Unify decode KV onto ONE shared block store with per-slot block
        tables (module docstring): concurrency bound by resident tokens
        instead of ``n_slots x cache_len``, prefix reuse by sharing
        instead of copying. The prefix trie always runs on the shared
        pool in this mode — ``prefix_cache_blocks`` must stay 0 (its
        legacy store would duplicate the unified one). Default False:
        the dense PR-1..5 path, byte-for-byte.
    kv_blocks : int, optional
        Paged mode: total store blocks, INCLUDING the reserved scratch
        block (id 0 — the write target for inactive rows and
        unallocated table entries). Default ``n_slots *
        ceil(cache_len/kv_block_size) + 1``, the dense-equivalent
        capacity; set smaller to oversubscribe slots against the real
        (short-request) working set — block-budget admission plus
        preemption keep it safe.
    kv_block_size : int
        Paged mode: tokens per block. Smaller blocks waste fewer rows on
        ragged tails but widen the tables. Default 16.
    kv_quant : {'none', 'int8'}
        Paged mode: quantize resident blocks to int8 with per-row
        per-head scales (~2x less KV memory; dequantized inside the
        attention gather — a small, tested perturbation of logits, NOT
        bit-parity with the f32/bf16 path). Default 'none'.
    cache_len : int, optional
        Per-slot KV capacity (prompt + generated); defaults to
        ``model.max_len``. A request needs ``len(prompt) + max_new <=
        cache_len``.
    speculative : SpeculativeConfig, optional
        Paged + greedy only: draft ``k`` tokens per slot per round with
        the configured drafter (prompt-lookup or a small draft model —
        see :mod:`chainermn_tpu.serving.speculative`) and verify the
        whole window in ONE target-model dispatch, committing 1..k+1
        tokens. Token-for-token identical to the non-speculative greedy
        stream; block-budget admission reserves ``ceil(k/block_size)``
        extra headroom per slot for the window's worst-case writes.
        The scheduler drives this through :meth:`decode_round`.
    decode_window : int
        Non-speculative dispatch amortization: ``decode_window=n > 1``
        compiles the decode step as a ``lax.fori_loop`` over ``n``
        tokens (ONE dispatch commits ``n`` tokens per active slot —
        see :meth:`decode_steps`). Mutually exclusive with
        ``speculative`` (the verify window already amortizes dispatch,
        adaptively). Default 1, the per-token legacy program.
    temperature / top_k / top_p : sampler configuration shared by every
        request (the compiled programs bake it in, exactly like
        ``generate()``'s lru-cache key).
    comm : communicator, optional
        Required iff ``model.tensor_axis`` is set: all programs then run
        inside its ``shard_map`` with head-sharded caches and block store.
    watchdog : Watchdog or float, optional
        Hang detection around every device program call (prefill, decode,
        prefix copies). Default **off**. A float builds a
        ``Watchdog(timeout=...)`` (abort mode — die loudly, the
        ``global_except_hook`` stance); pass a configured ``Watchdog``
        (e.g. ``on_timeout='warn'``) for report-only. On fire it dumps
        thread stacks + the monitor flight recorder (last events incl.
        slot admits/retires, per-device memory), so a wedged collective
        in serving aborts with evidence instead of hanging the client
        thread forever.
    """

    def __init__(self, model, params, *, n_slots: int,
                 prefill_len: Optional[int] = None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 prefill_batch: int = 1,
                 prefix_cache_blocks: int = 0,
                 prefix_block_size: int = 16,
                 prefix_min_insert_blocks: int = 1,
                 paged: bool = False,
                 kv_blocks: Optional[int] = None,
                 kv_block_size: int = 16,
                 kv_quant: str = "none",
                 paged_kernel: bool = False,
                 speculative: Optional[SpeculativeConfig] = None,
                 decode_window: int = 1,
                 cache_len: Optional[int] = None, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0, comm=None,
                 watchdog: Optional[Union[Watchdog, float]] = None):
        if model.sequence_axis is not None:
            raise ValueError(
                "serving decode does not support sequence-sharded models: "
                "rebuild with sequence_axis=None for inference"
            )
        if model.moe_experts and model.moe_impl != "gshard":
            raise ValueError(
                "serving decode supports MoE only via moe_impl='gshard' — "
                "rebuild the model with moe_impl='gshard' (same params)"
            )
        if model.tensor_axis is not None and comm is None:
            raise ValueError(
                "tensor-parallel serving needs comm= (the decode programs "
                "run inside the communicator's shard_map)"
            )
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        cache_len = cache_len or model.max_len
        if prefill_buckets is None:
            if prefill_len is None:
                raise ValueError("pass prefill_len or prefill_buckets")
            if not 0 < prefill_len <= cache_len:
                raise ValueError(
                    f"prefill_len must be in (0, cache_len={cache_len}], "
                    f"got {prefill_len}"
                )
            buckets = (int(prefill_len),)
        else:
            buckets = tuple(sorted({int(b) for b in prefill_buckets}))
            if not buckets:
                raise ValueError("prefill_buckets must be non-empty")
            if prefill_len is not None and int(prefill_len) != buckets[-1]:
                raise ValueError(
                    f"prefill_len {prefill_len} != max(prefill_buckets) "
                    f"{buckets[-1]} — the largest bucket IS the admission "
                    "length limit; pass one or make them agree"
                )
            prefill_len = buckets[-1]
        if not (0 < buckets[0] and buckets[-1] <= cache_len):
            raise ValueError(
                f"prefill buckets must be in (0, cache_len={cache_len}], "
                f"got {buckets}"
            )
        if not 0 < prefill_len <= cache_len:
            raise ValueError(
                f"prefill_len must be in (0, cache_len={cache_len}], got "
                f"{prefill_len}"
            )
        if cache_len > model.max_len:
            raise ValueError(
                f"cache_len {cache_len} exceeds model.max_len "
                f"{model.max_len}"
            )
        if prefill_batch < 1:
            raise ValueError(
                f"prefill_batch must be >= 1, got {prefill_batch}")
        self.model = model
        self.params = params
        self.n_slots = int(n_slots)
        self.prefill_len = int(prefill_len)
        self.prefill_buckets = buckets
        self.prefill_batch = min(int(prefill_batch), self.n_slots)
        self.cache_len = int(cache_len)
        self._comm = comm
        self._sample = _sampler(float(temperature), int(top_k), float(top_p))
        self.decode_window = int(decode_window)
        if self.decode_window < 1:
            raise ValueError(
                f"decode_window must be >= 1, got {decode_window}")
        self._spec = speculative
        if speculative is not None:
            speculative.validate()
            if not paged:
                raise ValueError(
                    "speculative decode needs paged=True — the verify "
                    "window scatters through block tables")
            if float(temperature) != 0.0:
                raise ValueError(
                    "speculative decode is greedy-only (temperature=0): "
                    "the verify step recomputes argmax per position")
            if self.decode_window != 1:
                raise ValueError(
                    "speculative= and decode_window> 1 are mutually "
                    "exclusive — the verify window already amortizes "
                    "dispatch (adaptively, by accept length)")
        if watchdog is not None and not isinstance(watchdog, Watchdog):
            watchdog = Watchdog(timeout=float(watchdog))
        self.watchdog = watchdog
        self._events = get_event_log()
        labels = {"engine": "serving"}
        reg = get_registry()
        self._reg = reg
        self._c_prefills = {
            b: reg.counter("serving_prefills_total",
                           dict(labels, prefill_bucket=str(b)))
            for b in buckets
        }
        # serving_decode_steps_total is created AFTER paged parsing below:
        # in paged mode it carries the paged_kernel="on"/"off" label so
        # kernel ON-vs-OFF A/Bs fork the time series instead of mixing
        self._c_restarts = reg.counter("serving_engine_restarts_total",
                                       labels)
        self._c_appends = reg.counter("kv_block_appends_total", labels)
        # chunked prefill + KV migration (the disaggregation spine)
        self._c_chunks = reg.counter("prefill_chunks_total", labels)
        self._h_chunk_tokens = reg.histogram("chunk_tokens", labels)
        self._c_migrations = reg.counter("kv_migrations_total", labels)
        self._c_migrated_blocks = reg.counter("kv_migrated_blocks_total",
                                              labels)
        self._h_migration = reg.histogram("migration_seconds", labels,
                                          unit="s")
        # versioned weights (the deploy layer's hot-swap surface):
        # version 0 is the constructor's params; every successful
        # swap_params bumps it and moves the gauge
        self.weight_version = 0
        self._g_weight_version = reg.gauge("serving_weight_version", labels)
        self._g_weight_version.set(0)

        # paged mode: ONE shared block store (pool + trie on it), per-slot
        # block tables; the dense caches/prefix store are never built
        self.paged = bool(paged)
        self.kv_quant = str(kv_quant)
        if self.kv_quant not in ("none", "int8"):
            raise ValueError(
                f"kv_quant must be 'none' or 'int8', got {kv_quant!r}")
        if not self.paged and self.kv_quant != "none":
            raise ValueError("kv_quant needs paged=True (the dense cache "
                             "regions are not quantized)")
        # fused Pallas paged-decode kernel (parallel/paged_kernel.py): an
        # OPT-IN replacement for the decode read side only — prefill and
        # every write stay XLA, and paged_kernel=False (the default) is
        # the byte-for-byte XLA trace. Unavailability degrades to the XLA
        # path with an event, never to a construction failure.
        self.paged_kernel = bool(paged_kernel)
        if self.paged_kernel and not self.paged:
            raise ValueError("paged_kernel=True needs paged=True (the "
                             "fused kernel reads the shared block store)")
        if self.paged_kernel:
            ok, why = kernel_supported()
            if not ok:
                self._events.emit("paged_kernel_fallback", reason=why)
                self.paged_kernel = False
        decode_labels = dict(labels)
        if self.paged:
            decode_labels["paged_kernel"] = (
                "on" if self.paged_kernel else "off")
        self._c_decode_steps = reg.counter("serving_decode_steps_total",
                                           decode_labels)
        self.peak_active = 0
        self.prefix_cache: Optional[PrefixCacheIndex] = None
        if self.paged:
            if prefix_cache_blocks:
                raise ValueError(
                    "paged mode unifies decode KV and the prefix cache on "
                    "one shared block store — drop prefix_cache_blocks and "
                    "size the store with kv_blocks/kv_block_size"
                )
            if kv_block_size < 1:
                raise ValueError(
                    f"kv_block_size must be >= 1, got {kv_block_size}")
            self.kv_block_size = int(kv_block_size)
            # table width: blocks covering a full-length slot (the last
            # block may straddle cache_len — its tail rows stay masked)
            self._n_max = -(-self.cache_len // self.kv_block_size)
            if kv_blocks is None:
                kv_blocks = self.n_slots * self._n_max + 1
            self.kv_blocks = int(kv_blocks)
            self._pool = BlockPool(self.kv_blocks, reserve_scratch=True)
            self.prefix_cache = PrefixCacheIndex(
                self.kv_blocks, self.kv_block_size, pool=self._pool)
            self._min_insert = max(1, int(prefix_min_insert_blocks))
            self._n_prog_blocks = self._n_max   # match cap for planning
            self._tables = np.zeros((self.n_slots, self._n_max), np.int32)
            self._slot_blocks: list[list[int]] = [
                [] for _ in range(self.n_slots)]
            # worst-case growth blocks each active slot may still append
            # (admission reserves them; append_block draws them down) —
            # what makes block-budget admission preemption-free in the
            # no-fault case
            self._slot_reserved = np.zeros((self.n_slots,), np.int64)
            # multi-token rounds write up to _write_horizon rows past the
            # commit frontier (a verify window's k drafts, or a decode
            # window's n-1 extra steps); admission reserves the matching
            # extra block headroom so mid-round appends can't run dry
            self._write_horizon = (speculative.k if speculative is not None
                                   else self.decode_window - 1)
            self._spec_headroom = -(-self._write_horizon
                                    // self.kv_block_size)
            # in-progress chunked prefills: slot -> ChunkedPrefill. The
            # slot is NOT in free_slots but also NOT _active — decode
            # dispatches mask it out, and its all-scratch table row
            # routes ride-along writes into the scratch block until the
            # final chunk commits the real ids
            self._chunking: dict[int, ChunkedPrefill] = {}
        elif prefix_cache_blocks:
            if not 0 < prefix_block_size <= self.prefill_len:
                raise ValueError(
                    f"prefix_block_size must be in (0, prefill_len="
                    f"{self.prefill_len}], got {prefix_block_size}"
                )
            self.prefix_cache = PrefixCacheIndex(prefix_cache_blocks,
                                                 prefix_block_size)
            # admission cost/benefit knob: an insert is a device copy, so
            # skip prompts contributing fewer than this many NEW blocks
            # (shared-prefix traffic caches the shared part on first
            # sight either way; unique ragged tails are never re-hit)
            self._min_insert = max(1, int(prefix_min_insert_blocks))
            # both copy programs move this many whole blocks (static
            # shapes); junk trailing ids are identity/masked writes
            self._n_prog_blocks = max(1, self.prefill_len // prefix_block_size)

        if model.tensor_axis is not None:
            self._init_tp_caches(comm)
            self._build_tp_fns(comm)
        elif self.paged:
            self.caches = None          # the block store IS the cache
            self._store = self._init_paged_store()
            self._build_fns()
        else:
            self.caches = init_kv_caches(model, self.n_slots, self.cache_len)
            if self.prefix_cache is not None:
                self._store = self._init_store()
            self._build_fns()

        # host-side slot mirror: the scheduler reads/writes through the
        # occupy/release API; the decode program consumes these as [B]
        # device operands each step (tiny transfers, static shapes)
        self._token = np.zeros((self.n_slots,), np.int32)
        self._pos = np.zeros((self.n_slots,), np.int32)
        self._active = np.zeros((self.n_slots,), bool)
        self._keys = self._fresh_keys()
        self.free_slots = set(range(self.n_slots))
        self._warm = False
        # deferred trie inserts: (prompt, slot) pairs copied store-side by
        # flush_inserts() — off the TTFT-critical admission path, always
        # flushed before the donor slot can be reused (scheduler end-of-
        # step + the defensive flush at the next admission)
        self._pending_inserts: list[tuple[np.ndarray, int]] = []

        # recompile tracking: the zero-recompile invariant as live
        # telemetry (compile/recompile events + recompiles_total counter),
        # checked after every device call — not only in tests
        self._guard = RecompileGuard()
        for b, fn in self._prefill_fns.items():
            self._guard.watch(f"serving_prefill_{b}", fn)
        self._guard.watch("serving_decode", self._decode_fn)
        if self.migration_supported:
            for w in self._mig_buckets:
                self._guard.watch(f"serving_kv_gather_{w}",
                                  self._kv_gather_fns[w])
                self._guard.watch(f"serving_kv_scatter_{w}",
                                  self._kv_scatter_fns[w])
        if self.prefix_cache is not None and not self.paged:
            self._guard.watch("serving_prefix_insert", self._insert_fn)
        if self.decode_window > 1:
            self._guard.watch("serving_decode_window", self._window_fn)
        # speculative drafter + its accept accounting (cumulative for
        # spec_stats(); per-round for the scheduler's metrics drain)
        self._drafter = None
        self._spec_proposed_total = 0
        self._spec_accepted_total = 0
        self._last_spec_window: Optional[tuple] = None
        # cost-attribution mirror of the window: {slot: (kd, a)} for the
        # last verify round (drafts that fit, drafts accepted) — NOT
        # popped with the window, the scheduler's ledger reads it right
        # after decode_round returns
        self._last_spec_slots: dict = {}
        if self._spec is not None:
            self._drafter = build_drafter(self._spec, self)
            self._guard.watch("serving_spec_verify", self._spec_fn)
            for name, fn in self._drafter.watched_fns().items():
                self._guard.watch(name, fn)

    def _fresh_keys(self):
        """Zeroed per-slot sampler keys. Under TP they are committed
        replicated on the mesh up front — the sharding a real admission's
        key writeback produces — so the decode program warmup-compiles on
        the SAME argument shardings it will see forever (sharding is part
        of the jit cache key; an uncommitted warmup key would cost one
        recompile on first traffic)."""
        keys = jnp.zeros((self.n_slots, 2), jnp.uint32)
        if self.model.tensor_axis is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            keys = jax.device_put(
                keys, NamedSharding(self._comm.mesh, P()))
        return keys

    def _watched(self, label: str, **ctx):
        """Watchdog context for one device-program call (no-op when hang
        detection is off). ``ctx`` carries request/trace identity from
        the scheduler, so a fire names WHOSE work wedged — the
        flight-recorder dump then joins against exported traces."""
        if self.watchdog is None:
            return contextlib.nullcontext()
        return self.watchdog.step(label, **ctx)

    @property
    def prefix_enabled(self) -> bool:
        return self.prefix_cache is not None

    @property
    def migration_supported(self) -> bool:
        """KV block migration needs the paged store AND a single-device
        layout: under TP the rows live head-sharded across the mesh and
        the host-bounce gather/scatter pair is not built (documented
        limitation — export raises, the router decodes in place)."""
        return self.paged and self.model.tensor_axis is None

    # ------------------------------------------------------------------ #
    # program construction                                                #
    # ------------------------------------------------------------------ #

    def _prefill_body(self, bucket: int, vocab_gather=None):
        """Batched suffix-prefill trace for one bucket: gather each group
        row's slot out of the pooled cache, splice each row's cached
        prefix blocks in from the store (prefix cache on — the fetch is
        INSIDE this program: a hit costs zero extra device calls), run the
        padded suffixes at their per-row start positions in ONE model
        call, splice the updated slots back (inactive rows write back
        what was there), and sample each row's first token from its last
        REAL position. Rows without a match carry junk block ids; the
        garbage span they splice sits entirely under rows their own
        prefill overwrites or the causal mask hides."""
        model, sample = self.model, self._sample
        k = self.prefill_batch
        prefix = self.prefix_cache is not None
        span = self._n_prog_blocks * self.prefix_cache.block_size \
            if prefix else 0

        def slot_sample(lg, key):
            nxt, key = sample(lg[None], key)
            return nxt[0], key

        def body(params, caches, tokens, slots, starts, last_idx, active,
                 keys, store=None, fetch_ids=None):
            with annotate("chainermn.prefill"):
                return body_inner(params, caches, tokens, slots, starts,
                                  last_idx, active, keys, store, fetch_ids)

        def body_inner(params, caches, tokens, slots, starts, last_idx,
                       active, keys, store, fetch_ids):
            slot_c = [
                {kk: jnp.take(c[kk], slots, axis=0) for kk in ("k", "v")}
                for c in caches
            ]
            if prefix:
                # per-row prefix splice: gather each row's matched blocks
                # and overwrite its gathered slot rows [0, span)
                for sc, st in zip(slot_c, store):
                    for kk in ("k", "v"):
                        rows = jnp.take(st[kk], fetch_ids.reshape(-1),
                                        axis=0)
                        rows = rows.reshape((k, span) + rows.shape[2:])
                        sc[kk] = jnp.concatenate(
                            [rows, sc[kk][:, span:]], axis=1)
            pos = starts[:, None] + jnp.arange(bucket)[None, :]
            logits, slot_c = model.apply(params, tokens, pos,
                                         kv_caches=slot_c)
            # each row's logits at its last PROMPT token, not a padded row
            lg = jax.vmap(
                lambda row, i: lax.dynamic_slice_in_dim(row, i, 1, 0)[0]
            )(logits, last_idx)
            if vocab_gather is not None:
                lg = vocab_gather(lg)
            nxt, keys = jax.vmap(slot_sample)(lg, keys)
            nxt = jnp.where(active, nxt, jnp.zeros_like(nxt))
            # write back per row; inactive rows re-write the pool's current
            # content (identity), so rows beyond the group never corrupt a
            # slot even if their (junk) slot index collides with a real one
            out = []
            for c, s in zip(caches, slot_c):
                buf = dict(c)
                for kk in ("k", "v"):
                    arr = buf[kk]
                    for i in range(k):
                        cur = lax.dynamic_slice_in_dim(arr, slots[i], 1, 0)
                        new = jnp.where(active[i], s[kk][i][None], cur)
                        arr = lax.dynamic_update_slice_in_dim(
                            arr, new, slots[i], 0)
                    buf[kk] = arr
                out.append(buf)
            return out, nxt, keys

        return body

    def _decode_body(self, vocab_gather=None):
        """Shared decode trace: one token for EVERY slot, per-slot
        positions, per-slot sampler keys (each slot draws exactly like a
        B=1 ``generate()`` so batching never perturbs a request)."""
        model, sample = self.model, self._sample

        def slot_sample(lg, key):
            nxt, key = sample(lg[None], key)
            return nxt[0], key

        def body(params, caches, tokens, pos, active, keys):
            with annotate("chainermn.decode"):
                return body_inner(params, caches, tokens, pos, active, keys)

        def body_inner(params, caches, tokens, pos, active, keys):
            lg, caches = model.apply(params, tokens[:, None], pos[:, None],
                                     kv_caches=caches)
            lg = lg[:, 0]
            if vocab_gather is not None:
                lg = vocab_gather(lg)
            nxt, keys = jax.vmap(slot_sample)(lg, keys)
            # free/retired slots ride along masked — shapes never change
            nxt = jnp.where(active, nxt, jnp.zeros_like(nxt))
            return caches, nxt, keys

        return body

    def _paged_prefill_body(self, bucket: int, vocab_gather=None):
        """Paged suffix-prefill trace for one bucket: each group row
        writes its padded suffix THROUGH its block-table row into the
        shared store (scatter), attends its gathered table span, and
        samples its first token from its last REAL position — all inside
        the model's ``[B, T]`` position path via
        ``paged_update_cache_and_attend``. No slot gather/scatter and no
        prefix splice: a cached prefix is just table entries, and
        inactive rows carry all-scratch tables so their writes land in
        the scratch block instead of anyone's KV."""
        model, sample = self.model, self._sample

        def slot_sample(lg, key):
            nxt, key = sample(lg[None], key)
            return nxt[0], key

        def body(params, store, table, tokens, starts, last_idx, active,
                 keys):
            with annotate("chainermn.prefill"):
                caches = [dict(layer, table=table) for layer in store]
                pos = starts[:, None] + jnp.arange(bucket)[None, :]
                logits, new_store = model.apply(params, tokens, pos,
                                                kv_caches=caches)
                lg = jax.vmap(
                    lambda row, i: lax.dynamic_slice_in_dim(row, i, 1, 0)[0]
                )(logits, last_idx)
                if vocab_gather is not None:
                    lg = vocab_gather(lg)
                nxt, keys = jax.vmap(slot_sample)(lg, keys)
                nxt = jnp.where(active, nxt, jnp.zeros_like(nxt))
                return new_store, nxt, keys

        return body

    def _paged_decode_body(self, vocab_gather=None):
        """Paged decode trace: one token for EVERY slot through the
        ``[n_slots, max_blocks]`` table — per-slot positions and sampler
        keys exactly like the dense body; free/retired slots carry
        all-scratch table rows, so their masked ride-along writes land in
        the scratch block. ``paged_kernel=True`` rides into the cache
        dicts as the static ``use_kernel`` flag — a different trace, not
        a different operand; with the flag off this body is byte-for-byte
        the pre-kernel trace (``**{}`` adds nothing)."""
        model, sample = self.model, self._sample
        extra = {"use_kernel": True} if self.paged_kernel else {}

        def slot_sample(lg, key):
            nxt, key = sample(lg[None], key)
            return nxt[0], key

        def body(params, store, table, tokens, pos, active, keys):
            with annotate("chainermn.decode"):
                caches = [dict(layer, table=table, **extra)
                          for layer in store]
                lg, new_store = model.apply(params, tokens[:, None],
                                            pos[:, None], kv_caches=caches)
                lg = lg[:, 0]
                if vocab_gather is not None:
                    lg = vocab_gather(lg)
                nxt, keys = jax.vmap(slot_sample)(lg, keys)
                nxt = jnp.where(active, nxt, jnp.zeros_like(nxt))
                return new_store, nxt, keys

        return body

    def _spec_verify_body(self, vocab_gather=None):
        """Speculative verify trace: score the ``k+1``-token window
        ``[t0, d1..dk]`` per slot at positions ``[p..p+k]`` in ONE model
        call, returning every position's greedy (argmax) choice. The
        host commits the longest draft prefix matching those choices
        plus one correction token. ``valid`` caps each slot's K/V
        writes (rows past it land in the scratch block — see
        ``paged_update_cache_and_attend``): slots near ``cache_len``
        would otherwise clamp their table lookup onto a LIVE row. The
        rejected rows this window writes are garbage only until the
        next window: its span always covers them, and every row is
        rewritten before any query attends it."""
        model = self.model
        window = self._spec.k + 1
        extra = {"use_kernel": True} if self.paged_kernel else {}

        def body(params, store, table, tokens, pos, valid, active):
            with annotate("chainermn.spec_verify"):
                caches = [dict(layer, table=table, valid=valid, **extra)
                          for layer in store]
                posm = pos[:, None] + jnp.arange(window)[None, :]
                lg, new_store = model.apply(params, tokens, posm,
                                            kv_caches=caches)
                if vocab_gather is not None:
                    lg = vocab_gather(lg)
                g = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                g = jnp.where(active[:, None], g, jnp.zeros_like(g))
                return new_store, g

        return body

    def _paged_decode_steps_body(self, n: int, vocab_gather=None):
        """Multi-token paged decode: ``n`` chained decode steps inside a
        ``lax.fori_loop`` — ONE dispatch commits ``n`` tokens per active
        slot (the non-speculative dispatch-amortization program; PERF.md
        "Dispatch amortization"). Each iteration samples through the
        same per-slot key splits as ``n`` separate decode steps, so the
        token stream is identical to the per-token program. ``valid``
        masks each iteration's single write for slots that crossed
        ``cache_len`` mid-window (their later rows are discarded by the
        scheduler's retirement anyway)."""
        model, sample = self.model, self._sample
        cache_len = self.cache_len
        extra = {"use_kernel": True} if self.paged_kernel else {}

        def slot_sample(lg, key):
            nxt, key = sample(lg[None], key)
            return nxt[0], key

        def body(params, store, table, tokens, pos, active, keys):
            with annotate("chainermn.decode"):
                def step(i, carry):
                    store, tok, keys, out = carry
                    p = pos + i
                    valid = (active & (p < cache_len)).astype(jnp.int32)
                    caches = [dict(layer, table=table, valid=valid,
                                   **extra)
                              for layer in store]
                    lg, store = model.apply(params, tok[:, None],
                                            p[:, None], kv_caches=caches)
                    lg = lg[:, 0]
                    if vocab_gather is not None:
                        lg = vocab_gather(lg)
                    nxt, keys = jax.vmap(slot_sample)(lg, keys)
                    nxt = jnp.where(active, nxt, jnp.zeros_like(nxt))
                    return store, nxt, keys, out.at[:, i].set(nxt)

                out0 = jnp.zeros((tokens.shape[0], n), jnp.int32)
                store, _, keys, out = lax.fori_loop(
                    0, n, step, (store, tokens, keys, out0))
                return store, out, keys

        return body

    def _decode_steps_body(self, n: int, vocab_gather=None):
        """Dense twin of :meth:`_paged_decode_steps_body`: the same
        fori_loop over the pooled per-slot cache regions. Overshooting
        writes clamp to a slot's own last row — stale-rows masking
        covers them exactly like warmup garbage."""
        model, sample = self.model, self._sample

        def slot_sample(lg, key):
            nxt, key = sample(lg[None], key)
            return nxt[0], key

        def body(params, caches, tokens, pos, active, keys):
            with annotate("chainermn.decode"):
                def step(i, carry):
                    caches, tok, keys, out = carry
                    lg, caches = model.apply(params, tok[:, None],
                                             (pos + i)[:, None],
                                             kv_caches=caches)
                    lg = lg[:, 0]
                    if vocab_gather is not None:
                        lg = vocab_gather(lg)
                    nxt, keys = jax.vmap(slot_sample)(lg, keys)
                    nxt = jnp.where(active, nxt, jnp.zeros_like(nxt))
                    return caches, nxt, keys, out.at[:, i].set(nxt)

                out0 = jnp.zeros((tokens.shape[0], n), jnp.int32)
                caches, _, keys, out = lax.fori_loop(
                    0, n, step, (caches, tokens, keys, out0))
                return caches, out, keys

        return body

    def _init_paged_store(self, local_heads: Optional[int] = None):
        return init_paged_kv_caches(self.model, self.kv_blocks,
                                    self.kv_block_size,
                                    local_heads=local_heads,
                                    quant=self.kv_quant)

    def _insert_body(self):
        """Prefix insert: copy each NEW full block's rows out of the donor
        slot into its allocated store block. Sequential per-block updates;
        blocks past ``n_used`` re-write the store's current content
        (identity), so junk trailing ids never clobber a live block."""
        bs = self.prefix_cache.block_size
        n_prog = self._n_prog_blocks

        def body(store, caches, slot, block_ids, row_starts, n_used):
            with annotate("chainermn.prefix_insert"):
                out = []
                for st, c in zip(store, caches):
                    buf = dict(st)
                    for kk in ("k", "v"):
                        arr = buf[kk]
                        h, dh = c[kk].shape[2], c[kk].shape[3]
                        for j in range(n_prog):
                            blk = lax.dynamic_slice(
                                c[kk], (slot, row_starts[j], 0, 0),
                                (1, bs, h, dh))[0]
                            cur = lax.dynamic_slice_in_dim(
                                arr, block_ids[j], 1, 0)[0]
                            new = jnp.where(j < n_used, blk, cur)
                            arr = lax.dynamic_update_slice_in_dim(
                                arr, new[None], block_ids[j], 0)
                        buf[kk] = arr
                    out.append(buf)
                return out

        return body

    def _init_store(self, local_heads: Optional[int] = None):
        pc = self.prefix_cache
        h = local_heads or self.model.n_heads
        dh = self.model.d_model // self.model.n_heads
        z = lambda: jnp.zeros((pc.n_blocks, pc.block_size, h, dh),
                              self.model.compute_dtype)
        return [{"k": z(), "v": z()} for _ in range(self.model.n_layers)]

    def _kv_gather_body(self):
        """Migration read side: pull one bucket's worth of block rows
        (every array in each layer dict — int8 rows AND their scales move
        as stored, no dequant round-trip) out of the store by id, in ONE
        dispatch. The block-id operand is data, not a trace constant, so
        each warmup-bucketed width compiles exactly once and covers every
        block list of that size — the same scalar-operand trick as the
        paged decode path. Junk trailing ids gather scratch content the
        importer's ``n_used`` mask discards. Compiled WITHOUT donation:
        export must leave the source store intact so a failed handover
        can keep decoding in place."""
        def body(store, ids):
            with annotate("chainermn.kv_gather"):
                return [{kk: jnp.take(layer[kk], ids, axis=0)
                         for kk in layer} for layer in store]

        return body

    def _kv_scatter_body(self, width: int):
        """Migration write side: land ``n_used`` gathered block rows into
        freshly allocated ids of THIS store (donated — the store is
        consumed and returned like every other program), one compiled
        program per warmup bucket ``width``. Rows past ``n_used`` carry
        the scratch id 0 and re-write scratch's current content
        (identity), so each bucket's program covers every migration size
        it pads to and duplicate padding ids stay deterministic."""
        def body(store, ids, rows, n_used):
            with annotate("chainermn.kv_scatter"):
                valid = jnp.arange(width) < n_used
                out = []
                for layer, lrows in zip(store, rows):
                    buf = dict(layer)
                    for kk in layer:
                        cur = jnp.take(buf[kk], ids, axis=0)
                        mask = valid.reshape(
                            (-1,) + (1,) * (cur.ndim - 1))
                        buf[kk] = buf[kk].at[ids].set(
                            jnp.where(mask, lrows[kk], cur))
                    out.append(buf)
                return out

        return body

    def _migration_bucket_widths(self) -> tuple:
        """Warmup bucket widths for the fused migration transfer: powers
        of two up to ``n_max`` plus ``n_max`` itself, always including 1
        (the per-block reference path rides the width-1 program). A
        transfer pads its block list to the smallest covering bucket —
        at most 2x the live blocks move, and no block count ever
        compiles a new program."""
        widths = {1, self._n_max}
        w = 2
        while w < self._n_max:
            widths.add(w)
            w *= 2
        return tuple(sorted(widths))

    def _mig_bucket(self, n: int) -> int:
        """Smallest warmup bucket covering ``n`` blocks."""
        for w in self._mig_buckets:
            if w >= n:
                return w
        raise RuntimeError(
            f"{n} blocks exceed the largest migration bucket "
            f"{self._mig_buckets[-1]}")

    def _build_fns(self):
        if self.paged:
            self._prefill_fns = {
                b: jax.jit(self._paged_prefill_body(b), donate_argnums=(1,))
                for b in self.prefill_buckets
            }
            self._decode_fn = jax.jit(self._paged_decode_body(),
                                      donate_argnums=(1,))
            self._mig_buckets = self._migration_bucket_widths()
            self._kv_gather_fns = {
                w: jax.jit(self._kv_gather_body())
                for w in self._mig_buckets
            }
            self._kv_scatter_fns = {
                w: jax.jit(self._kv_scatter_body(w), donate_argnums=(0,))
                for w in self._mig_buckets
            }
            if self._spec is not None:
                self._spec_fn = jax.jit(self._spec_verify_body(),
                                        donate_argnums=(1,))
            if self.decode_window > 1:
                self._window_fn = jax.jit(
                    self._paged_decode_steps_body(self.decode_window),
                    donate_argnums=(1,))
            return
        self._prefill_fns = {
            b: jax.jit(self._prefill_body(b), donate_argnums=(1,))
            for b in self.prefill_buckets
        }
        self._decode_fn = jax.jit(self._decode_body(), donate_argnums=(1,))
        if self.decode_window > 1:
            self._window_fn = jax.jit(
                self._decode_steps_body(self.decode_window),
                donate_argnums=(1,))
        if self.prefix_cache is not None:
            self._insert_fn = jax.jit(self._insert_body(),
                                      donate_argnums=(0,))

    def _init_tp_caches(self, comm):
        from jax.sharding import NamedSharding, PartitionSpec as P

        axis = self.model.tensor_axis
        n_tp = comm.mesh.shape[axis]
        if self.model.n_heads % n_tp:
            raise ValueError(
                f"n_heads {self.model.n_heads} not divisible by "
                f"tensor-axis size {n_tp}"
            )
        shard = NamedSharding(comm.mesh, P(None, None, axis))
        if self.paged:
            # the store's head axis (2) shards like the dense caches';
            # quant scale arrays are [N, bs, H] so the same spec splits
            # their heads too, and the tiny tables stay replicated
            self.caches = None
            self._store = jax.device_put(self._init_paged_store(), shard)
            return
        self.caches = jax.device_put(
            init_kv_caches(self.model, self.n_slots, self.cache_len), shard)
        if self.prefix_cache is not None:
            # full-head store buffers; device_put splits the head axis
            # over the mesh exactly like the pooled caches
            self._store = jax.device_put(self._init_store(), shard)

    def _build_tp_fns(self, comm):
        from jax.sharding import PartitionSpec as P

        axis = self.model.tensor_axis
        gather = None
        if self.model.vocab_parallel_head:
            def gather(lg):
                return lax.all_gather(lg, axis, axis=-1, tiled=True)

        if self.paged:
            layer_spec = {"k": P(None, None, axis), "v": P(None, None, axis)}
            if self.kv_quant == "int8":
                layer_spec.update(k_scale=P(None, None, axis),
                                  v_scale=P(None, None, axis))
            store_spec = [dict(layer_spec)
                          for _ in range(self.model.n_layers)]
            self._prefill_fns = {
                b: jax.jit(comm.shard_map(
                    self._paged_prefill_body(b, gather),
                    in_specs=(P(), store_spec, P(), P(), P(), P(), P(),
                              P()),
                    out_specs=(store_spec, P(), P()),
                    check_vma=False,
                ), donate_argnums=(1,))
                for b in self.prefill_buckets
            }
            self._decode_fn = jax.jit(comm.shard_map(
                self._paged_decode_body(gather),
                in_specs=(P(), store_spec, P(), P(), P(), P(), P()),
                out_specs=(store_spec, P(), P()),
                check_vma=False,
            ), donate_argnums=(1,))
            if self._spec is not None:
                self._spec_fn = jax.jit(comm.shard_map(
                    self._spec_verify_body(gather),
                    in_specs=(P(), store_spec, P(), P(), P(), P(), P()),
                    out_specs=(store_spec, P()),
                    check_vma=False,
                ), donate_argnums=(1,))
            if self.decode_window > 1:
                self._window_fn = jax.jit(comm.shard_map(
                    self._paged_decode_steps_body(self.decode_window,
                                                  gather),
                    in_specs=(P(), store_spec, P(), P(), P(), P(), P()),
                    out_specs=(store_spec, P(), P()),
                    check_vma=False,
                ), donate_argnums=(1,))
            return

        cache_spec = [{"k": P(None, None, axis), "v": P(None, None, axis)}
                      for _ in range(self.model.n_layers)]
        prefill_specs = (P(), cache_spec, P(), P(), P(), P(), P(), P())
        if self.prefix_cache is not None:
            prefill_specs = prefill_specs + (cache_spec, P())
        self._prefill_fns = {
            b: jax.jit(comm.shard_map(
                self._prefill_body(b, gather),
                in_specs=prefill_specs,
                out_specs=(cache_spec, P(), P()),
                check_vma=False,
            ), donate_argnums=(1,))
            for b in self.prefill_buckets
        }
        self._decode_fn = jax.jit(comm.shard_map(
            self._decode_body(gather),
            in_specs=(P(), cache_spec, P(), P(), P(), P()),
            out_specs=(cache_spec, P(), P()),
            check_vma=False,
        ), donate_argnums=(1,))
        if self.decode_window > 1:
            self._window_fn = jax.jit(comm.shard_map(
                self._decode_steps_body(self.decode_window, gather),
                in_specs=(P(), cache_spec, P(), P(), P(), P()),
                out_specs=(cache_spec, P(), P()),
                check_vma=False,
            ), donate_argnums=(1,))
        if self.prefix_cache is not None:
            self._insert_fn = jax.jit(comm.shard_map(
                self._insert_body(),
                in_specs=(cache_spec, cache_spec, P(), P(), P(), P()),
                out_specs=cache_spec,
                check_vma=False,
            ), donate_argnums=(0,))

    # ------------------------------------------------------------------ #
    # admission planning (host side, cheap)                               #
    # ------------------------------------------------------------------ #

    def bucket_for(self, suffix_len: int, start: int = 0) -> Optional[int]:
        """Smallest bucket covering a ``suffix_len``-token prefill that
        starts at row ``start`` and must stay inside ``cache_len``;
        ``None`` when no bucket fits."""
        for b in self.prefill_buckets:
            if b >= suffix_len and start + b <= self.cache_len:
                return b
        return None

    def plan_admission(self, prompt, rng=None,
                       max_new: int = 1) -> AdmitPlan:
        """Decide how a prompt admits: match (and pin) the longest cached
        prefix that still leaves a bucket fitting inside the slot, and
        pick that bucket. Pure host work — no device call. The caller owns
        the plan: feed it to :meth:`admit_batch` or return the pin with
        :meth:`cancel_plan`. ``max_new`` is the request's token budget —
        paged admission reserves its worst-case growth blocks from it."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.validate_request(len(prompt), max_new)
        match = None
        if self.prefix_cache is not None:
            max_blocks = self._n_prog_blocks
            while True:
                match = (self.prefix_cache.match(prompt, max_blocks)
                         if max_blocks > 0 else None)
                if match is None:
                    break
                if self.bucket_for(len(prompt) - match.length,
                                   match.length) is not None:
                    break
                # a max-length match can leave no room for a bucket inside
                # cache_len — shrink and retry (rare: near-capacity slots)
                max_blocks = len(match.nodes) - 1
                self.prefix_cache.release(match)
        start = match.length if match is not None else 0
        bucket = self.bucket_for(len(prompt) - start, start)
        assert bucket is not None  # start=0 always fits (validate_request)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        return AdmitPlan(prompt=prompt, rng=rng, match=match, start=start,
                         bucket=bucket, max_new=int(max_new))

    def cancel_plan(self, plan: AdmitPlan) -> None:
        """Discard an unused plan, unpinning its prefix match."""
        if plan.match is not None and self.prefix_cache is not None:
            self.prefix_cache.release(plan.match)

    # ------------------------------------------------------------------ #
    # slot API (host side)                                                #
    # ------------------------------------------------------------------ #

    @property
    def active_slots(self) -> int:
        return int(self._active.sum())

    def validate_request(self, prompt_len: int, max_new_tokens: int) -> None:
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if prompt_len > self.prefill_len:
            raise ValueError(
                f"prompt of {prompt_len} tokens exceeds prefill_len="
                f"{self.prefill_len}"
            )
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt_len + max_new_tokens > self.cache_len:
            raise ValueError(
                f"{prompt_len} prompt + {max_new_tokens} new tokens exceed "
                f"cache_len={self.cache_len}"
            )
        if self.paged:
            need = self.blocks_needed(prompt_len, max_new_tokens)
            if need > self._pool.capacity:
                raise ValueError(
                    f"request needs {need} KV blocks worst-case but the "
                    f"pool holds {self._pool.capacity} — raise kv_blocks "
                    "or shrink the request"
                )

    def warmup(self) -> None:
        """Compile every device program once, on dummy no-op inputs (all
        rows inactive — semantically identity; the garbage K/V rows they
        write are covered by the stale-rows masking argument). After this,
        NOTHING recompiles: the zero-recompile invariant holds across
        every bucket, the decode step, and both prefix-copy programs —
        asserted by tests and carried live by the ``RecompileGuard``."""
        if self._warm:
            return
        if self.active_slots:
            raise RuntimeError("warmup needs an idle engine")
        k = self.prefill_batch
        zeros_i = jnp.zeros((k,), jnp.int32)
        if self.paged:
            # all-scratch tables: every warmup write lands in the scratch
            # block, no allocation and no real KV touched
            tab = jnp.zeros((k, self._n_max), jnp.int32)
            for b in self.prefill_buckets:
                with self._watched(f"serving warmup prefill[{b}]"):
                    self._store, _, _ = self._prefill_fns[b](
                        self.params, self._store, tab,
                        jnp.zeros((k, b), jnp.int32), zeros_i, zeros_i,
                        jnp.zeros((k,), bool),
                        jnp.zeros((k, 2), jnp.uint32))
            with self._watched("serving warmup decode"):
                self._store, _, _ = self._decode_fn(
                    self.params, self._store, jnp.asarray(self._tables),
                    jnp.asarray(self._token), jnp.asarray(self._pos),
                    jnp.asarray(self._active), self._keys)
            if self.migration_supported:
                # all-scratch ids + n_used=0 at EVERY bucket width: the
                # gather reads scratch, the scatter re-writes scratch's
                # own content — one compile per bucket covers every
                # future migration size that pads to it
                for w in self._mig_buckets:
                    mig_ids = jnp.zeros((w,), jnp.int32)
                    with self._watched(f"serving warmup kv_gather[{w}]"):
                        rows = self._kv_gather_fns[w](self._store, mig_ids)
                    with self._watched(f"serving warmup kv_scatter[{w}]"):
                        self._store = self._kv_scatter_fns[w](
                            self._store, mig_ids, rows, jnp.int32(0))
            if self.decode_window > 1:
                with self._watched("serving warmup decode_window"):
                    self._store, _, _ = self._window_fn(
                        self.params, self._store,
                        jnp.asarray(self._tables),
                        jnp.asarray(self._token), jnp.asarray(self._pos),
                        jnp.asarray(self._active), self._keys)
            if self._spec is not None:
                # all rows inactive + valid=0: every verify-window write
                # lands in the scratch block — the one compile covers
                # EVERY accept length (accept is host-side bookkeeping;
                # the program's shapes never depend on it)
                with self._watched("serving warmup spec_verify"):
                    self._store, _ = self._spec_fn(
                        self.params, self._store,
                        jnp.asarray(self._tables),
                        jnp.zeros((self.n_slots, self._spec.k + 1),
                                  jnp.int32),
                        jnp.asarray(self._pos),
                        jnp.zeros((self.n_slots,), jnp.int32),
                        jnp.asarray(self._active))
                self._drafter.warmup()
        else:
            extra = ()
            if self.prefix_cache is not None:
                extra = (self._store,
                         jnp.zeros((k, self._n_prog_blocks), jnp.int32))
            for b in self.prefill_buckets:
                with self._watched(f"serving warmup prefill[{b}]"):
                    self.caches, _, _ = self._prefill_fns[b](
                        self.params, self.caches,
                        jnp.zeros((k, b), jnp.int32), zeros_i, zeros_i,
                        zeros_i, jnp.zeros((k,), bool),
                        jnp.zeros((k, 2), jnp.uint32), *extra)
            with self._watched("serving warmup decode"):
                self.caches, _, _ = self._decode_fn(
                    self.params, self.caches, jnp.asarray(self._token),
                    jnp.asarray(self._pos), jnp.asarray(self._active),
                    self._keys)
            if self.prefix_cache is not None:
                ids = jnp.zeros((self._n_prog_blocks,), jnp.int32)
                with self._watched("serving warmup prefix"):
                    self._store = self._insert_fn(self._store, self.caches,
                                                  jnp.int32(0), ids, ids,
                                                  jnp.int32(0))
        self._warm = True
        self._guard.check()
        self._events.emit("serving_warmup",
                          buckets=list(self.prefill_buckets),
                          prefill_batch=k, paged=self.paged,
                          prefix=self.prefix_cache is not None)

    def prefill(self, prompt: np.ndarray, rng,
                ctx: Optional[dict] = None) -> tuple[int, int]:
        """Admit one prompt into a free slot (no prefix reuse — the PR-1
        surface): runs the smallest covering bucket's compiled prefill,
        returns ``(slot, first_token)``. ``rng`` is the request's own PRNG
        key (its sampler split sequence matches a solo ``generate()``).
        Raises ``RuntimeError`` when no slot is free — admission control
        is the scheduler's job, not a silent queue here."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.validate_request(len(prompt), 1)
        bucket = self.bucket_for(len(prompt))
        plan = AdmitPlan(prompt=prompt, rng=rng, match=None, start=0,
                         bucket=bucket)
        return self.admit_batch([plan], point=SERVING_PREFILL,
                                ctx=ctx)[0]

    def admit_batch(self, plans: Sequence[AdmitPlan], *,
                    point: str = SERVING_PREFILL_BATCH,
                    ctx: Optional[dict] = None
                    ) -> list[tuple[int, int]]:
        """Admit a same-bucket group in ONE batched prefill call (plus one
        prefix-fetch copy per cached member, before): returns ``[(slot,
        first_token), ...]`` in plan order. Slot mirrors commit only after
        the device calls succeed, so a raise BEFORE device execution (the
        fault cut-points) leaves the engine intact — the scheduler then
        errors only this group. A failure that consumed the donated cache
        buffers re-raises as :class:`EngineStateError` (full restart).

        After commit, each member's full prompt blocks are inserted into
        the prefix trie (best effort — an insert failure never un-admits
        a request; a store-corrupting one resets the prefix cache)."""
        if not plans:
            return []
        if len(plans) > self.prefill_batch:
            raise ValueError(
                f"group of {len(plans)} exceeds prefill_batch="
                f"{self.prefill_batch}"
            )
        if len(plans) > len(self.free_slots):
            raise RuntimeError("no free slot (scheduler admitted too many)")
        buckets = {p.bucket for p in plans}
        if len(buckets) != 1:
            raise ValueError(
                f"admission group mixes buckets {sorted(buckets)} — one "
                "compiled program per call"
            )
        if self.paged:
            return self._paged_admit(plans, point=point, ctx=ctx)
        bucket = plans[0].bucket
        k = self.prefill_batch
        if self._pending_inserts:
            self.flush_inserts()   # before slots are picked: never insert
        slots = sorted(self.free_slots)[:len(plans)]  # deterministic pick
        n_cached = sum(p.match is not None for p in plans)
        try:
            try:
                with self._watched("serving prefill", **(ctx or {})), \
                        annotate("chainermn.serving_prefill"):
                    if n_cached:
                        inject(SERVING_PREFIX_COPY, op="fetch",
                               hits=n_cached, batch=len(plans))
                    # fault cut-point INSIDE the watchdog window: an
                    # injected hang here exercises exactly the wedge hang
                    # detection exists for
                    inject(point, batch=len(plans), bucket=bucket,
                           slots=slots)
                    tokens = np.zeros((k, bucket), np.int32)
                    starts = np.zeros((k,), np.int32)
                    last = np.zeros((k,), np.int32)
                    active = np.zeros((k,), bool)
                    slot_ids = np.zeros((k,), np.int32)
                    keys = [jnp.zeros((2,), jnp.uint32)] * k
                    extra = ()
                    if self.prefix_cache is not None:
                        fetch_ids = np.zeros((k, self._n_prog_blocks),
                                             np.int32)
                    for i, (plan, slot) in enumerate(zip(plans, slots)):
                        suffix = plan.prompt[plan.start:]
                        tokens[i, : len(suffix)] = suffix
                        starts[i] = plan.start
                        last[i] = len(suffix) - 1
                        active[i] = True
                        slot_ids[i] = slot
                        keys[i] = plan.rng
                        if plan.match is not None:
                            fetch_ids[i, : len(plan.match.block_ids)] = \
                                plan.match.block_ids
                    if self.prefix_cache is not None:
                        extra = (self._store, jnp.asarray(fetch_ids))
                    self.caches, firsts, keys_out = self._prefill_fns[bucket](
                        self.params, self.caches, jnp.asarray(tokens),
                        jnp.asarray(slot_ids), jnp.asarray(starts),
                        jnp.asarray(last), jnp.asarray(active),
                        jnp.stack(keys), *extra)
                    firsts = device_fetch(firsts)
            except Exception as e:
                if not self._state_ok():
                    raise EngineStateError(
                        f"admission failed mid-device-call "
                        f"({type(e).__name__}: {e}); donated cache buffers "
                        "are gone — restart required"
                    ) from e
                raise
        finally:
            for plan in plans:
                self.cancel_plan(plan)   # pins served their purpose
        out = []
        for i, (plan, slot) in enumerate(zip(plans, slots)):
            first = int(firsts[i])
            self.free_slots.discard(slot)
            self._token[slot] = first
            self._pos[slot] = len(plan.prompt)
            self._active[slot] = True
            self._keys = self._keys.at[slot].set(keys_out[i])
            self._c_prefills[bucket].inc()
            self._events.emit("prefill", slot=slot,
                              prompt_len=len(plan.prompt), bucket=bucket,
                              cached=plan.start, batch=len(plans))
            out.append((slot, first))
            if self.prefix_cache is not None:
                self._pending_inserts.append((plan.prompt, slot))
        self.peak_active = max(self.peak_active, self.active_slots)
        self._guard.check()
        return out

    # ------------------------------------------------------------------ #
    # paged admission + block management                                   #
    # ------------------------------------------------------------------ #

    def _paged_alloc_slot(self, plan: AdmitPlan, slot: int) -> list:
        """Allocate the blocks a plan's prefill writes into ([start,
        len(prompt)) — shared prefix blocks are referenced, not copied),
        write the slot's table mirror, and reserve the worst-case decode
        growth. Raises ``RuntimeError`` when the pool (plus trie
        eviction) cannot cover it — the scheduler's block-budget gate
        makes that unreachable in the no-fault case."""
        bs = self.kv_block_size
        plen = len(plan.prompt)
        shared = list(plan.match.block_ids) if plan.match is not None else []
        need_now = -(-plen // bs) - len(shared)
        new = self.prefix_cache.alloc_blocks(need_now)
        if len(new) < need_now:
            for block in new:
                self._pool.decref(block)
            raise RuntimeError(
                f"kv block pool exhausted: slot {slot} needs {need_now} "
                f"blocks, {len(new)} allocatable (free="
                f"{self._pool.free_blocks})"
            )
        for block in shared:
            self._pool.incref(block)    # the slot co-owns its prefix
        ids = shared + new
        self._tables[slot, :] = 0
        self._tables[slot, : len(ids)] = ids
        self._slot_reserved[slot] = (
            -(-(plen + plan.max_new) // bs) - (-(-plen // bs))
            + self._spec_headroom)
        return ids

    # graftlint: hot — the paged-path body of admit_batch
    def _paged_admit(self, plans: Sequence[AdmitPlan], *, point: str,
                     ctx: Optional[dict] = None) -> list[tuple[int, int]]:
        """Paged twin of the dense ``admit_batch`` body: allocate block
        tables (prefix hits = shared entries, zero copies), run the ONE
        bucketed prefill program through them, then commit mirrors and
        adopt each prompt's full blocks into the trie (``insert_shared``
        — pure bookkeeping, nothing device-side). A failure before the
        device call rolls the allocations back and errors only this
        group; one that consumed the donated store re-raises as
        :class:`EngineStateError`."""
        bucket = plans[0].bucket
        k = self.prefill_batch
        slots = sorted(self.free_slots)[:len(plans)]  # deterministic pick
        n_cached = sum(p.match is not None for p in plans)
        alloc_records: list[tuple[int, list]] = []
        try:
            try:
                with self._watched("serving prefill", **(ctx or {})), \
                        annotate("chainermn.serving_prefill"):
                    if n_cached:
                        inject(SERVING_PREFIX_COPY, op="share",
                               hits=n_cached, batch=len(plans))
                    inject(point, batch=len(plans), bucket=bucket,
                           slots=slots)
                    tokens = np.zeros((k, bucket), np.int32)
                    starts = np.zeros((k,), np.int32)
                    last = np.zeros((k,), np.int32)
                    active = np.zeros((k,), bool)
                    table = np.zeros((k, self._n_max), np.int32)
                    keys = [jnp.zeros((2,), jnp.uint32)] * k
                    for i, (plan, slot) in enumerate(zip(plans, slots)):
                        ids = self._paged_alloc_slot(plan, slot)
                        alloc_records.append((slot, ids))
                        table[i, : len(ids)] = ids
                        suffix = plan.prompt[plan.start:]
                        tokens[i, : len(suffix)] = suffix
                        starts[i] = plan.start
                        last[i] = len(suffix) - 1
                        active[i] = True
                        keys[i] = plan.rng
                    self._store, firsts, keys_out = self._prefill_fns[bucket](
                        self.params, self._store, jnp.asarray(table),
                        jnp.asarray(tokens), jnp.asarray(starts),
                        jnp.asarray(last), jnp.asarray(active),
                        jnp.stack(keys))
                    firsts = device_fetch(firsts)
            except Exception as e:
                for slot, ids in alloc_records:   # undo: nothing admitted
                    for block in ids:
                        self._pool.decref(block)
                    self._slot_reserved[slot] = 0
                    self._tables[slot, :] = 0
                if not self._state_ok():
                    raise EngineStateError(
                        f"admission failed mid-device-call "
                        f"({type(e).__name__}: {e}); donated store buffers "
                        "are gone — restart required"
                    ) from e
                raise
        finally:
            for plan in plans:
                self.cancel_plan(plan)   # pins served their purpose
        out = []
        for (plan, slot), (_, ids) in zip(zip(plans, slots), alloc_records):
            first = int(firsts[len(out)])
            self.free_slots.discard(slot)
            self._token[slot] = first
            self._pos[slot] = len(plan.prompt)
            self._active[slot] = True
            self._keys = self._keys.at[slot].set(keys_out[len(out)])
            self._slot_blocks[slot] = list(ids)
            self._c_prefills[bucket].inc()
            self._events.emit("prefill", slot=slot,
                              prompt_len=len(plan.prompt), bucket=bucket,
                              cached=plan.start, batch=len(plans),
                              blocks=len(ids))
            out.append((slot, first))
            if self._drafter is not None:
                self._drafter.on_admit(slot, plan.prompt, first)
            # zero-copy trie insert: the slot's blocks already hold the
            # prompt's KV — adopting them IS the cache insert
            if (self.prefix_cache.missing_blocks(plan.prompt)
                    >= self._min_insert):
                self.prefix_cache.insert_shared(plan.prompt, ids)
        self.peak_active = max(self.peak_active, self.active_slots)
        self._guard.check()
        return out

    # ------------------------------------------------------------------ #
    # chunked prefill (paged only)                                        #
    # ------------------------------------------------------------------ #

    def plan_chunks(self, plan: AdmitPlan,
                    chunk_tokens: int) -> Optional[list]:
        """Chunk schedule for a plan's suffix: split ``[start, len(prompt))``
        into ``chunk_tokens``-sized pieces and pick each piece's bucket at
        its own frontier. Returns ``[(frontier, chunk_len, bucket), ...]``
        or ``None`` when chunking doesn't apply — non-paged engines, a
        suffix that already fits one chunk (the one-shot path is strictly
        better), or a chunk whose frontier leaves no bucket inside
        ``cache_len`` (``bucket_for``'s ``start + b <= cache_len``
        constraint; an out-of-range bucket would clamp table lookups onto
        live blocks). ``None`` means: admit unchunked."""
        if not self.paged:
            return None
        chunk_tokens = int(chunk_tokens)
        if chunk_tokens < 1:
            return None
        plen = len(plan.prompt)
        if plen - plan.start <= chunk_tokens:
            return None
        from chainermn_tpu.parallel.sequence import chunk_spans

        chunks = []
        for frontier, clen in chunk_spans(plan.start, plen, chunk_tokens):
            bucket = self.bucket_for(clen, frontier)
            if bucket is None:
                return None
            chunks.append((frontier, clen, bucket))
        return chunks

    def begin_chunked(self, plan: AdmitPlan, chunks: list) -> int:
        """Stage a chunked admission: claim a free slot, allocate ALL the
        prompt's blocks up front (shared prefix blocks referenced, not
        copied — exactly :meth:`_paged_alloc_slot`'s accounting) and
        reserve decode growth, but leave the slot's decode-table row
        **all-scratch**: decode rounds interleaving with the chunks still
        pass the full ``[n_slots]`` table, and the masked ride-along
        write at this inactive slot's stale position must land in the
        scratch block, never in a real (possibly trie-shared) block. The
        real ids live privately in the :class:`ChunkedPrefill` until the
        final chunk commits them. Consumes the plan (its match pin
        converts into refcounts). Returns the claimed slot."""
        if not self.paged:
            raise RuntimeError("chunked prefill needs paged=True")
        if not self.free_slots:
            raise RuntimeError("no free slot for chunked prefill")
        slot = min(self.free_slots)
        bs = self.kv_block_size
        plen = len(plan.prompt)
        shared = (list(plan.match.block_ids)
                  if plan.match is not None else [])
        need_now = -(-plen // bs) - len(shared)
        try:
            new = self.prefix_cache.alloc_blocks_atomic(need_now)
            if new is None:
                raise RuntimeError(
                    f"kv block pool exhausted: chunked slot {slot} needs "
                    f"{need_now} blocks (free={self._pool.free_blocks})")
            for block in shared:
                self._pool.incref(block)   # the slot co-owns its prefix
        finally:
            self.cancel_plan(plan)
        ids = shared + new
        self._tables[slot, :] = 0          # stays scratch until commit
        self._slot_reserved[slot] = (
            -(-(plen + plan.max_new) // bs) - (-(-plen // bs))
            + self._spec_headroom)
        self._slot_blocks[slot] = list(ids)
        self.free_slots.discard(slot)
        self._chunking[slot] = ChunkedPrefill(
            prompt=plan.prompt, rng=plan.rng, start=plan.start,
            max_new=int(plan.max_new), ids=ids, chunks=list(chunks),
            t_begin=time.perf_counter())
        return slot

    def chunk_state(self, slot: int) -> Optional[ChunkedPrefill]:
        return self._chunking.get(slot)

    def prefill_chunk(self, slot: int,
                      ctx: Optional[dict] = None) -> Optional[int]:
        """Run ONE staged chunk through its bucket's compiled prefill
        program (row 0 carries the chunk at ``starts=frontier``; the
        other rows ride inactive on all-scratch tables — the warmup
        shapes, so nothing recompiles). Intermediate chunks discard the
        sampled output and consume NO rng (their pad-tail garbage rows
        are overwritten by the next chunk's writes before anything
        attends them — the module's stale-rows induction, unchanged);
        the FINAL chunk samples with the request's own rng (the one
        admission split, sampler parity with a solo ``generate()``),
        commits the slot's table/mirrors, and returns the first token.
        Returns ``None`` after an intermediate chunk.

        A raise before the device call leaves the staged state intact
        (the scheduler may retry or release the slot); one that consumed
        the donated store re-raises as :class:`EngineStateError`."""
        st = self._chunking[slot]
        frontier, clen, bucket = st.chunks[st.next_idx]
        final = st.next_idx == len(st.chunks) - 1
        k = self.prefill_batch
        try:
            with self._watched("serving chunk_prefill", **(ctx or {})), \
                    annotate("chainermn.serving_chunk_prefill"):
                inject(SERVING_CHUNK_PREFILL, slot=slot,
                       chunk=st.next_idx, of=len(st.chunks),
                       bucket=bucket, frontier=frontier)
                tokens = np.zeros((k, bucket), np.int32)
                starts = np.zeros((k,), np.int32)
                last = np.zeros((k,), np.int32)
                active = np.zeros((k,), bool)
                table = np.zeros((k, self._n_max), np.int32)
                keys = [jnp.zeros((2,), jnp.uint32)] * k
                tokens[0, :clen] = st.prompt[frontier:frontier + clen]
                starts[0] = frontier
                last[0] = clen - 1
                active[0] = True
                table[0, : len(st.ids)] = st.ids
                if final:
                    keys[0] = st.rng
                self._store, nxt, keys_out = self._prefill_fns[bucket](
                    self.params, self._store, jnp.asarray(table),
                    jnp.asarray(tokens), jnp.asarray(starts),
                    jnp.asarray(last), jnp.asarray(active),
                    jnp.stack(keys))
                first = int(device_fetch(nxt)[0]) if final else None
        except Exception as e:
            if not self._state_ok():
                raise EngineStateError(
                    f"chunked prefill failed mid-device-call "
                    f"({type(e).__name__}: {e}); donated store buffers "
                    "are gone — restart required") from e
            raise
        st.next_idx += 1
        self._c_chunks.inc()
        self._c_prefills[bucket].inc()
        self._h_chunk_tokens.observe(clen)
        self._events.emit("prefill_chunk", slot=slot, chunk=st.next_idx,
                          of=len(st.chunks), tokens=clen, bucket=bucket,
                          frontier=frontier, final=final)
        self._guard.check()
        if not final:
            return None
        # final-chunk commit: the staged ids become the slot's decode
        # table and the slot joins the active set — from here on it is
        # indistinguishable from an unchunked admission
        plen = len(st.prompt)
        self._tables[slot, : len(st.ids)] = st.ids
        self._token[slot] = first
        self._pos[slot] = plen
        self._active[slot] = True
        self._keys = self._keys.at[slot].set(keys_out[0])
        self._chunking.pop(slot)
        self._events.emit("prefill", slot=slot, prompt_len=plen,
                          bucket=bucket, cached=st.start, batch=1,
                          blocks=len(st.ids), chunks=len(st.chunks))
        if self._drafter is not None:
            self._drafter.on_admit(slot, st.prompt, first)
        if (self.prefix_cache.missing_blocks(st.prompt)
                >= self._min_insert):
            self.prefix_cache.insert_shared(st.prompt, st.ids)
        self.peak_active = max(self.peak_active, self.active_slots)
        return first

    # ------------------------------------------------------------------ #
    # KV block migration (paged, single-device)                           #
    # ------------------------------------------------------------------ #

    def _gather_block_rows(self, ids: list, ctx: Optional[dict],
                           fused: bool) -> list:
        """Pull ``ids``' block rows to the host. Fused: pad the block
        list to the smallest warmup bucket and run ONE gather dispatch.
        Per-block (the pre-round-20 reference path, kept for the
        bit-equality pin and the PERF.md phase model): one width-1
        gather per block — N dispatches + N host bounces. Both return
        the identical layers structure."""
        n = len(ids)
        if fused:
            w = self._mig_bucket(n)
            ids_op = np.zeros((w,), np.int32)
            ids_op[:n] = ids
            with self._watched(f"serving kv_gather[{w}]", **(ctx or {})), \
                    annotate("chainermn.kv_gather"):
                rows = self._kv_gather_fns[w](self._store,
                                              jnp.asarray(ids_op))
            self._guard.check()
            return [{kk: np.asarray(layer[kk])[:n] for kk in layer}
                    for layer in rows]
        per_block = []
        for b in ids:
            one = np.asarray([b], np.int32)
            with self._watched("serving kv_gather[1]", **(ctx or {})), \
                    annotate("chainermn.kv_gather"):
                rows = self._kv_gather_fns[1](self._store,
                                              jnp.asarray(one))
            self._guard.check()
            per_block.append([{kk: np.asarray(layer[kk])
                               for kk in layer} for layer in rows])
        return [{kk: np.concatenate([blk[li][kk] for blk in per_block])
                 for kk in per_block[0][li]}
                for li in range(len(per_block[0]))]

    def _scatter_block_rows(self, new: list, layers: list,
                            ctx: Optional[dict], fused: bool) -> None:
        """Land host ``layers`` rows into blocks ``new`` of THIS store.
        Fused: one scatter dispatch at the covering bucket width.
        Per-block: one width-1 scatter per block (reference path). Any
        raise leaves rollback to the caller."""
        n = len(new)
        if fused:
            w = self._mig_bucket(n)
            ids_op = np.zeros((w,), np.int32)
            ids_op[:n] = new
            rows = []
            for layer in layers:
                full = {}
                for kk, arr in layer.items():
                    pad = np.zeros((w,) + tuple(arr.shape[1:]), arr.dtype)
                    pad[:n] = arr
                    full[kk] = jnp.asarray(pad)
                rows.append(full)
            with self._watched(f"serving kv_scatter[{w}]", **(ctx or {})), \
                    annotate("chainermn.kv_scatter"):
                self._store = self._kv_scatter_fns[w](
                    self._store, jnp.asarray(ids_op), rows, jnp.int32(n))
            return
        for j in range(n):
            one = np.asarray([new[j]], np.int32)
            rows = [{kk: jnp.asarray(arr[j:j + 1])
                     for kk, arr in layer.items()} for layer in layers]
            with self._watched("serving kv_scatter[1]", **(ctx or {})), \
                    annotate("chainermn.kv_scatter"):
                self._store = self._kv_scatter_fns[1](
                    self._store, jnp.asarray(one), rows, jnp.int32(1))

    def export_slot_kv(self, slot: int,
                       ctx: Optional[dict] = None, *,
                       fused: bool = True) -> dict:
        """Read an active slot's entire KV state out to the host: ONE
        compiled gather dispatch at the covering warmup bucket (no
        donation — the source store is untouched, so a failed handover
        keeps decoding in place) pulls the slot's block rows, then the
        host slices exactly ``n_blocks`` rows per layer array — bytes
        moved = bucket(n) x block_bytes, int8 rows + scales as stored,
        no dequant round-trip. ``fused=False`` keeps the per-block
        reference path (one dispatch per block) for parity pins. The
        payload plus the slot's host mirrors (position, last token,
        sampler key) is everything a decode-tier engine needs to
        continue the request token-exactly via :meth:`import_slot_kv`.
        Read-only: the slot stays active here; the caller releases it
        only after the import commits."""
        if not self.migration_supported:
            raise RuntimeError(
                "KV migration needs paged=True on a single-device engine "
                "(TP stores are head-sharded across the mesh)")
        if not self._active[slot]:
            raise RuntimeError(f"slot {slot} is not active")
        t0 = time.perf_counter()
        ids = list(self._slot_blocks[slot])
        n = len(ids)
        layers = self._gather_block_rows(ids, ctx, fused)
        return {
            "n_blocks": n,
            "block_size": self.kv_block_size,
            "kv_quant": self.kv_quant,
            "n_layers": self.model.n_layers,
            "pos": int(self._pos[slot]),
            "token": int(self._token[slot]),
            "key": np.asarray(self._keys[slot]),
            "layers": layers,
            "t_start": t0,
        }

    def can_import(self, payload: dict, max_new: int = 1, *,
                   static_only: bool = False) -> bool:
        """Cheap host-side pre-check that :meth:`import_slot_kv` would
        succeed here: layout agreement (block size / quant / layers /
        row shapes), a free slot, and block budget for the resident
        blocks plus remaining decode growth. ``static_only`` checks the
        layout/position constraints alone — a False there means the
        import can NEVER succeed on this engine (structural mismatch),
        while a transient False (slots/blocks busy) clears on its own."""
        if not self.migration_supported:
            return False
        if not static_only and not (self._warm and self.free_slots):
            return False
        if (int(payload["block_size"]) != self.kv_block_size
                or str(payload["kv_quant"]) != self.kv_quant
                or int(payload["n_layers"]) != self.model.n_layers):
            return False
        n = int(payload["n_blocks"])
        if not 0 < n <= self._n_max:
            return False
        for kk, arr in payload["layers"][0].items():
            if tuple(arr.shape[1:]) != tuple(self._store[0][kk].shape[1:]):
                return False
        pos = int(payload["pos"])
        if pos + int(max_new) > self.cache_len:
            return False
        if static_only:
            return True
        bs = self.kv_block_size
        need = (n + max(0, -(-(pos + int(max_new)) // bs) - n)
                + self._spec_headroom)
        return need <= self.kv_blocks_admittable()

    def import_slot_kv(self, payload: dict, *,
                       prompt: Optional[np.ndarray] = None,
                       max_new: int = 1,
                       ctx: Optional[dict] = None,
                       fused: bool = True) -> int:
        """Land a migrated request into THIS engine: allocate fresh
        blocks, scatter the host rows in with the compiled-once pair's
        write side (one dispatch at the covering warmup bucket — the pad
        tail carries scratch ids and identity content), and
        commit the slot mirrors (position/token/sampler key) so the next
        decode round continues the request token-exactly. When
        ``prompt`` is given, its full blocks are adopted into this
        engine's prefix trie (``insert_shared`` — the migrated prefix
        becomes ground truth here, not router belief). Returns the slot.
        Raises ``RuntimeError`` (layout/budget) with the engine intact —
        the caller's fallback is decoding in place at the source."""
        if not self.migration_supported:
            raise RuntimeError(
                "KV migration needs paged=True on a single-device engine")
        if (int(payload["block_size"]) != self.kv_block_size
                or str(payload["kv_quant"]) != self.kv_quant
                or int(payload["n_layers"]) != self.model.n_layers):
            raise RuntimeError(
                "migration layout mismatch: source/dest engines disagree "
                "on block_size/kv_quant/n_layers")
        if not self.free_slots:
            raise RuntimeError("no free slot for migration import")
        n = int(payload["n_blocks"])
        if not 0 < n <= self._n_max:
            raise RuntimeError(
                f"migration carries {n} blocks; this engine's tables "
                f"hold at most {self._n_max}")
        pos = int(payload["pos"])
        if pos + int(max_new) > self.cache_len:
            raise RuntimeError(
                f"migrated position {pos} + {max_new} new tokens exceed "
                f"cache_len={self.cache_len}")
        new = self.prefix_cache.alloc_blocks_atomic(n)
        if new is None:
            raise RuntimeError(
                f"kv block pool exhausted: import needs {n} blocks "
                f"(free={self._pool.free_blocks})")
        slot = min(self.free_slots)
        bs = self.kv_block_size
        try:
            self._scatter_block_rows(new, payload["layers"], ctx, fused)
        except Exception as e:
            for block in new:
                self._pool.decref(block)
            if not self._state_ok():
                raise EngineStateError(
                    f"migration import failed mid-device-call "
                    f"({type(e).__name__}: {e}); donated store buffers "
                    "are gone — restart required") from e
            raise
        self._guard.check()
        self.free_slots.discard(slot)
        self._tables[slot, :] = 0
        self._tables[slot, :n] = new
        self._slot_blocks[slot] = list(new)
        self._slot_reserved[slot] = (
            max(0, -(-(pos + int(max_new)) // bs) - n)
            + self._spec_headroom)
        self._pos[slot] = pos
        self._token[slot] = int(payload["token"])
        self._active[slot] = True
        self._keys = self._keys.at[slot].set(jnp.asarray(payload["key"]))
        seconds = time.perf_counter() - float(payload.get("t_start", 0.0)) \
            if payload.get("t_start") else 0.0
        self._c_migrations.inc()
        self._c_migrated_blocks.inc(n)
        if seconds > 0.0:
            self._h_migration.observe(seconds)
        self._events.emit("kv_migrate", slot=slot, blocks=n, pos=pos,
                          seconds=round(seconds, 6))
        if self._drafter is not None and prompt is not None:
            self._drafter.on_admit(slot, np.asarray(prompt, np.int32),
                                   int(payload["token"]))
        if prompt is not None:
            prompt = np.asarray(prompt, np.int32).reshape(-1)
            if (self.prefix_cache.missing_blocks(prompt)
                    >= self._min_insert):
                self.prefix_cache.insert_shared(prompt, new)
        self.peak_active = max(self.peak_active, self.active_slots)
        return slot

    # ------------------------------------------------------------------ #
    # cross-replica prefix sharing (paged, single-device)                 #
    # ------------------------------------------------------------------ #

    def export_prefix_kv(self, tokens, ctx: Optional[dict] = None, *,
                         min_blocks: int = 1) -> Optional[dict]:
        """Read this engine's cached prefix of ``tokens`` out to the
        host through the fused migration gather — the share payload
        another replica imports via :meth:`import_prefix_kv` instead of
        re-prefilling blocks the fleet already paid for. Returns ``None``
        (never raises on a cold cache) when sharing is unsupported, the
        trie holds fewer than ``min_blocks`` of the prompt, or the
        engine is not warm — the caller's fallback is a plain prefill.
        Read-only on the store; the matched blocks are pinned only for
        the duration of the gather."""
        if not (self.migration_supported and self._warm
                and self.prefix_cache is not None):
            return None
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        m = self.prefix_cache.match(tokens)
        if m is None:
            return None
        try:
            n = len(m.block_ids)
            if n < max(1, int(min_blocks)):
                return None
            t0 = time.perf_counter()
            layers = self._gather_block_rows(list(m.block_ids), ctx, True)
            return {
                "n_blocks": n,
                "block_size": self.kv_block_size,
                "kv_quant": self.kv_quant,
                "n_layers": self.model.n_layers,
                "tokens": tokens[:m.length].copy(),
                "layers": layers,
                "t_start": t0,
            }
        finally:
            self.prefix_cache.release(m)

    def can_import_prefix(self, payload: dict, *,
                          static_only: bool = False) -> bool:
        """Pre-check that :meth:`import_prefix_kv` would succeed here:
        layout agreement and (non-static) warm programs plus block
        budget. Same static/transient split as :meth:`can_import`."""
        if not (self.migration_supported and self.prefix_cache
                is not None):
            return False
        if (int(payload["block_size"]) != self.kv_block_size
                or str(payload["kv_quant"]) != self.kv_quant
                or int(payload["n_layers"]) != self.model.n_layers):
            return False
        n = int(payload["n_blocks"])
        if not 0 < n <= self._n_max:
            return False
        for kk, arr in payload["layers"][0].items():
            if tuple(arr.shape[1:]) != tuple(self._store[0][kk].shape[1:]):
                return False
        if static_only:
            return True
        return self._warm and n <= self.kv_blocks_admittable()

    def import_prefix_kv(self, payload: dict,
                         ctx: Optional[dict] = None) -> int:
        """Adopt a shared prefix payload into THIS engine's trie:
        allocate blocks all-or-nothing, scatter the rows in through the
        fused write side, then ``insert_shared`` hands ownership to the
        trie (each adopted block settles at refcount 1, trie-owned; a
        block whose trie position was cached concurrently drops straight
        back to the free list). The next admission matching this prefix
        prefills ZERO of its shared blocks. Returns blocks adopted (0 =
        already resident, nothing to do); raises ``RuntimeError`` with
        the engine intact on layout mismatch or pool exhaustion — the
        caller's fallback is a plain prefill."""
        if not self.migration_supported or self.prefix_cache is None:
            raise RuntimeError(
                "prefix sharing needs paged=True on a single-device "
                "engine")
        if (int(payload["block_size"]) != self.kv_block_size
                or str(payload["kv_quant"]) != self.kv_quant
                or int(payload["n_layers"]) != self.model.n_layers):
            raise RuntimeError(
                "share layout mismatch: source/dest engines disagree "
                "on block_size/kv_quant/n_layers")
        n = int(payload["n_blocks"])
        if not 0 < n <= self._n_max:
            raise RuntimeError(
                f"shared prefix carries {n} blocks; this engine's "
                f"tables hold at most {self._n_max}")
        tokens = np.asarray(payload["tokens"], np.int32).reshape(-1)
        if self.prefix_cache.missing_blocks(tokens) == 0:
            return 0                       # already ground truth here
        new = self.prefix_cache.alloc_blocks_atomic(n)
        if new is None:
            raise RuntimeError(
                f"kv block pool exhausted: share import needs {n} "
                f"blocks (free={self._pool.free_blocks})")
        try:
            self._scatter_block_rows(new, payload["layers"], ctx, True)
        except Exception as e:
            for block in new:
                self._pool.decref(block)
            if not self._state_ok():
                raise EngineStateError(
                    f"share import failed mid-device-call "
                    f"({type(e).__name__}: {e}); donated store buffers "
                    "are gone — restart required") from e
            raise
        self._guard.check()
        adopted = self.prefix_cache.insert_shared(tokens, new)
        for block in new:
            self._pool.decref(block)
        return adopted

    def blocks_needed(self, prompt_len: int, max_new: int,
                      start: int = 0) -> int:
        """Worst-case NEW blocks a request admits with: blocks covering
        ``[start, prompt_len + max_new)`` (``start`` = cached-prefix
        tokens, whose blocks are shared, not allocated). The scheduler's
        block-budget admission compares this against
        :meth:`kv_blocks_admittable`. Multi-token rounds add
        ``ceil(write_horizon / block_size)`` headroom: a verify window
        writes up to ``k`` draft rows past the commit frontier, and those
        writes must never find the pool dry mid-round."""
        bs = self.kv_block_size
        return (-(-(prompt_len + max_new) // bs) - start // bs
                + self._spec_headroom)

    def kv_blocks_admittable(self) -> int:
        """Blocks an admission may claim without ever starving a decode:
        free pool blocks, plus trie blocks eviction could reclaim, minus
        the growth already reserved by active slots."""
        return (self._pool.free_blocks
                + self.prefix_cache.evictable_blocks()
                - int(self._slot_reserved.sum()))

    def _horizon_block_range(self, slot: int) -> range:
        """Table indices the slot's next round may write: blocks covering
        ``[pos, pos + write_horizon]`` clipped to ``cache_len``. Horizon
        0 (the legacy per-token path) is exactly the next write's block."""
        bs = self.kv_block_size
        p = int(self._pos[slot])
        if p >= self.cache_len:
            return range(0)   # no further real writes (valid masks them)
        hi = min(p + self._write_horizon, self.cache_len - 1)
        return range(p // bs, hi // bs + 1)

    def slot_needs_block(self, slot: int) -> bool:
        """True when a write inside the slot's next decode round crosses
        into a block it has not allocated yet (a table entry in the
        horizon span still points at scratch). Multi-token rounds
        (speculative window / decode_window) widen the span checked."""
        if not self.paged or not self._active[slot]:
            return False
        return any(self._tables[slot, i] == 0
                   for i in self._horizon_block_range(slot))

    def append_block(self, slot: int) -> bool:
        """Lazily allocate the slot's next block (evicting idle trie
        prefixes if the free list is dry) — the FIRST unallocated entry
        in the next round's write span. Returns False when the pool is
        truly exhausted — the scheduler then preempts the lowest-priority
        request and retries. Carries the ``serving.kv_append`` fault
        cut-point: an injected failure here is contained by preempting
        ONLY this slot (no engine restart)."""
        inject(SERVING_KV_APPEND, slot=slot, pos=int(self._pos[slot]))
        idx = next((i for i in self._horizon_block_range(slot)
                    if self._tables[slot, i] == 0), None)
        if idx is None:
            return True   # span fully allocated — nothing to do
        got = self.prefix_cache.alloc_blocks(1)
        if not got:
            return False
        block = got[0]
        self._tables[slot, idx] = block
        self._slot_blocks[slot].append(block)
        if self._slot_reserved[slot] > 0:
            self._slot_reserved[slot] -= 1
        self._c_appends.inc()
        self._events.emit("kv_append", slot=slot, block=block,
                          pos=int(self._pos[slot]))
        return True

    def slot_block_count(self, slot: int) -> int:
        """Blocks the slot's table currently references (0 in dense
        mode) — the per-request block-count series at retirement."""
        return len(self._slot_blocks[slot]) if self.paged else 0

    def slot_block_shares(self, slot: int) -> float:
        """Refcount-weighted block count the slot holds RIGHT NOW (0.0
        in dense mode): a private block counts 1, a prefix block shared
        by ``r`` live holders counts ``1/r`` — so summing this over all
        holders always reproduces the pool's true occupancy. The cost
        ledger integrates it into per-tenant KV block-seconds."""
        if not self.paged:
            return 0.0
        return sum(1.0 / max(self._pool.refs(b), 1)
                   for b in self._slot_blocks[slot])

    def kv_pool_stats(self) -> tuple[int, int]:
        """(blocks in use, blocks free) — the scheduler samples these
        into the ``kv_blocks_in_use``/``kv_blocks_free`` gauges."""
        return self._pool.used_blocks, self._pool.free_blocks

    def kv_stats(self) -> dict:
        """Paged-store occupancy/config block for bench records (empty
        dict in dense mode)."""
        if not self.paged:
            return {}
        return {
            "kv_blocks": self.kv_blocks,
            "kv_block_size": self.kv_block_size,
            "kv_quant": self.kv_quant,
            "blocks_in_use": self._pool.used_blocks,
            "blocks_free": self._pool.free_blocks,
            "blocks_reserved": int(self._slot_reserved.sum()),
            "peak_active": self.peak_active,
        }

    def flush_inserts(self) -> None:
        """Run the deferred trie inserts (one compiled copy per prompt
        with new full blocks). Deferral keeps the insert copies off the
        TTFT-critical admission path; the scheduler flushes at the end of
        every step and :meth:`admit_batch` flushes defensively before
        picking slots, so a donor's rows are always copied out before its
        slot can be reused by a later tenant."""
        if self.paged:
            return   # paged inserts are zero-copy, done at admission
        pending, self._pending_inserts = self._pending_inserts, []
        for prompt, slot in pending:
            self._insert_prefix(prompt, slot)

    def _insert_prefix(self, prompt: np.ndarray, slot: int) -> None:
        """Cache a freshly-prefilled prompt's full blocks (best effort:
        never fails the admitted request; a store-corrupting failure
        resets the prefix cache to a consistent empty state)."""
        if self.prefix_cache.missing_blocks(prompt) < self._min_insert:
            return
        plan = self.prefix_cache.plan_insert(prompt)
        if plan is None:
            return
        try:
            inject(SERVING_PREFIX_COPY, op="insert", slot=slot,
                   blocks=len(plan.block_ids))
            ids = np.zeros((self._n_prog_blocks,), np.int32)
            ids[: len(plan.block_ids)] = plan.block_ids
            rows = np.zeros((self._n_prog_blocks,), np.int32)
            rows[: len(plan.row_starts)] = plan.row_starts
            with self._watched("serving prefix insert"), \
                    annotate("chainermn.serving_prefix_copy"):
                self._store = self._insert_fn(
                    self._store, self.caches, jnp.int32(slot),
                    jnp.asarray(ids), jnp.asarray(rows),
                    jnp.int32(len(plan.block_ids)))
            self.prefix_cache.commit_insert(plan)
            self._guard.check()
        except Exception as e:  # noqa: BLE001 — insertion is best-effort
            self.prefix_cache.abort_insert(plan)
            if not self._state_ok():
                self._reset_prefix()
            self._events.emit("prefix_insert_error",
                              error=type(e).__name__, detail=str(e)[:200])

    def _state_ok(self) -> bool:
        """True when the donated device buffers are still alive (an
        exception fired BEFORE the device call consumed them) — the
        scheduler's containment test: intact state means only the group
        being admitted failed, everything decoding is untouched."""
        try:
            leaves = jax.tree_util.tree_leaves(
                self._store if self.paged else self.caches)
            if self.prefix_cache is not None and not self.paged:
                leaves += jax.tree_util.tree_leaves(self._store)
            return not any(leaf.is_deleted() for leaf in leaves)
        except Exception:  # noqa: BLE001 — can't tell: assume the worst
            return False

    def _reset_prefix(self) -> None:
        """Fresh (empty) prefix store + cleared trie, together — a trie
        naming blocks of a dead store would hand out KV that no longer
        exists (same shapes/shardings: nothing recompiles)."""
        if self.prefix_cache is None:
            return
        if self.model.tensor_axis is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            axis = self.model.tensor_axis
            shard = NamedSharding(self._comm.mesh, P(None, None, axis))
            self._store = jax.device_put(self._init_store(), shard)
        else:
            self._store = self._init_store()
        self.prefix_cache.clear()

    def decode_step(self, ctx: Optional[dict] = None) -> dict[int, int]:
        """Advance every active slot one token (ONE compiled call for the
        whole pool); returns ``{slot: token}`` for the active slots. No-op
        ({}) when nothing is active. ``ctx`` (request/trace ids from the
        scheduler) labels the watchdog window."""
        if not self._active.any():
            return {}
        # the fetch (np.asarray) is inside the watchdog window on purpose:
        # a wedged collective hangs exactly there, and that is the hang
        # the serving watchdog exists to turn into a loud abort
        with self._watched("serving decode_step", **(ctx or {})), \
                annotate("chainermn.serving_decode"):
            inject(SERVING_DECODE, active=int(self._active.sum()))
            if self.paged:
                self._store, nxt, self._keys = self._decode_fn(
                    self.params, self._store, jnp.asarray(self._tables),
                    jnp.asarray(self._token), jnp.asarray(self._pos),
                    jnp.asarray(self._active), self._keys)
            else:
                self.caches, nxt, self._keys = self._decode_fn(
                    self.params, self.caches, jnp.asarray(self._token),
                    jnp.asarray(self._pos), jnp.asarray(self._active),
                    self._keys)
            nxt = device_fetch(nxt)
        self._c_decode_steps.inc()
        self._events.emit("decode_step", active=int(self._active.sum()))
        self._guard.check()
        out = {}
        for slot in np.flatnonzero(self._active):
            slot = int(slot)
            tok = int(nxt[slot])
            self._token[slot] = tok
            self._pos[slot] += 1
            out[slot] = tok
        return out

    def decode_steps(self, ctx: Optional[dict] = None
                     ) -> dict[int, list[int]]:
        """Advance every active slot ``decode_window`` tokens in ONE
        device dispatch (the fori_loop program — PERF.md "Dispatch
        amortization"); returns ``{slot: [tokens...]}`` in generation
        order. The token stream is identical to ``decode_window`` calls
        of :meth:`decode_step` (same per-slot key splits); the scheduler
        retires mid-window and discards the tail past EOS/budget."""
        if self.decode_window < 2:
            raise RuntimeError(
                "decode_steps needs ServingEngine(decode_window=n>1)")
        if not self._active.any():
            return {}
        n = self.decode_window
        with self._watched("serving decode_steps", **(ctx or {})), \
                annotate("chainermn.serving_decode"):
            inject(SERVING_DECODE, active=int(self._active.sum()), window=n)
            if self.paged:
                self._store, out, self._keys = self._window_fn(
                    self.params, self._store, jnp.asarray(self._tables),
                    jnp.asarray(self._token), jnp.asarray(self._pos),
                    jnp.asarray(self._active), self._keys)
            else:
                self.caches, out, self._keys = self._window_fn(
                    self.params, self.caches, jnp.asarray(self._token),
                    jnp.asarray(self._pos), jnp.asarray(self._active),
                    self._keys)
            out = device_fetch(out)
        self._c_decode_steps.inc()
        self._events.emit("decode_step", active=int(self._active.sum()),
                          window=n)
        self._guard.check()
        res = {}
        for slot in np.flatnonzero(self._active):
            slot = int(slot)
            toks = [int(t) for t in out[slot]]
            self._token[slot] = toks[-1]
            self._pos[slot] += n
            res[slot] = toks
        return res

    def spec_decode_step(self, ctx: Optional[dict] = None
                         ) -> dict[int, list[int]]:
        """One speculative round for every active slot: draft ``k``
        tokens per slot (host-side drafter), verify the ``k+1``-token
        window in ONE target dispatch, and commit each slot's longest
        matching draft prefix plus the correction token (1..k+1 tokens —
        exactly the greedy stream, by the module's induction argument).
        Returns ``{slot: [tokens...]}``; blocks appended for rejected
        rows are rolled back so a mispredicted window never holds pool
        capacity."""
        if self._spec is None:
            raise RuntimeError(
                "spec_decode_step needs ServingEngine(speculative=...)")
        if not self._active.any():
            return {}
        k = self._spec.k
        drafts = self._drafter.propose(k)          # [n_slots, k] host int32
        tokens = np.concatenate([self._token[:, None], drafts], axis=1)
        # rows past valid land in the scratch block: a slot nearing
        # cache_len must not let the clamped table lookup hit a live row
        valid = np.where(self._active,
                         np.clip(self.cache_len - self._pos, 0, k + 1),
                         0).astype(np.int32)
        with self._watched("serving spec_verify", **(ctx or {})), \
                annotate("chainermn.serving_spec_verify"):
            inject(SERVING_SPEC_VERIFY, active=int(self._active.sum()), k=k)
            self._store, g = self._spec_fn(
                self.params, self._store, jnp.asarray(self._tables),
                jnp.asarray(tokens), jnp.asarray(self._pos),
                jnp.asarray(valid), jnp.asarray(self._active))
            g = device_fetch(g)
        self._c_decode_steps.inc()
        self._events.emit("decode_step", active=int(self._active.sum()),
                          window=k + 1)
        self._guard.check()
        res = {}
        proposed = accepted = 0
        lengths = []
        spec_slots = {}
        for slot in np.flatnonzero(self._active):
            slot = int(slot)
            kd = min(k, int(valid[slot]) - 1)   # drafts that fit the slot
            a = 0
            while a < kd and int(drafts[slot, a]) == int(g[slot, a]):
                a += 1
            toks = [int(t) for t in drafts[slot, :a]] + [int(g[slot, a])]
            self._token[slot] = toks[-1]
            self._pos[slot] += len(toks)
            self._drafter.on_commit(slot, toks)
            self._rollback_spec_blocks(slot)
            proposed += kd
            accepted += a
            lengths.append(a)
            spec_slots[slot] = (kd, a)
            res[slot] = toks
        self._spec_proposed_total += proposed
        self._spec_accepted_total += accepted
        self._last_spec_window = (proposed, accepted, lengths)
        self._last_spec_slots = spec_slots
        return res

    def _rollback_spec_blocks(self, slot: int) -> None:
        """Free blocks the verify window appended for rows that got
        rejected: keep the block the slot's NEXT write lands in, free
        every allocated entry strictly beyond it (back into the slot's
        reserved headroom, keeping ``reserved = worst-case remaining −
        held``). Shared prefix blocks are out of reach by construction —
        they cover only rows ``< len(prompt) <= pos``."""
        keep = min(int(self._pos[slot]) // self.kv_block_size + 1,
                   self._n_max)
        freed = 0
        for idx in range(keep, self._n_max):
            block = int(self._tables[slot, idx])
            if block == 0:
                continue
            self._pool.decref(block)
            self._slot_blocks[slot].remove(block)
            self._tables[slot, idx] = 0
            self._slot_reserved[slot] += 1
            freed += 1
        if freed:
            self._events.emit("spec_rollback", slot=slot, blocks=freed,
                              pos=int(self._pos[slot]))

    def decode_round(self, ctx: Optional[dict] = None
                     ) -> dict[int, list[int]]:
        """One decode dispatch under whatever mode the engine was built
        with — the scheduler's single entry point. Speculative engines
        verify a draft window, ``decode_window`` engines run the
        fori_loop program, and the legacy engine wraps its single token
        in a one-element list."""
        if self._spec is not None:
            return self.spec_decode_step(ctx=ctx)
        if self.decode_window > 1:
            return self.decode_steps(ctx=ctx)
        return {slot: [tok]
                for slot, tok in self.decode_step(ctx=ctx).items()}

    @property
    def spec_enabled(self) -> bool:
        return self._spec is not None

    @property
    def last_spec_slots(self) -> dict:
        """``{slot: (kd, a)}`` of the last verify round (drafts that fit,
        drafts accepted) — the per-slot attribution the cost ledger
        splits accepted-vs-wasted verify work with. Unlike
        :meth:`pop_spec_window` this is NOT cleared on read."""
        return self._last_spec_slots

    def pop_spec_window(self) -> Optional[tuple]:
        """``(proposed, accepted, accept_lengths)`` of the last verify
        round, cleared on read — the scheduler drains it into
        :class:`~chainermn_tpu.serving.metrics.ServingMetrics` right
        after delivering the round's tokens."""
        win, self._last_spec_window = self._last_spec_window, None
        return win

    def spec_stats(self) -> dict:
        """Cumulative speculative counters for the bench record (empty
        dict when speculation is off)."""
        if self._spec is None:
            return {}
        prop = self._spec_proposed_total
        acc = self._spec_accepted_total
        return {
            "drafter": self._spec.drafter,
            "spec_k": self._spec.k,
            "spec_tokens_proposed": prop,
            "spec_tokens_accepted": acc,
            "accept_rate": (acc / prop) if prop else 0.0,
        }

    def slot_tokens_used(self, slot: int) -> int:
        """Current sequence depth of a slot (prompt + generated so far)."""
        return int(self._pos[slot]) + 1 if self._active[slot] else 0

    def release(self, slot: int) -> None:
        """Retire a slot (EOS / length / cancellation). The cache is NOT
        zeroed: the causal position mask makes stale rows unreachable to
        the next tenant (module docstring — pinned by the slot-reuse
        parity test)."""
        if slot in self.free_slots:
            return
        if self.paged:
            # give the slot's block references back: exclusively-owned
            # blocks free immediately, trie-shared ones stay resident for
            # the next hit (the store, not the slot, owns cached prefixes)
            for block in self._slot_blocks[slot]:
                self._pool.decref(block)
            self._slot_blocks[slot] = []
            self._slot_reserved[slot] = 0
            self._tables[slot, :] = 0
            # a half-prefilled chunked slot releases the same way: its
            # staged ids ARE _slot_blocks, so cancel/preempt/deadline
            # mid-chunk leaks nothing (replay reproduces the tokens from
            # the same prompt + rng)
            self._chunking.pop(slot, None)
        if self._drafter is not None:
            self._drafter.on_release(slot)
        self._active[slot] = False
        self.free_slots.add(slot)

    def restart(self) -> None:
        """Warm restart after an engine-side failure: fresh KV caches,
        cleared host slot mirrors, AND a fresh prefix store + emptied trie
        — all rebuilt together, with the SAME compiled programs (the new
        arrays have identical shapes/shardings, so nothing recompiles —
        pinned by the restart tests). The prefix index must reset with the
        store: a warm restart keeping a stale trie would "hit" on KV
        blocks that no longer exist and serve a new request another
        prompt's attention state. Needed because a failed call may have
        consumed the donated cache buffers; params are never donated and
        survive. The scheduler drives this from its exception boundary;
        every restart is a counted, event-logged recovery."""
        if self.model.tensor_axis is not None:
            self._init_tp_caches(self._comm)
        elif self.paged:
            self._store = self._init_paged_store()
        else:
            self.caches = init_kv_caches(self.model, self.n_slots,
                                         self.cache_len)
            if self.prefix_cache is not None:
                self._store = self._init_store()
        if self.prefix_cache is not None:
            self.prefix_cache.clear()
        if self.paged:
            # trie dropped above; now drop the slot tables' references and
            # reset the pool wholesale — a stale table pinning blocks of a
            # dead store would leak capacity forever (and a stale ENTRY
            # would read KV that no longer exists)
            self._pool.reset()
            self._tables[:] = 0
            self._slot_blocks = [[] for _ in range(self.n_slots)]
            self._slot_reserved[:] = 0
            self._chunking.clear()
        self._pending_inserts = []
        self._token[:] = 0
        self._pos[:] = 0
        self._active[:] = False
        self._keys = self._fresh_keys()
        self.free_slots = set(range(self.n_slots))
        if self._drafter is not None:
            self._drafter.reset()
        self._c_restarts.inc()
        self._events.emit("engine_restart")

    # ------------------------------------------------------------------ #
    # versioned weights (the deploy layer's swap surface)                 #
    # ------------------------------------------------------------------ #

    def swap_params(self, new_params, *, version: Optional[int] = None) -> int:
        """Commit a new param pytree in place; returns the new version.

        The caller (normally :class:`~chainermn_tpu.deploy.publish
        .WeightPublisher`, via the scheduler's swap fence) must hand over
        a tree with the EXACT structure, per-leaf shape/dtype, and
        shardings of the current params — sharding is part of the jit
        cache key, so an identically-committed tree makes the swap a
        pure pointer exchange: the compiled prefill/decode programs next
        run on the new weights with ZERO recompiles. Validation happens
        BEFORE anything is assigned, so a rejected swap leaves the
        engine bit-for-bit on its prior weights (never a half-written
        engine). Params are never donated (see :meth:`restart`), so the
        old tree stays alive for any caller-held reference.
        """
        old_leaves, old_def = jax.tree_util.tree_flatten(self.params)
        new_leaves, new_def = jax.tree_util.tree_flatten(new_params)
        if new_def != old_def:
            raise EngineStateError(
                f"swap_params: tree structure mismatch — engine has "
                f"{old_def}, got {new_def}")
        for i, (old, new) in enumerate(zip(old_leaves, new_leaves)):
            if getattr(new, "shape", None) != old.shape or \
                    getattr(new, "dtype", None) != old.dtype:
                raise EngineStateError(
                    f"swap_params: leaf {i} is "
                    f"{getattr(new, 'shape', None)}/"
                    f"{getattr(new, 'dtype', None)}, engine compiled "
                    f"against {old.shape}/{old.dtype}")
            old_sh = getattr(old, "sharding", None)
            new_sh = getattr(new, "sharding", None)
            if old_sh is not None and (
                    new_sh is None
                    or not new_sh.is_equivalent_to(old_sh, old.ndim)):
                raise EngineStateError(
                    f"swap_params: leaf {i} sharding {new_sh} is not "
                    f"equivalent to the warmup-compiled {old_sh} — "
                    "device_put against engine.params shardings first "
                    "(jit cache key discipline)")
        self.params = new_params
        self.weight_version = (int(version) if version is not None
                               else self.weight_version + 1)
        self._g_weight_version.set(self.weight_version)
        self._events.emit("weight_swap", version=self.weight_version)
        return self.weight_version

    # ------------------------------------------------------------------ #
    # observability                                                       #
    # ------------------------------------------------------------------ #

    def compile_counts(self) -> dict[str, int]:
        """Executable counts of the prefill family (summed over buckets)
        and the decode program — the zero-recompile invariant is
        ``{'prefill': len(buckets), 'decode': 1}`` after warmup, asserted
        by tests and reported by the serving benchmark."""
        return {
            "prefill": sum(int(fn._cache_size())
                           for fn in self._prefill_fns.values()),
            "decode": int(self._decode_fn._cache_size()),
        }

    def compile_counts_detailed(self) -> dict[str, int]:
        """Per-program executable counts (every bucket + decode + the
        prefix-copy pair) — each must be exactly 1 after :meth:`warmup`."""
        out = {f"prefill_{b}": int(fn._cache_size())
               for b, fn in self._prefill_fns.items()}
        out["decode"] = int(self._decode_fn._cache_size())
        if self.migration_supported:
            for w in self._mig_buckets:
                out[f"kv_gather_{w}"] = int(
                    self._kv_gather_fns[w]._cache_size())
                out[f"kv_scatter_{w}"] = int(
                    self._kv_scatter_fns[w]._cache_size())
        if self.prefix_cache is not None and not self.paged:
            out["prefix_insert"] = int(self._insert_fn._cache_size())
        if self._spec is not None:
            out["spec_verify"] = int(self._spec_fn._cache_size())
            out.update(self._drafter.compile_counts())
        if self.decode_window > 1:
            out["decode_window"] = int(self._window_fn._cache_size())
        return out

    @property
    def recompiles(self) -> dict[str, int]:
        """Recompiles observed past each program's warmup compile (the
        guard's live count; empty == the invariant holds)."""
        return self._guard.recompiles

    def prefix_stats(self) -> dict:
        """The prefix cache's hit/eviction/occupancy numbers (empty dict
        when disabled) — embedded in the serving bench record."""
        return self.prefix_cache.stats() if self.prefix_cache else {}

    def occupancy(self) -> dict:
        """Cheap host-side occupancy snapshot — the fleet router's
        occupancy-aware-admission input (no device call, no locks beyond
        numpy reads): slot fill, free-KV fraction (paged engines count
        blocks; dense engines count free slots), and whether the prefix
        trie is live on this engine."""
        active = self.active_slots
        if self.paged:
            free = self._pool.free_blocks
            kv_free_frac = free / max(self._pool.capacity, 1)
        else:
            kv_free_frac = len(self.free_slots) / max(self.n_slots, 1)
        return {
            "n_slots": self.n_slots,
            "active_slots": active,
            "free_slots": len(self.free_slots),
            "kv_free_frac": round(float(kv_free_frac), 4),
            "prefix_enabled": self.prefix_enabled,
            "paged": self.paged,
            "warm": self._warm,
            "weight_version": self.weight_version,
        }


__all__ = ["AdmitPlan", "ChunkedPrefill", "EngineStateError",
           "ServingEngine"]
